"""Measured, hierarchical link-cost model.

Everything upstream of this module used to assume one flat ICI: the
dry-runner priced every wire byte at a hardcoded ``_SEC_PER_ICI_BYTE``,
``grad_sync`` sized buckets from one global ``grad_bucket_mb``, and
nothing distinguished a byte crossing slice-local ICI from a byte
crossing the data-center network between slices. This module replaces
those constants with ONE measured subsystem:

- **``LinkModel``** — per-link bandwidth (GB/s) + latency for the three
  link classes a multi-slice TPU job crosses: ``ici`` (intra-slice
  chip fabric, per mesh axis), ``dcn`` (cross-slice network), and
  ``host`` (D2H/H2D staging). Consumers ask ``sec_per_ici_byte()`` /
  ``sec_per_dcn_byte()`` instead of importing constants.
- **``probe_link_model``** — the startup probe: times a real collective
  per ICI axis, a cross-slice collective over the ``dcn_axes``
  submesh groups, and host transfers. The result is JSON-persisted per
  **device fingerprint** so warm restarts (and elastic resizes back to
  the same hardware) skip the probe entirely; a resize must re-probe
  only when the fingerprint changes (docs/elastic-resize.md).
- **CPU/virtual fallback** — backends with no real interconnect get the
  documented constants (the exact numbers the old hardcoded model
  used), labeled ``source="fallback-cpu"`` and logged once when the
  cost model consumes them (``note_fallback_use``).

Downstream consumers: ``accel/dry_runner._comm_estimate`` (est_step_s
priced from the probed model whenever a cache exists),
``grad_sync`` per-link bucket sizing (``bucket_bytes_for``) and the
two-level sync, the trainer's startup/resize probe, ``bench.py
run_topology_bench``, and the heterogeneous per-slice throughput
weighting (``slice_throughput_weights``) that feeds the elastic data
layer's unequal shard sizing.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import asdict, dataclass, field, replace as dc_replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

# -- documented fallback constants ------------------------------------------
# ICI matches the dry-runner's historical _SEC_PER_ICI_BYTE = 1/9e10
# (v5p-class ~90 GB/s effective per chip); DCN is the per-host
# data-center NIC class (~100 Gbit/s => 12.5 GB/s); host is a PCIe-gen3
# D2H staging link. The *ordering* (ici >= dcn >= host) is the invariant
# the bench gates — a model violating it would invert every scheduling
# decision built on top.
FALLBACK_ICI_GBPS = 90.0
FALLBACK_DCN_GBPS = 12.5
FALLBACK_HOST_GBPS = 8.0
FALLBACK_ICI_LAT_S = 1e-6
FALLBACK_DCN_LAT_S = 50e-6
FALLBACK_HOST_LAT_S = 10e-6

_CACHE_ENV = "DLROVER_TPU_TOPOLOGY_CACHE"


@dataclass(frozen=True)
class LinkModel:
    """Per-link bandwidth/latency of the current device world.

    ``ici_axis_gbps`` carries the per-mesh-axis measurements when the
    probe ran per axis (different ICI axes can ride different numbers
    of physical links); ``ici_gbps`` is the bottleneck (min) of those,
    which is what a conservative cost model should price with.
    """

    ici_gbps: float = FALLBACK_ICI_GBPS
    dcn_gbps: float = FALLBACK_DCN_GBPS
    host_d2h_gbps: float = FALLBACK_HOST_GBPS
    host_h2d_gbps: float = FALLBACK_HOST_GBPS
    ici_lat_s: float = FALLBACK_ICI_LAT_S
    dcn_lat_s: float = FALLBACK_DCN_LAT_S
    host_lat_s: float = FALLBACK_HOST_LAT_S
    ici_axis_gbps: Tuple[Tuple[str, float], ...] = ()
    # "measured" | "fallback-cpu" | "fallback"; consumers log once when
    # pricing from a non-measured model (note_fallback_use)
    source: str = "fallback"
    fingerprint: str = ""
    probed_at: float = 0.0

    # -- pricing ------------------------------------------------------
    def sec_per_ici_byte(self) -> float:
        return 1.0 / max(self.ici_gbps * 1e9, 1.0)

    def sec_per_dcn_byte(self) -> float:
        return 1.0 / max(self.dcn_gbps * 1e9, 1.0)

    def sec_per_host_byte(self, h2d: bool = False) -> float:
        bw = self.host_h2d_gbps if h2d else self.host_d2h_gbps
        return 1.0 / max(bw * 1e9, 1.0)

    def axis_gbps(self, axis: str) -> float:
        for a, bw in self.ici_axis_gbps:
            if a == axis:
                return bw
        return self.ici_gbps

    def sec_per_axis_byte(self, axis: str) -> float:
        """Per-ICI-axis pricing: different mesh axes can ride
        different numbers of physical links, and the probe measures
        each axis with size > 1 (e.g. a dp x fsdp mesh carries both a
        "dp" and an "fsdp" entry). Falls back to the conservative
        bottleneck ``ici_gbps`` for unmeasured axes."""
        return 1.0 / max(self.axis_gbps(axis) * 1e9, 1.0)

    @property
    def ordering_ok(self) -> bool:
        """The sanity invariant: chip fabric >= cross-slice network >=
        host staging link."""
        return (
            self.ici_gbps >= self.dcn_gbps >= min(
                self.host_d2h_gbps, self.host_h2d_gbps
            )
        )

    def describe(self) -> str:
        return (
            f"links[{self.source}]: ici {self.ici_gbps:.1f} GB/s, "
            f"dcn {self.dcn_gbps:.1f} GB/s, host "
            f"{self.host_d2h_gbps:.1f}/{self.host_h2d_gbps:.1f} GB/s "
            f"d2h/h2d (fp {self.fingerprint or '-'})"
        )

    # -- persistence --------------------------------------------------
    def to_json(self) -> str:
        d = asdict(self)
        d["ici_axis_gbps"] = [list(p) for p in self.ici_axis_gbps]
        return json.dumps(d, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "LinkModel":
        d = json.loads(s)
        d["ici_axis_gbps"] = tuple(
            (str(a), float(b)) for a, b in d.get("ici_axis_gbps", [])
        )
        return LinkModel(**d)


def fallback_link_model(
    fingerprint: str = "", source: str = "fallback"
) -> LinkModel:
    return LinkModel(source=source, fingerprint=fingerprint)


# -- device fingerprint / cache ---------------------------------------------


def device_fingerprint(devices=None) -> str:
    """Stable id of the device world a probe is valid for: platform,
    chip kind, device count, process count, and the slice topology.
    A resize that lands on the same fingerprint reuses the cached
    probe; a different one (new chip kind, different slice count)
    invalidates it."""
    import jax

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    kinds = sorted({getattr(d, "device_kind", "?") for d in devices})
    plats = sorted({getattr(d, "platform", "?") for d in devices})
    slices = sorted(
        {getattr(d, "slice_index", None) for d in devices},
        key=lambda s: (-1 if s is None else int(s)),
    )
    procs = len({getattr(d, "process_index", 0) for d in devices})
    raw = "|".join(
        [
            ",".join(plats),
            ",".join(kinds),
            str(len(devices)),
            str(procs),
            ",".join(str(s) for s in slices),
        ]
    )
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def cache_dir(override: Optional[str] = None) -> str:
    return (
        override
        or os.getenv(_CACHE_ENV)
        or os.path.join(
            os.path.expanduser("~"), ".cache", "dlrover_tpu"
        )
    )


def cache_path(fingerprint: str, dir_override: Optional[str] = None) -> str:
    return os.path.join(
        cache_dir(dir_override), f"linkmodel-{fingerprint}.json"
    )


def load_cached(
    fingerprint: str, dir_override: Optional[str] = None
) -> Optional[LinkModel]:
    try:
        with open(cache_path(fingerprint, dir_override)) as f:
            model = LinkModel.from_json(f.read())
    except (OSError, ValueError, TypeError, KeyError):
        return None
    if model.fingerprint != fingerprint:
        return None  # stale file copied across worlds
    return model


def save_cache(
    model: LinkModel, dir_override: Optional[str] = None
) -> Optional[str]:
    """Best-effort persist (atomic rename); a read-only filesystem must
    never take down the probe."""
    path = cache_path(model.fingerprint, dir_override)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(model.to_json())
        # graftlint: disable=durable-rename reason=best-effort probe cache; a torn file fails the json/fingerprint check on load and the next startup just re-probes
        os.replace(tmp, path)
        return path
    except OSError as e:
        logger.warning(f"link-model cache write failed: {e!r}")
        return None


# -- observed rail rates ------------------------------------------------------

# EWMA weight of one NEW realized-rate sample: a changed link settles
# in ~3 stripes without letting a single anomalous transfer (page-cache
# hit, one congested instant) own the price
RAIL_RATE_EWMA_WEIGHT = 0.3
# a transfer smaller than this prices latency, not bandwidth — the
# striper's fold skips rails that moved less
RAIL_RATE_MIN_BYTES = 1 << 20

# observed-rate key ("rail direction") -> the LinkModel field it
# overrides; the same vocabulary rail_link_gbps prices by
_RAIL_RATE_FIELDS = {
    "d2h": "host_d2h_gbps",
    "h2d": "host_h2d_gbps",
    "peer": "dcn_gbps",
}


@dataclass
class ObservedRailRates:
    """Realized per-rail throughput (GB/s), EWMA-folded from finished
    striped transfers and persisted next to the probed ``LinkModel``
    cache under the same device fingerprint. The startup probe measures
    each link once with a synthetic payload; these numbers come from
    the job's actual traffic — ``get_link_model`` overlays them onto
    whatever model it returns, so bucket auto-sizing, stripe shares,
    arbiter pricing and the dry-runner's est_step_s track the link the
    job really has, not the link it had at startup. Keys are rail
    directions (``"d2h"`` | ``"h2d"`` | ``"peer"``)."""

    fingerprint: str = ""
    gbps: Dict[str, float] = field(default_factory=dict)
    samples: Dict[str, int] = field(default_factory=dict)
    updated_at: float = 0.0

    def to_payload(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "gbps": {k: float(v) for k, v in self.gbps.items()},
            "samples": {k: int(v) for k, v in self.samples.items()},
            "updated_at": float(self.updated_at),
        }

    @staticmethod
    def from_payload(d: dict) -> "ObservedRailRates":
        return ObservedRailRates(
            fingerprint=str(d["fingerprint"]),
            gbps={
                str(k): float(v) for k, v in dict(d["gbps"]).items()
            },
            samples={
                str(k): int(v)
                for k, v in dict(d.get("samples", {})).items()
            },
            updated_at=float(d.get("updated_at", 0.0)),
        )


_OBSERVED: Optional[ObservedRailRates] = None
# fingerprints whose disk file this process already looked for — the
# overlay rides every get_link_model() call, which must stay a dict
# lookup, not a stat() per pricing query
_OBS_DISK_CHECKED: set = set()


def rail_rates_path(
    fingerprint: str, dir_override: Optional[str] = None
) -> str:
    return os.path.join(
        cache_dir(dir_override), f"railrates-{fingerprint}.json"
    )


def load_rail_rates(
    fingerprint: Optional[str] = None,
    dir_override: Optional[str] = None,
) -> Optional[ObservedRailRates]:
    if fingerprint is None:
        try:
            fingerprint = device_fingerprint()
        except Exception:  # no backend yet (early import paths)
            return None
    try:
        with open(rail_rates_path(fingerprint, dir_override)) as f:
            rates = ObservedRailRates.from_payload(json.load(f))
    except (OSError, ValueError, TypeError, KeyError):
        return None
    if rates.fingerprint != fingerprint:
        return None  # stale file copied across worlds
    return rates


def save_rail_rates(
    rates: ObservedRailRates, dir_override: Optional[str] = None
) -> Optional[str]:
    """Durable persist (fsync-before-rename: the EWMA is long-lived
    state a crash should not tear). Best-effort all the same — a
    read-only cache dir must never take down the transfer that fed the
    sample; the EWMA just stays process-local."""
    path = rail_rates_path(rates.fingerprint, dir_override)
    try:
        from dlrover_tpu.agent.monitor import atomic_write_json

        atomic_write_json(path, rates.to_payload(), durable=True)
        return path
    except OSError as e:
        logger.warning(f"observed rail-rate cache write failed: {e!r}")
        return None


def set_rail_rates(rates: Optional[ObservedRailRates]) -> None:
    """Install an observed-rates snapshot as the process-current one
    (tests/bench; ``observe_rail_rate`` maintains it in production)."""
    global _OBSERVED
    _OBSERVED = rates


def reset_rail_rates() -> None:
    global _OBSERVED
    _OBSERVED = None
    _OBS_DISK_CHECKED.clear()


def _observed_for(
    fp: str, dir_override: Optional[str] = None
) -> Optional[ObservedRailRates]:
    """The observed-rates snapshot applicable to ``fp``: the in-process
    one when its fingerprint matches (or either side has none), else a
    one-time disk probe per fingerprint."""
    global _OBSERVED
    obs = _OBSERVED
    if obs is not None and (
        not fp or not obs.fingerprint or obs.fingerprint == fp
    ):
        return obs
    if fp and fp not in _OBS_DISK_CHECKED:
        _OBS_DISK_CHECKED.add(fp)
        disk = load_rail_rates(fp, dir_override)
        if disk is not None:
            if _OBSERVED is None:
                _OBSERVED = disk
            return disk
    return None


def get_rail_rates(
    devices=None, dir_override: Optional[str] = None
) -> Optional[ObservedRailRates]:
    """Process-current observed rates for this device world, else the
    disk cache, else None. Never measures — samples arrive only from
    real transfers through ``observe_rail_rate``."""
    try:
        fp = device_fingerprint(devices)
    except Exception:
        fp = ""
    return _observed_for(fp, dir_override)


def observe_rail_rate(
    rail: str,
    gbps: float,
    devices=None,
    dir_override: Optional[str] = None,
) -> Optional[ObservedRailRates]:
    """Fold one realized-throughput sample (GB/s over a finished
    transfer of at least ``RAIL_RATE_MIN_BYTES``) into the per-rail
    EWMA, persist the snapshot, and export the gauge. ``rail`` is a
    direction key from ``_RAIL_RATE_FIELDS``; anything else (a custom
    bench rail with no LinkModel leg) is ignored."""
    global _OBSERVED
    if rail not in _RAIL_RATE_FIELDS or not gbps > 0.0:
        return _OBSERVED
    try:
        fp = device_fingerprint(devices)
    except Exception:
        fp = ""
    obs = _observed_for(fp, dir_override)
    if obs is None:
        obs = ObservedRailRates(fingerprint=fp)
    prev = obs.gbps.get(rail)
    if prev is None:
        new = float(gbps)
    else:
        w = RAIL_RATE_EWMA_WEIGHT
        new = (1.0 - w) * prev + w * float(gbps)
    obs.gbps[rail] = new
    obs.samples[rail] = obs.samples.get(rail, 0) + 1
    obs.updated_at = time.time()
    _OBSERVED = obs
    save_rail_rates(obs, dir_override)
    export_rail_rate_metrics(obs)
    return obs


def apply_observed_rates(
    model: LinkModel, rates: ObservedRailRates
) -> LinkModel:
    """``model`` with every observed rail rate overriding the probed
    (or fallback) figure for its leg. Latency and ICI stay as probed —
    the striper only ever realizes host/DCN legs."""
    kw = {}
    for rail, gbps in rates.gbps.items():
        fld = _RAIL_RATE_FIELDS.get(rail)
        if fld is not None and gbps > 0.0:
            kw[fld] = float(gbps)
    return dc_replace(model, **kw) if kw else model


def export_rail_rate_metrics(
    rates: ObservedRailRates, registry=None
) -> None:
    """``dlrover_link_observed_gbps{rail}`` gauges
    (docs/observability.md)."""
    if registry is None:
        from dlrover_tpu.obs.metrics import default_registry

        registry = default_registry()
    g = registry.gauge(
        "dlrover_link_observed_gbps",
        "EWMA realized rail throughput from striped transfers "
        "(parallel/topology.py)",
        labelnames=("rail",),
    )
    for rail, gbps in rates.gbps.items():
        g.labels(rail).set(float(gbps))


# -- measurement -------------------------------------------------------------


def _time_allreduce(
    mesh, axis: str, nbytes: int, groups=None, iters: int = 3
) -> Tuple[float, float]:
    """(bandwidth GB/s, latency s) of an all-reduce over ``axis``
    (optionally restricted to ``groups`` of axis indices). Bandwidth
    from the ring cost 2(n-1)/n x payload per device; latency from a
    4-byte collective."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.common.jax_compat import shard_map

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    group_n = len(groups[0]) if groups else n
    if group_n <= 1:
        return 0.0, 0.0
    elems = max(group_n, (nbytes // 4 // group_n) * group_n)

    def _run(size):
        def body(v):
            return jax.lax.psum(v, axis, axis_index_groups=groups)

        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            )
        )
        x = jnp.zeros((size,), jnp.float32)
        jax.block_until_ready(fn(x))  # compile + warmup
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(x))
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    lat = _run(1)
    t = _run(elems)
    ring_bytes = 2.0 * (group_n - 1) / group_n * elems * 4
    bw = ring_bytes / max(t - lat, 1e-9)
    return bw / 1e9, max(lat, 0.0)


def _time_host_link(nbytes: int, iters: int = 3) -> Tuple[float, float]:
    """(d2h GB/s, h2d GB/s). Fresh device arrays per read — jax.Array
    caches its host copy after the first np.asarray."""
    import jax
    import jax.numpy as jnp

    elems = max(1, nbytes // 4)
    make = jax.jit(lambda s: jnp.full((elems,), s, jnp.float32))
    jax.block_until_ready(make(0.0))
    np.asarray(make(1.0))  # path warmup
    d2h = []
    for i in range(iters):
        x = make(float(i + 2))
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        np.asarray(x)
        d2h.append(time.perf_counter() - t0)
    host = np.zeros((elems,), np.float32)
    jax.block_until_ready(jax.device_put(host))  # warmup
    h2d = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(host))
        h2d.append(time.perf_counter() - t0)
    b = elems * 4
    return (
        b / max(float(np.median(d2h)), 1e-9) / 1e9,
        b / max(float(np.median(h2d)), 1e-9) / 1e9,
    )


def probe_link_model(
    mesh_config=None,
    devices=None,
    force: bool = False,
    cache_dir: Optional[str] = None,
    measure_on_cpu: bool = False,
    probe_mb: int = 4,
) -> LinkModel:
    """The startup probe. Returns the cached model when one exists for
    this device fingerprint (warm restarts and same-hardware resizes
    skip the measurement entirely, ``force=True`` overrides); measures
    per-ICI-axis, cross-slice DCN and host-link timings otherwise.
    CPU/virtual backends fall back to the documented constants unless
    ``measure_on_cpu`` (tests exercise the measurement machinery with
    it; a memcpy "bandwidth" is meaningless as a real model)."""
    import jax

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    fp = device_fingerprint(devices)
    if not force:
        cached = load_cached(fp, cache_dir)
        if cached is not None:
            set_link_model(cached)
            return cached
    platform = getattr(devices[0], "platform", "cpu")
    if platform == "cpu" and not measure_on_cpu:
        model = fallback_link_model(fp, source="fallback-cpu")
        save_cache(model, cache_dir)
        set_link_model(model)
        logger.info(model.describe())
        return model

    from dlrover_tpu.parallel.mesh import AXIS_ORDER, MeshConfig, build_mesh

    if mesh_config is None:
        mesh_config = MeshConfig(dp=len(devices))
    mesh = build_mesh(mesh_config, devices=devices)
    nbytes = probe_mb << 20
    axis_bws: List[Tuple[str, float]] = []
    ici_lat = FALLBACK_ICI_LAT_S
    dcn_bw, dcn_lat = 0.0, 0.0
    slices = mesh_config.dp_slices()
    for a in AXIS_ORDER:
        size = getattr(mesh_config, a)
        if size <= 1:
            continue
        if a in mesh_config.dcn_axes and not (a == "dp" and slices > 1):
            # whole axis crosses DCN
            bw, lat = _time_allreduce(mesh, a, nbytes)
            if bw > 0:
                dcn_bw, dcn_lat = bw, lat
            continue
        if a == "dp" and slices > 1:
            # the EXACT groups the two-level sync will use — any drift
            # between what the probe times and what sync_grads runs
            # would price the wrong link
            from dlrover_tpu.parallel.grad_sync import _slice_groups

            ici_groups, dcn_groups = _slice_groups(size, slices)
            bw, lat = _time_allreduce(mesh, a, nbytes, groups=ici_groups)
            if bw > 0:
                axis_bws.append((a, bw))
                ici_lat = lat
            bw, lat = _time_allreduce(mesh, a, nbytes, groups=dcn_groups)
            if bw > 0:
                dcn_bw, dcn_lat = bw, lat
            continue
        bw, lat = _time_allreduce(mesh, a, nbytes)
        if bw > 0:
            axis_bws.append((a, bw))
            ici_lat = lat
    d2h, h2d = _time_host_link(nbytes)
    ici_bw = min((bw for _, bw in axis_bws), default=FALLBACK_ICI_GBPS)
    model = LinkModel(
        ici_gbps=ici_bw,
        dcn_gbps=dcn_bw or FALLBACK_DCN_GBPS,
        host_d2h_gbps=d2h,
        host_h2d_gbps=h2d,
        ici_lat_s=ici_lat,
        dcn_lat_s=dcn_lat or FALLBACK_DCN_LAT_S,
        host_lat_s=FALLBACK_HOST_LAT_S,
        ici_axis_gbps=tuple(axis_bws),
        source="measured",
        fingerprint=fp,
        probed_at=time.time(),
    )
    save_cache(model, cache_dir)
    set_link_model(model)
    logger.info(model.describe())
    return model


# -- process-level accessor ---------------------------------------------------

_MEMO: Dict[str, LinkModel] = {}
# the most recently probed/installed model in THIS process. Consumers
# that cannot know the exact device subset in play (the dry-runner and
# bucket sizer call get_link_model() with no devices, which fingerprints
# ALL of jax.devices()) would otherwise miss a model the trainer probed
# for its mesh's subset — e.g. right after an elastic resize — and
# silently price from the fallback constants.
_CURRENT: Optional[LinkModel] = None
_FALLBACK_WARNED = False


def get_link_model(
    devices=None, cache_dir: Optional[str] = None
) -> LinkModel:
    """The cost model's view, in preference order: the in-process
    model for this exact device fingerprint, else whatever this
    process most recently probed/installed (a subset probe from a
    resize beats stale disk files from other runs), else a persisted
    probe cache for the fingerprint, else the documented fallback
    constants. NEVER probes — probing is an explicit startup/bench
    action (``probe_link_model``); estimation paths must stay cheap
    and deterministic.

    Observed rail rates (``observe_rail_rate`` — realized throughput
    from the job's own striped transfers) overlay the result AFTER the
    memo lookup, so a sample folded mid-run reprices every consumer on
    its next query without invalidating the cached probe."""
    try:
        fp = device_fingerprint(devices)
    except Exception:  # no backend yet (early import paths)
        fp = ""
    if fp in _MEMO:
        model = _MEMO[fp]
    elif _CURRENT is not None:
        model = _CURRENT
    else:
        model = load_cached(fp, cache_dir) if fp else None
        if model is None:
            model = fallback_link_model(fp, source="fallback")
        _MEMO[fp] = model
    obs = _observed_for(fp, cache_dir)
    if obs is not None and obs.gbps:
        model = apply_observed_rates(model, obs)
    return model


def set_link_model(model: LinkModel, devices=None) -> None:
    """Install a model as the process-current one (tests/bench, and
    any consumer asking without an exact fingerprint match)."""
    global _CURRENT
    fp = model.fingerprint or device_fingerprint(devices)
    _MEMO[fp] = model
    _CURRENT = model


def reset_link_model() -> None:
    global _FALLBACK_WARNED, _CURRENT
    _MEMO.clear()
    _CURRENT = None
    _FALLBACK_WARNED = False
    # observed rail rates overlay whatever get_link_model returns, so a
    # full model reset (tests/bench teardown) must drop them too or the
    # "pristine" fallback would come back pre-overlaid
    reset_rail_rates()


def note_fallback_use(model: LinkModel) -> None:
    """Log ONCE per process when a consumer prices wire time from a
    non-measured model — the old hardcoded constants are now an
    explicit, visible fallback instead of a silent assumption."""
    global _FALLBACK_WARNED
    if model.source == "measured" or _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED = True
    logger.info(
        f"comm cost model: no measured link probe for this backend — "
        f"pricing from documented constants ({model.describe()}); run "
        f"parallel.topology.probe_link_model on real hardware to "
        f"replace them"
    )


def rail_link_gbps(model: LinkModel, direction: str) -> float:
    """Bandwidth of a transfer-arbiter rail under this model, by the
    rail's direction: ``d2h``/``h2d`` price the host legs, ``peer``
    prices the DCN path the batched RPC legs traverse. The multi-rail
    striper plans completion-time-balanced chunk shares from these
    numbers, so a measured model directly shapes the stripe."""
    if direction == "h2d":
        return model.host_h2d_gbps
    if direction == "peer":
        return model.dcn_gbps
    return model.host_d2h_gbps


def price_host_transfer(
    nbytes: int, h2d: bool = False, model: Optional[LinkModel] = None
) -> float:
    """Seconds a host↔device transfer of ``nbytes`` costs on the PR-6
    host leg (bandwidth + per-transfer latency). The embedding row
    pipeline prices its fault-in (H2D) and spill/scatter-back (D2H)
    traffic through here so the dry-runner's est_step_s and the Brain's
    job telemetry see the same host-link physics the collectives and
    checkpoint staging are priced with — not an invented constant."""
    if nbytes <= 0:
        return 0.0
    m = model if model is not None else get_link_model()
    note_fallback_use(m)
    return m.host_lat_s + nbytes * m.sec_per_host_byte(h2d=h2d)


def export_link_metrics(model: LinkModel, registry=None) -> None:
    """Per-link gauges into the metrics registry
    (docs/observability.md): ``dlrover_link_{ici,dcn,host_d2h,
    host_h2d}_gbps`` + ``dlrover_link_model_measured`` (1 when the
    numbers come from a real probe)."""
    if registry is None:
        from dlrover_tpu.obs.metrics import default_registry

        registry = default_registry()
    for name, value in (
        ("dlrover_link_ici_gbps", model.ici_gbps),
        ("dlrover_link_dcn_gbps", model.dcn_gbps),
        ("dlrover_link_host_d2h_gbps", model.host_d2h_gbps),
        ("dlrover_link_host_h2d_gbps", model.host_h2d_gbps),
        (
            "dlrover_link_model_measured",
            1.0 if model.source == "measured" else 0.0,
        ),
    ):
        registry.gauge(
            name, "link cost model (parallel/topology.py)"
        ).set(float(value))


# -- derived knobs ------------------------------------------------------------

# target wire time per sync bucket: small enough that XLA's scheduler
# has multiple independent collectives to interleave with backward
# compute, large enough that per-collective latency stays amortized
BUCKET_TARGET_COMM_MS = 2.0
_BUCKET_MIN_BYTES = 1 << 20
_BUCKET_MAX_BYTES = 64 << 20


def bucket_bytes_for(
    model: LinkModel,
    link: str = "ici",
    target_ms: float = BUCKET_TARGET_COMM_MS,
) -> int:
    """Per-link bucket size: the byte count whose wire time on ``link``
    is ~``target_ms`` (clamped to [1, 64] MiB). A DCN-bound two-level
    sync gets smaller buckets than a pure-ICI one because the same
    2 ms window holds fewer cross-slice bytes."""
    bw = {
        "ici": model.ici_gbps,
        "dcn": model.dcn_gbps,
        "host": model.host_d2h_gbps,
    }.get(link)
    if bw is None:
        raise ValueError(f"unknown link {link!r} (ici|dcn|host)")
    b = int(bw * 1e9 * target_ms / 1e3)
    return max(_BUCKET_MIN_BYTES, min(_BUCKET_MAX_BYTES, b))


def alltoall_time_s(
    nbytes: int,
    n: int,
    model: Optional[LinkModel] = None,
    dcn: bool = False,
) -> float:
    """Seconds of one all-to-all over an ``n``-device group where each
    device holds ``nbytes`` of payload: ``(n-1)/n`` of it leaves the
    device, at the ICI rate (or DCN when the group crosses slices) plus
    one collective's latency. The MoE dispatch/combine legs
    (``parallel/moe.py``) are priced through here so the dry-runner's
    est_step_s sees the same link physics the gradient collectives are
    priced with."""
    if n <= 1 or nbytes <= 0:
        return 0.0
    m = model if model is not None else get_link_model()
    note_fallback_use(m)
    rate = m.sec_per_dcn_byte() if dcn else m.sec_per_ici_byte()
    lat = m.dcn_lat_s if dcn else m.ici_lat_s
    return (n - 1) / n * nbytes * rate + n * lat


# -- heterogeneous per-slice throughput weighting -----------------------------


def slice_throughput_weights(
    step_times_s: Sequence[float],
) -> List[float]:
    """Normalized data-shard weights from per-slice step times: a slice
    twice as fast gets twice the data (arXiv 2602.18007's unequal
    shards for unequal slices). Non-positive/missing entries get the
    mean throughput so one bad measurement cannot zero out a slice."""
    times = [float(t) for t in step_times_s]
    if not times:
        return []
    thr = [1.0 / t if t > 0 else 0.0 for t in times]
    positive = [t for t in thr if t > 0]
    if not positive:
        return [1.0 / len(times)] * len(times)
    mean_thr = sum(positive) / len(positive)
    thr = [t if t > 0 else mean_thr for t in thr]
    total = sum(thr)
    return [t / total for t in thr]
