"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Parity: the reference's PiPPy-based pipe compiler
(atorch/atorch/modules/distributed_modules/compilers/pipe_compiler/
distributed_pippy_compiler.py:541, PipelineStage.py:989) traces the model
into per-stage graphs, places them on ranks and streams microbatches over
torch RPC, with DeepSpeed 3D as a second backend
(ds_3d_parallel_optimization.py). The TPU-native design needs none of that
machinery:

- per-stage layer parameters are **stacked on a leading axis sharded over
  ``pp``** (stage s owns rows [s]), so placement is a sharding, not a
  graph partitioner;
- the microbatch rotation runs inside ``jax.shard_map`` that is *manual
  over pp only* — dp/fsdp/tp stay GSPMD-auto inside the body, so ZeRO-3
  and megatron-TP sharding compose with PP without stage-local rewrites;
- activations hop stages via ``lax.ppermute`` over ICI;
- autodiff through the scan-of-ppermute yields the backward pipeline
  schedule for free (ppermute transposes to the reverse rotation).

Schedules:

- **GPipe** (``schedule="gpipe"``): M microbatch forwards scanned over the
  stage ring, reverse-mode AD gives the backward rotation; bubble fraction
  (P-1)/(M+P-1), activation footprint O(M) stage inputs per device (the
  scan carry is saved per tick).
- **1F1B** (``schedule="1f1b"``): the steady-state one-forward-one-backward
  schedule (PipeDream-flush, what Megatron/DeepSpeed run). Reverse-mode AD
  cannot produce it (it is not "forward then transpose"), so the backward
  is built manually: each tick every stage runs one microbatch forward
  AND one microbatch backward (``jax.vjp`` per stage, recomputing the
  stage forward from its saved *input* — remat at stage granularity), the
  last stage turns a microbatch's loss into d(loss)/dy the same tick its
  forward completes. Activation footprint is a ring buffer of 2P-1 stage
  inputs per device — **independent of M**, the property that lets real
  pipelines run M >> P microbatches to shrink the bubble.
- **Interleaved 1F1B** (``schedule="interleaved"``, Megatron virtual
  pipeline stages; ref StageInterleaver.py:16): each device owns
  ``virtual_stages`` non-contiguous layer chunks, shrinking the bubble
  by ~v at equal M for an O(vP) activation ring buffer — see
  ``pipeline_value_and_grad_1f1b``'s docstring for the tick algebra.

Layout contract: the embedding runs before the pipeline region and the
final-norm/LM-head after it, in plain GSPMD-auto land; only the L
transformer blocks are staged. ``cfg.num_layers`` must divide evenly into
``pp`` stages and all blocks must be homogeneous (no MoE interleave —
EP×PP composition is scoped out, as in the reference where MoE and PiPPy
pipelines are separate optimizations).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from dlrover_tpu.common.jax_compat import pcast, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.models.config import TransformerConfig
from dlrover_tpu.models.train import TrainState, opt_state_shardings
from dlrover_tpu.models.transformer import (
    _attention_block,
    _mlp_block,
    embed_tokens,
    init_params,
    lm_head,
    logical_axes,
    token_nll,
)
from dlrover_tpu.parallel.sharding_rules import (
    ShardingRules,
    apply_rules,
    default_lm_rules,
)

STAGE_AXES = ("stage", "layer_stack")  # leading axes of stacked stage params


def pipeline_rules(rules: Optional[ShardingRules] = None) -> ShardingRules:
    """Extend the LM rule table with the stage axes: "stage" → pp mesh
    axis, the intra-stage layer-stack axis replicated."""
    rules = rules or default_lm_rules()
    merged = dict(rules.rules)
    merged.setdefault("stage", "pp")
    merged.setdefault("chunk", None)  # virtual stages: per-device slots
    merged.setdefault("layer_stack", None)
    return ShardingRules(rules=merged)


def _microbatch_axes(mesh, mb: int) -> Tuple[str, ...]:
    """Mesh axes to shard the per-microbatch batch dim over: the largest
    prefix of ("dp", "fsdp") whose device product divides ``mb``.

    Constraining mb over axes that do NOT divide it (e.g. mb=2 over
    dp*fsdp=4) makes XLA pad-and-reshard every stage boundary — the
    "Involuntary full rematerialization" warnings the SPMD partitioner
    emits when it must replicate a tensor to move between such layouts.
    """
    axes = []
    n = 1
    for ax in ("dp", "fsdp"):
        sz = mesh.shape.get(ax, 1)
        if sz > 1 and mb % (n * sz) == 0:
            axes.append(ax)
            n *= sz
    return tuple(axes)


def _check_pipeline_cfg(
    cfg: TransformerConfig, pp: int, virtual: int = 1
) -> None:
    if cfg.num_experts:
        raise ValueError(
            "pipeline parallelism requires homogeneous blocks (MoE layers "
            "interleave a different tree structure); use ep without pp"
        )
    if cfg.scan_layers:
        raise ValueError(
            "pipeline parallelism has its own stage-stacked layout; set "
            "scan_layers=False (stages already scan their layer block)"
        )
    stages = pp * virtual
    if cfg.num_layers % stages != 0:
        what = (
            f"pp={pp} x virtual={virtual} = {stages} chunks"
            if virtual > 1
            else f"pp={pp} stages"
        )
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide into {what}"
        )


def stack_pipeline_params(params: Any, pp: int, virtual: int = 1) -> Any:
    """{"embed","final_norm",("lm_head"),"layers":[L dicts]} →
    same dict with "layers" replaced by "stages".

    ``virtual=1``: leaves [pp, L/pp, ...] — device d owns the contiguous
    layer block d.
    ``virtual=v>1`` (interleaved schedules): leaves [pp, v, L/(v*pp), ...]
    — global stage s = q*pp + d lives at [d, q], i.e. device d owns v
    NON-contiguous layer chunks (Megatron virtual pipeline stages, ref
    StageInterleaver.py:16)."""
    layers = params["layers"]
    lc = len(layers) // (pp * virtual)
    stages = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs).reshape(
            virtual, pp, lc, *xs[0].shape
        ).swapaxes(0, 1)
        if virtual > 1
        else jnp.stack(xs).reshape(pp, lc, *xs[0].shape),
        *layers,
    )
    out = {k: v for k, v in params.items() if k != "layers"}
    out["stages"] = stages
    return out


def _dechunk_leaf(x, virtual: int):
    """One stacked-stage leaf back to global layer order [L, ...]:
    [pp, lc, ...] (``virtual=1``) or chunk-major [pp, v, lc, ...]
    (``virtual>1``, via stage-major [v, pp, lc, ...]). The SINGLE home
    of the interleaved-layout algebra — ``unstack_pipeline_params`` and
    ``pipeline_forward``'s eval restack both go through here."""
    if virtual > 1:
        x = x.swapaxes(0, 1)
    return x.reshape(-1, *x.shape[2 + (virtual > 1):])


def unstack_pipeline_params(
    pparams: Any, cfg: TransformerConfig, virtual: int = 1
) -> Any:
    """Inverse of ``stack_pipeline_params`` (for checkpoints / eval)."""
    stages = pparams["stages"]
    L = cfg.num_layers

    flat = jax.tree_util.tree_map(
        lambda x: _dechunk_leaf(x, virtual), stages
    )
    layers = [
        jax.tree_util.tree_map(lambda x: x[i], flat) for i in range(L)
    ]
    out = {k: v for k, v in pparams.items() if k != "stages"}
    out["layers"] = layers
    return out


def pipeline_logical_axes(
    cfg: TransformerConfig, pp: int, virtual: int = 1
) -> Any:
    """Logical-axis pytree congruent with ``stack_pipeline_params``'s
    output: per-layer axes prefixed with the (stage[, chunk], layer_stack)
    axes."""
    axes = logical_axes(cfg)
    layer0 = axes["layers"][0]
    prefix = (
        ("stage", "chunk", "layer_stack") if virtual > 1 else STAGE_AXES
    )

    def prefixed(t):
        return prefix + t

    stages = jax.tree_util.tree_map(
        prefixed,
        layer0,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )
    out = {k: v for k, v in axes.items() if k != "layers"}
    out["stages"] = stages
    return out


def pipeline_param_shardings(
    cfg: TransformerConfig, mesh, pp: int, rules=None, virtual: int = 1
):
    return apply_rules(
        pipeline_logical_axes(cfg, pp, virtual), pipeline_rules(rules), mesh
    )


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def pipeline_forward(
    pparams: Any,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh,
    num_microbatches: int,
    virtual: int = 1,
) -> jnp.ndarray:
    """tokens [B,T] int32 → logits [B,T,vocab] fp32, staged over pp.

    B must divide by ``num_microbatches`` (and the microbatch by the dp
    sharding, as usual).

    ``virtual>1`` accepts params in the interleaved [pp, v, lc, ...]
    layout (stack_pipeline_params) and restacks them in-graph to the
    contiguous [pp, L/pp, ...] layout this forward schedule uses: the
    grad-free eval path doesn't need the interleaved bubble win, only
    layout compatibility with the training state. The restack is one
    GSPMD reshard over pp per eval compile — acceptable for eval.
    """
    pp = mesh.shape["pp"]
    M = num_microbatches
    _check_pipeline_cfg(cfg, pp, virtual)
    if virtual > 1:
        L = cfg.num_layers

        def to_contiguous(x):
            # global layer order, then contiguous stages [pp, L/pp, ...]
            flat = _dechunk_leaf(x, virtual)
            return flat.reshape(pp, L // pp, *flat.shape[1:])

        pparams = dict(pparams)
        pparams["stages"] = jax.tree_util.tree_map(
            to_contiguous, pparams["stages"]
        )
    if mesh.shape.get("sp", 1) > 1:
        raise ValueError("sp (ring attention) inside pp stages not supported")
    B, T = tokens.shape
    if B % M != 0:
        raise ValueError(f"batch {B} must divide into {M} microbatches")
    mb = B // M

    # embedding: before the pipeline region, plain GSPMD. Reshape the
    # token ids into microbatch layout FIRST and pin the layout, so the
    # [M, mb, T, D] activations are BORN in the spec the pipeline body
    # uses — never resharded at the region boundary
    mb_axes = _microbatch_axes(mesh, mb)
    tok_mb = lax.with_sharding_constraint(
        tokens.reshape(M, mb, T), NamedSharding(mesh, P(None, mb_axes))
    )
    x = embed_tokens(pparams, tok_mb, cfg)
    x = lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(None, mb_axes))
    )

    def block(x, layer):
        positions = jnp.broadcast_to(jnp.arange(T), x.shape[:2])
        x = _attention_block(x, layer, cfg, None, positions)
        x, _ = _mlp_block(x, layer, cfg, None)
        return x

    def stage_fn(stage_layers, x):
        """Apply this stage's L/pp stacked layers via scan."""

        def body(x, layer):
            y = block(x, layer)
            return y, None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = lax.scan(body, x, stage_layers)
        return x

    def pipelined(stages, x_mb):
        # manual over pp: stages arrive [1, L/pp, ...] — drop the stage dim
        stages_loc = jax.tree_util.tree_map(lambda a: a[0], stages)
        idx = lax.axis_index("pp")
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        x_loc = pcast(x_mb, ("pp",), to="varying")
        state = jnp.zeros_like(x_loc[0])
        outputs = jnp.zeros_like(x_loc)

        def tick(carry, t):
            state, outputs = carry
            inject = lax.dynamic_index_in_dim(
                x_loc, jnp.minimum(t, M - 1), 0, keepdims=False
            )
            cur = jnp.where(idx == 0, inject, state)
            out = stage_fn(stages_loc, cur)
            oi = t - (pp - 1)
            write = (idx == pp - 1) & (oi >= 0)
            upd = lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(oi, 0, M - 1), 0
            )
            outputs = jnp.where(write, upd, outputs)
            if pp > 1:
                state = lax.ppermute(out, "pp", perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(
            tick, (state, outputs), jnp.arange(M + pp - 1)
        )
        # new leading axis concatenated over pp → global [pp, M, mb, T, D]
        return outputs[None]

    outs = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(P("pp"), P()),
        out_specs=P("pp"),
        # manual over pp ONLY: dp/fsdp/tp stay GSPMD-auto inside the body
        # (without this, shard_map is manual over every mesh axis — stage
        # params would be all-gathered and each dp device would redo the
        # full batch)
        axis_names={"pp"},
    )(pparams["stages"], x)
    y = lax.with_sharding_constraint(
        outs[pp - 1], NamedSharding(mesh, P(None, mb_axes))
    ).reshape(B, T, cfg.model_dim)

    # final norm + head: after the pipeline region, plain GSPMD
    return lm_head(pparams, y, cfg)


def pipeline_loss_fn(
    pparams,
    tokens,
    targets,
    cfg: TransformerConfig,
    mesh,
    num_microbatches,
    virtual: int = 1,
) -> jnp.ndarray:
    logits = pipeline_forward(
        pparams, tokens, cfg, mesh, num_microbatches, virtual=virtual
    )
    return token_nll(logits, targets)


# ---------------------------------------------------------------------------
# 1F1B schedule (manual backward)
# ---------------------------------------------------------------------------
def schedule_occupancy(pp: int, M: int, virtual: int = 1):
    """Pure-Python occupancy model of the (interleaved) 1F1B tick clock —
    the same index algebra the compiled scan uses. Returns
    ``(n_ticks, busy_slots, total_slots)`` where each device contributes
    2 slots per tick (one forward, one backward) and a slot is busy when
    its decomposition lands on a real (microbatch, chunk) pair.

    Bubble fraction = 1 - busy/total = (v+1)(P-1)/(vM + (v+1)(P-1))
    — interleaving with v chunks divides the non-overlapped pipeline
    fill/drain by v relative to the work, the Megatron virtual-pipeline
    effect (bubble (P-1)/(vM+P-1) in their accounting, which counts the
    overlapped last-stage fwd+bwd tick once)."""
    v = virtual
    # v>1: microbatches enter in lane groups of P; a partial last group
    # still takes a full group's ticks (its empty lanes are bubbles)
    m_pad = M if v == 1 else -(-M // pp) * pp
    n_ticks = v * m_pad + (v + 1) * pp - 2
    busy = 0
    for d in range(pp):
        for t in range(n_ticks):
            u = t - d
            if u >= 0:
                i, r = u % pp, u // pp
                if (r // v) * pp + i < M:
                    busy += 1
            wb = t + d - 2 * (pp - 1)
            if wb >= 0:
                i, r = wb % pp, wb // pp
                q = (2 * v - 2 - r) % v
                g = (r - (2 * v - 2 - q)) // v
                if g >= 0 and g * pp + i < M:
                    busy += 1
    return n_ticks, busy, 2 * pp * n_ticks


def _shared_grads(cfg: TransformerConfig, ghead: Any, gemb: Any) -> Any:
    """Combine head/embed grads into the tree ``plan_for_pipeline``'s
    shared plan was built over (the non-stage keys of
    ``stack_pipeline_params``' output): {"embed", "final_norm"
    [, "lm_head"]}, with the tied-embedding head contribution folded
    into the embed leaf."""
    if cfg.tie_embeddings:
        embed = jax.tree_util.tree_map(
            jnp.add, gemb, ghead["embed"]
        )
        return {"embed": embed, "final_norm": ghead["final_norm"]}
    return {
        "embed": gemb,
        "final_norm": ghead["final_norm"],
        "lm_head": ghead["lm_head"],
    }


def pipeline_value_and_grad_1f1b(
    pparams: Any,
    tokens: jnp.ndarray,
    targets: jnp.ndarray,
    cfg: TransformerConfig,
    mesh,
    num_microbatches: int,
    virtual: int = 1,
    sync_plan=None,
) -> Tuple[jnp.ndarray, Any]:
    """(loss, grads) under the 1F1B schedule; grads congruent to pparams.

    ``sync_plan`` (a ``grad_sync.PPSyncPlan``, pp x dp meshes only):
    the explicit per-stage sync path — the region goes manual over
    (pp, dp), each dp rank runs the schedule on its ``mb/dp`` rows
    and accumulates LOCAL grads, and the moment the scan drains each
    stage's grads are bucket-synced over its dp sub-axis inside the
    region (``grad_sync.sync_local_tree``): independent per-stage
    collectives XLA schedules into the fill/drain bubble instead of
    GSPMD's post-drain monolithic all-reduce. Returns
    ``(loss, grads, grad_norm)`` in this mode (the norm falls out of
    the bucket walk).

    Tick clock (``virtual=1``): stage i runs forward of microbatch j at
    tick ``i + j`` and backward of microbatch j at tick ``2(P-1) - i + j``
    (so the last stage does fwd+bwd of the same microbatch in one tick,
    stage 0's backward lags its forward by 2(P-1) ticks — the classic
    1F1B picture). Both hops (activations forward, cotangents backward)
    are next-tick ``ppermute`` neighbours, so one scan over ``M + 2(P-1)``
    ticks runs the whole schedule. Stage inputs wait in a ring buffer of
    ``2P-1`` slots (max residency 2(P-1) ticks < 2P-1); the stage forward
    is recomputed inside ``jax.vjp`` at the backward tick, so nothing
    else is stored.

    **Interleaved 1F1B** (``virtual=v>1``, ref StageInterleaver.py:16 /
    Megatron virtual pipeline stages): device d owns v layer *chunks* —
    global stage s = q*P + d — so each microbatch rides the same P-device
    ring v times. The whole schedule stays one scan because every
    transition remains a single-tick ring hop: forward of (microbatch
    group g, lane i, chunk q) on device d fires at tick
    ``t = g*vP + q*P + i + d`` and its backward at
    ``t + (2v-2-2q)*P + 2(P-1-d)`` — both decompositions are unique per
    (device, tick), so each device runs exactly one chunk-forward and one
    chunk-backward per tick, picking its chunk by ``q = (u div P) mod v``.
    The chunk-(v-1)→chunk-q+1 wraparound rides the SAME ppermute as the
    stage hops (ring edge P-1 → 0). Per-tick work is 1/v of a ``virtual=1``
    stage, so the fill/drain bubble shrinks by ~v at equal microbatch
    count: bubble (v+1)(P-1) slot-pairs against vM of work (see
    ``schedule_occupancy``). Cost: the activation ring buffer grows to
    ``2vP-1`` *chunk* inputs (same bytes per entry), the known memory
    trade of interleaving.

    Only *token ids* ([M, mb, T] int32 — no model-dim factor) cross the
    shard_map boundary per microbatch: the embedding lookup runs inside
    the tick on stage 0 and its backward is a hand-written scatter-add
    into the embedding-grad accumulator (the gather's exact vjp, but
    touching only the mb*T gathered rows per tick instead of
    materializing a dense [vocab, D] cotangent to sum). So per-device
    activation state really is the O(P) ring buffer; nothing activation-
    sized scales with M.

    The loss head (final norm + vocab projection) and the embedding are
    evaluated inside the tick on every stage (SPMD lockstep — only the
    last/first stage's result is kept); the head costs one microbatch
    head per tick, the same order as the stage compute it overlaps with.
    """
    pp = mesh.shape["pp"]
    M = num_microbatches
    v = virtual
    _check_pipeline_cfg(cfg, pp, v)
    if mesh.shape.get("sp", 1) > 1:
        raise ValueError("sp (ring attention) inside pp stages not supported")
    B, T = tokens.shape
    if B % M != 0:
        raise ValueError(f"batch {B} must divide into {M} microbatches")
    mb = B // M
    dp = mesh.shape.get("dp", 1)
    local_dp = sync_plan is not None and dp > 1
    if local_dp and mb % dp:
        raise ValueError(
            f"explicit pp sync needs the microbatch ({mb}) to divide "
            f"over dp={dp} (each rank runs the schedule on its rows)"
        )
    mb_loc = mb // dp if local_dp else mb
    D = cfg.model_dim

    head_params = {"final_norm": pparams["final_norm"]}
    if cfg.tie_embeddings:
        head_params["embed"] = pparams["embed"]
    else:
        head_params["lm_head"] = pparams["lm_head"]

    emb_params = pparams["embed"]
    if mesh.shape.get("tp", 1) > 1:
        # PP×TP composition: the vocab-PARALLEL embedding gather /
        # scatter-add and head projection cannot be partitioned inside
        # the pp-manual scan — XLA's SPMD partitioner hits a subgroup
        # CHECK (spmd_partitioner_util.cc) trying to group the gather's
        # collective across tp while pp is manual. The persistent state
        # keeps its vocab→tp layout (shared with gpipe, whose embed/head
        # run OUTSIDE the shard_map region); here we pin a vocab-
        # replicated copy for the body — one tp all-gather of the
        # embed/head tables per step, amortized over all M microbatches.
        devocab = dict(pipeline_rules(None).rules)
        devocab["vocab"] = None
        devocab_rules = ShardingRules(rules=devocab)
        la = logical_axes(cfg)

        def _pin(tree, axes):
            return jax.tree_util.tree_map(
                lax.with_sharding_constraint,
                tree,
                apply_rules(axes, devocab_rules, mesh),
            )

        emb_params = _pin(emb_params, la["embed"])
        head_params = _pin(
            head_params, {k: la[k] for k in head_params}
        )

    mb_axes = _microbatch_axes(mesh, mb)
    tok = lax.with_sharding_constraint(
        tokens.reshape(M, mb, T),
        NamedSharding(mesh, P(None, mb_axes)),
    )
    tgt = lax.with_sharding_constraint(
        targets.reshape(M, mb, T),
        NamedSharding(mesh, P(None, mb_axes)),
    )

    def block(xx, layer):
        positions = jnp.broadcast_to(jnp.arange(T), xx.shape[:2])
        xx = _attention_block(xx, layer, cfg, None, positions)
        xx, _ = _mlp_block(xx, layer, cfg, None)
        return xx

    def stage_fn(stage_layers, xx):
        def body(xx, layer):
            return block(xx, layer), None

        if cfg.remat:
            body = jax.checkpoint(body)
        xx, _ = lax.scan(body, xx, stage_layers)
        return xx

    def head_loss(hp, y, t_mb):
        # /M so per-microbatch cotangents and head grads sum to the grads
        # of the mean-over-microbatches loss
        return token_nll(lm_head(hp, y, cfg), t_mb) / M

    # v>1: microbatches enter in lane groups of P; when M is not a
    # multiple of P the last (partial) group still occupies a full
    # group's ticks — without the pad, the final group's backward slots
    # would fall past the scan end and their gradient contributions
    # silently vanish. v=1 injects at rate 1 (j == t - d), no pad needed.
    m_pad = M if v == 1 else -(-M // pp) * pp
    n_ticks = v * m_pad + (v + 1) * pp - 2
    buf_n = 2 * v * pp - 1

    def pipelined(stages, head_p, emb_p, tok_all, tgt_all):
        stages_loc = jax.tree_util.tree_map(lambda a: a[0], stages)
        idx = lax.axis_index("pp")
        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
        bwd_perm = [((i + 1) % pp, i) for i in range(pp)]

        def vary(a):
            return pcast(
                a, ("pp", "dp") if local_dp else ("pp",), to="varying"
            )

        tok_loc = vary(tok_all)
        tgt_loc = vary(tgt_all)
        head_loc = jax.tree_util.tree_map(vary, head_p)
        emb_loc = jax.tree_util.tree_map(vary, emb_p)

        def chunk_of(tree, q_c):
            """Select chunk q's [lc, ...] slice of a [v, lc, ...] tree
            (identity when virtual == 1 — leaves carry no chunk axis)."""
            if v == 1:
                return tree
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(
                    a, q_c, 0, keepdims=False
                ),
                tree,
            )

        act_dt = jnp.dtype(cfg.dtype)
        zeros_mb = vary(jnp.zeros((mb_loc, T, D), act_dt))
        carry0 = (
            zeros_mb,  # act: activation arriving from the previous stage
            zeros_mb,  # gin: cotangent arriving from the next stage
            vary(jnp.zeros((buf_n, mb_loc, T, D), act_dt)),
            jax.tree_util.tree_map(jnp.zeros_like, stages_loc),
            jax.tree_util.tree_map(jnp.zeros_like, head_loc),
            jax.tree_util.tree_map(jnp.zeros_like, emb_loc),
            vary(jnp.float32(0.0)),  # loss accumulator (last stage)
        )

        def tick(carry, t):
            act, gin, buf, gstage, ghead, gemb, loss_acc = carry
            last = idx == pp - 1

            # -- forward slot: unique (group g, lane i, chunk q) for this
            # (device, tick): u = g*vP + q*P + i
            u = t - idx
            i_f = u % pp
            r_f = u // pp
            q_f = r_f % v
            jf = (r_f // v) * pp + i_f
            fwd_on = (u >= 0) & (jf < M)
            jf_c = jnp.clip(jf, 0, M - 1)
            q_f_c = jnp.clip(q_f, 0, v - 1)
            tok_mb = lax.dynamic_index_in_dim(
                tok_loc, jf_c, 0, keepdims=False
            )
            inject = embed_tokens({"embed": emb_loc}, tok_mb, cfg)
            x_in = jnp.where(
                (idx == 0) & (q_f == 0), inject.astype(act_dt), act
            )
            y = stage_fn(chunk_of(stages_loc, q_f_c), x_in)
            buf = jnp.where(
                fwd_on,
                lax.dynamic_update_index_in_dim(buf, x_in, t % buf_n, 0),
                buf,
            )

            # -- global last stage (chunk v-1 on device P-1): loss ->
            # d(loss)/dy the same tick (the "1B" of this tick consumes it
            # below: jb == jf and q_b == v-1 there)
            t_mb = lax.dynamic_index_in_dim(
                tgt_loc, jf_c, 0, keepdims=False
            )
            loss_mb, (dhead, dy_head) = jax.value_and_grad(
                head_loss, argnums=(0, 1)
            )(head_loc, y, t_mb)
            loss_on = last & fwd_on & (q_f == v - 1)
            loss_w = loss_on.astype(jnp.float32)
            loss_acc = loss_acc + loss_mb * loss_w
            # mask by scalar multiply, not where-select: a 0/1 scale
            # fuses into the add (matters for the tied-embedding head
            # whose grads are [vocab, D]-dense)
            ghead = jax.tree_util.tree_map(
                lambda g, d: g + d * loss_w.astype(d.dtype), ghead, dhead
            )

            # -- backward slot: wb = g*vP + (2v-2-q)*P + i
            wb = t + idx - 2 * (pp - 1)
            i_b = wb % pp
            r_b = wb // pp
            q_b = (2 * v - 2 - r_b) % v
            g_b = (r_b - (2 * v - 2 - q_b)) // v
            jb = g_b * pp + i_b
            bwd_on = (wb >= 0) & (g_b >= 0) & (jb < M)
            jb_c = jnp.clip(jb, 0, M - 1)
            q_b_c = jnp.clip(q_b, 0, v - 1)
            # the forward of (jb, q_b) on this device ran at
            # t - (2v-2-2q_b)*P - 2(P-1-idx); its input sits at that
            # tick's ring-buffer slot
            t_f_saved = (
                t - (2 * v - 2 - 2 * q_b_c) * pp - 2 * (pp - 1 - idx)
            )
            x_saved = lax.dynamic_index_in_dim(
                buf, t_f_saved % buf_n, 0, keepdims=False
            )
            dy = jnp.where(
                last & (q_b == v - 1), dy_head.astype(x_saved.dtype), gin
            )
            chunk_b = chunk_of(stages_loc, q_b_c)
            _, svjp = jax.vjp(stage_fn, chunk_b, x_saved)
            dstage, dxi = svjp(dy)
            bwd_w = bwd_on.astype(jnp.float32)
            if v == 1:
                gstage = jax.tree_util.tree_map(
                    lambda g, d: g + d * bwd_w.astype(d.dtype),
                    gstage,
                    dstage,
                )
            else:
                # accumulate into chunk q_b's rows (a masked-off tick
                # writes back chunk + 0 — a no-op)
                gstage = jax.tree_util.tree_map(
                    lambda g, d: lax.dynamic_update_index_in_dim(
                        g,
                        lax.dynamic_index_in_dim(
                            g, q_b_c, 0, keepdims=False
                        )
                        + d * bwd_w.astype(d.dtype),
                        q_b_c,
                        0,
                    ),
                    gstage,
                    dstage,
                )

            # -- embedding backward (global stage 0 = chunk 0, device 0):
            # the gather's vjp is a scatter-add touching only the mb*T
            # gathered rows — never a dense [vocab, D] cotangent
            emb_w = ((idx == 0) & (q_b == 0) & bwd_on).astype(jnp.float32)
            tok_jb = lax.dynamic_index_in_dim(
                tok_loc, jb_c, 0, keepdims=False
            )
            contrib = dxi.astype(jnp.float32) * emb_w
            gtok = gemb["tokens"].at[tok_jb.reshape(-1)].add(
                contrib.reshape(-1, D).astype(gemb["tokens"].dtype)
            )
            new_gemb = dict(gemb)
            new_gemb["tokens"] = gtok
            if "positions" in gemb:
                new_gemb["positions"] = (
                    gemb["positions"]
                    .at[:T]
                    .add(contrib.sum(0).astype(gemb["positions"].dtype))
                )
            gemb = new_gemb

            # -- next-tick hops: activations one stage forward, cotangents
            # one stage back
            if pp > 1:
                act = lax.ppermute(y, "pp", fwd_perm)
                gin = lax.ppermute(dxi, "pp", bwd_perm)
            return (act, gin, buf, gstage, ghead, gemb, loss_acc), None

        (_, _, _, gstage, ghead, gemb, loss_acc), _ = lax.scan(
            tick, carry0, jnp.arange(n_ticks)
        )
        # only one stage holds each of these (masked zeros elsewhere), so
        # psum over pp is selection, not averaging
        loss_out = lax.psum(loss_acc, "pp")
        ghead_out = jax.tree_util.tree_map(
            lambda g: lax.psum(g, "pp"), ghead
        )
        gemb_out = jax.tree_util.tree_map(
            lambda g: lax.psum(g, "pp"), gemb
        )
        if local_dp:
            # the explicit per-stage sync, INSIDE the manual region:
            # this stage's dp sub-axis collectives are issued the
            # moment its grads are complete — independent ops the
            # scheduler packs into the drain bubble
            from dlrover_tpu.parallel.grad_sync import sync_local_tree

            shared = _shared_grads(cfg, ghead_out, gemb_out)
            gstage_s, ss_st = sync_local_tree(
                gstage, sync_plan.stage_plan
            )
            shared_s, ss_sh = sync_local_tree(
                shared, sync_plan.shared_plan
            )
            gnorm = jnp.sqrt(lax.psum(ss_st, "pp") + ss_sh)
            gstage_out = jax.tree_util.tree_map(
                lambda g: g[None], gstage_s
            )
            return (
                gstage_out,
                shared_s,
                lax.pmean(loss_out, "dp"),
                gnorm,
            )
        gstage_out = jax.tree_util.tree_map(lambda g: g[None], gstage)
        return gstage_out, ghead_out, gemb_out, loss_out

    if local_dp:
        gstage, shared, loss, gnorm = shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P("pp"), P(), P(), P(None, "dp"), P(None, "dp")),
            out_specs=(P("pp"), P(), P(), P()),
            axis_names={"pp", "dp"},
            check_vma=False,
        )(pparams["stages"], head_params, emb_params, tok, tgt)
        grads = dict(shared)
        grads["stages"] = gstage
        return loss, grads, gnorm
    gstage, ghead, gemb, loss = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(), P()),
        out_specs=(P("pp"), P(), P(), P()),
        axis_names={"pp"},
    )(pparams["stages"], head_params, emb_params, tok, tgt)

    if mesh.shape.get("tp", 1) > 1:
        # pin the grad OUTPUTS to the same vocab-replicated layout: the
        # optimizer downstream holds vocab→tp moments, and without this
        # boundary XLA propagates that layout back into the scan carry —
        # recreating exactly the unpartitionable gather/scatter inside
        # the loop that the input pin above avoided
        ghead = _pin(ghead, {k: la[k] for k in ghead})
        gemb = _pin(gemb, la["embed"])

    grads = {
        "stages": gstage,
        "final_norm": ghead["final_norm"],
        "embed": gemb,
    }
    if cfg.tie_embeddings:
        grads["embed"] = jax.tree_util.tree_map(
            jnp.add, grads["embed"], ghead["embed"]
        )
    else:
        grads["lm_head"] = ghead["lm_head"]
    return loss, grads


def pipeline_value_and_grad_gpipe_sync(
    pparams: Any,
    tokens: jnp.ndarray,
    targets: jnp.ndarray,
    cfg: TransformerConfig,
    mesh,
    num_microbatches: int,
    sync_plan,
) -> Tuple[jnp.ndarray, Any, jnp.ndarray]:
    """(loss, grads, grad_norm) under the GPipe schedule with the
    explicit per-stage dp sync (pp x dp meshes, ``PPSyncPlan``).

    The region is fully manual over (pp, dp): each dp rank runs the
    same M+P-1 tick rotation ``pipeline_forward`` uses — embedding
    and head INSIDE the region on its ``mb/dp`` rows — and
    reverse-mode AD through the scan-of-ppermute yields the backward
    rotation, producing per-rank LOCAL grads (no GSPMD dp psum).
    Each stage's grads are then bucket-synced over its dp sub-axis
    in the region (``grad_sync.sync_local_tree``): per-stage
    independent reduce-scatter/all-gather pairs the scheduler can
    start during the drain, instead of one post-drain monolithic
    all-reduce over the whole tree."""
    pp = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    M = num_microbatches
    _check_pipeline_cfg(cfg, pp, 1)
    if mesh.shape.get("sp", 1) > 1:
        raise ValueError("sp (ring attention) inside pp stages not supported")
    B, T = tokens.shape
    if B % M != 0:
        raise ValueError(f"batch {B} must divide into {M} microbatches")
    mb = B // M
    if dp > 1 and mb % dp:
        raise ValueError(
            f"explicit pp sync needs the microbatch ({mb}) to divide "
            f"over dp={dp}"
        )
    mb_loc = mb // max(dp, 1)
    D = cfg.model_dim

    head_params = {"final_norm": pparams["final_norm"]}
    if cfg.tie_embeddings:
        head_params["embed"] = pparams["embed"]
    else:
        head_params["lm_head"] = pparams["lm_head"]
    emb_params = pparams["embed"]

    mb_axes = _microbatch_axes(mesh, mb)
    tok = lax.with_sharding_constraint(
        tokens.reshape(M, mb, T),
        NamedSharding(mesh, P(None, mb_axes)),
    )
    tgt = lax.with_sharding_constraint(
        targets.reshape(M, mb, T),
        NamedSharding(mesh, P(None, mb_axes)),
    )

    def block(xx, layer):
        positions = jnp.broadcast_to(jnp.arange(T), xx.shape[:2])
        xx = _attention_block(xx, layer, cfg, None, positions)
        xx, _ = _mlp_block(xx, layer, cfg, None)
        return xx

    def stage_fn(stage_layers, xx):
        def body(xx, layer):
            return block(xx, layer), None

        if cfg.remat:
            body = jax.checkpoint(body)
        xx, _ = lax.scan(body, xx, stage_layers)
        return xx

    def pipelined(stages, head_p, emb_p, tok_all, tgt_all):
        from dlrover_tpu.parallel.grad_sync import sync_local_tree

        stages_loc = jax.tree_util.tree_map(lambda a: a[0], stages)
        idx = lax.axis_index("pp")
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def vary(a):
            return pcast(a, ("pp", "dp"), to="varying")

        tok_loc = vary(tok_all)
        tgt_loc = vary(tgt_all)
        head_loc = jax.tree_util.tree_map(vary, head_p)
        emb_loc = jax.tree_util.tree_map(vary, emb_p)
        act_dt = jnp.dtype(cfg.dtype)
        last = idx == pp - 1

        def local_loss(stages_l, head_l, emb_l):
            x_all = embed_tokens(
                {"embed": emb_l}, tok_loc, cfg
            ).astype(act_dt)  # [M, mb_loc, T, D]
            carry0 = (
                jnp.zeros((mb_loc, T, D), act_dt),
                jnp.zeros((M, mb_loc, T, D), act_dt),
            )

            def tick(carry, t):
                st, outputs = carry
                inject = lax.dynamic_index_in_dim(
                    x_all, jnp.minimum(t, M - 1), 0, keepdims=False
                )
                cur = jnp.where(idx == 0, inject, st)
                out = stage_fn(stages_l, cur)
                oi = t - (pp - 1)
                write = last & (oi >= 0)
                upd = lax.dynamic_update_index_in_dim(
                    outputs, out, jnp.clip(oi, 0, M - 1), 0
                )
                outputs = jnp.where(write, upd, outputs)
                if pp > 1:
                    st = lax.ppermute(out, "pp", perm)
                else:
                    st = out
                return (st, outputs), None

            (_, outputs), _ = lax.scan(
                tick, carry0, jnp.arange(M + pp - 1)
            )
            y = outputs.reshape(M * mb_loc, T, D)
            t_flat = tgt_loc.reshape(M * mb_loc, T)
            loss_local = token_nll(lm_head(head_l, y, cfg), t_flat)
            # only the last stage's outputs are real. The psum that
            # shares the scalar happens OUTSIDE the AD below: psum
            # transposes to psum, which would hand every rank a
            # pp-scaled cotangent; seeding ct=1 on each rank's MASKED
            # local loss is the correct seed (non-last ranks' zeros
            # contribute nothing, and their params' influence arrives
            # through the ppermute transpose)
            return loss_local * last.astype(jnp.float32)

        loss_l, (dstage, dhead, demb) = jax.value_and_grad(
            local_loss, argnums=(0, 1, 2)
        )(stages_loc, head_loc, emb_loc)
        loss = lax.psum(loss_l, "pp")  # selection, not averaging
        # head grads live only on the last stage, embed-gather grads
        # only on stage 0 (masked zeros elsewhere): psum = selection
        dhead = jax.tree_util.tree_map(
            lambda g: lax.psum(g, "pp"), dhead
        )
        demb = jax.tree_util.tree_map(
            lambda g: lax.psum(g, "pp"), demb
        )
        shared = _shared_grads(cfg, dhead, demb)
        gstage_s, ss_st = sync_local_tree(dstage, sync_plan.stage_plan)
        shared_s, ss_sh = sync_local_tree(
            shared, sync_plan.shared_plan
        )
        gnorm = jnp.sqrt(lax.psum(ss_st, "pp") + ss_sh)
        gstage_out = jax.tree_util.tree_map(
            lambda g: g[None], gstage_s
        )
        return gstage_out, shared_s, lax.pmean(loss, "dp"), gnorm

    gstage, shared, loss, gnorm = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(P("pp"), P(), P(), P(None, "dp"), P(None, "dp")),
        out_specs=(P("pp"), P(), P(), P()),
        axis_names={"pp", "dp"},
        check_vma=False,
    )(pparams["stages"], head_params, emb_params, tok, tgt)
    grads = dict(shared)
    grads["stages"] = gstage
    return loss, grads, gnorm


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------
def pipeline_state_shardings(
    cfg: TransformerConfig, mesh, tx, rules=None, virtual: int = 1
) -> TrainState:
    pp = mesh.shape["pp"]
    p_sh = pipeline_param_shardings(cfg, mesh, pp, rules, virtual)
    replicated = NamedSharding(mesh, P())
    params_shape = jax.eval_shape(
        lambda: stack_pipeline_params(
            init_params(jax.random.PRNGKey(0), cfg), pp, virtual
        )
    )
    opt_sh = opt_state_shardings(params_shape, p_sh, tx, mesh)
    return TrainState(step=replicated, params=p_sh, opt_state=opt_sh)


def init_pipeline_state(
    key, cfg: TransformerConfig, mesh, tx, rules=None, virtual: int = 1
) -> Tuple[TrainState, TrainState]:
    """Initialize stacked pipeline params/opt state directly into their
    shardings (stage s's rows materialize on stage s's devices)."""
    pp = mesh.shape["pp"]
    _check_pipeline_cfg(cfg, pp, virtual)
    sh = pipeline_state_shardings(cfg, mesh, tx, rules, virtual)

    def _init(key):
        return stack_pipeline_params(init_params(key, cfg), pp, virtual)

    params = jax.jit(_init, out_shardings=sh.params)(key)
    opt_state = jax.jit(tx.init, out_shardings=sh.opt_state)(params)
    step = jax.device_put(jnp.zeros((), jnp.int32), sh.step)
    return TrainState(step=step, params=params, opt_state=opt_state), sh


def build_pipeline_train_step(
    cfg: TransformerConfig,
    mesh,
    tx,
    num_microbatches: int,
    rules: Optional[ShardingRules] = None,
    donate: bool = True,
    schedule: str = "gpipe",
    virtual_stages: int = 2,
    comm_overlap: bool = False,
    grad_bucket_mb: int = 4,
    grad_slices: int = 1,
):
    """jitted (state, tokens, targets) → (state, metrics) over pp.

    ``schedule``: "gpipe" (AD backward, O(M) activation footprint),
    "1f1b" (manual backward, O(P) footprint), or "interleaved"
    (1F1B with ``virtual_stages`` chunks per device — smaller bubble,
    O(vP) footprint; state must come from
    ``init_pipeline_state(..., virtual=virtual_stages)``).

    ``comm_overlap``: the explicit per-stage gradient sync for
    pp x dp meshes (``grad_sync.plan_for_pipeline``) — each stage's
    dp sync runs as independent bucketed collectives scheduled into
    the pipeline bubble instead of GSPMD's post-drain monolithic
    all-reduce; all three schedules are covered. Meshes that don't
    qualify (pp composed with fsdp/tp/sp/ep, or dp=1) fall back to
    the GSPMD schedule with a once-per-mesh log naming the axes.
    ``grad_slices`` threads a hybrid dp axis's DCN slice count
    (two-level dp legs)."""
    import optax

    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    virtual = virtual_stages if schedule == "interleaved" else 1
    if schedule == "interleaved" and virtual < 2:
        raise ValueError("interleaved schedule needs virtual_stages >= 2")

    sync_plan = None
    if comm_overlap:
        from dlrover_tpu.parallel.grad_sync import (
            note_gspmd_fallback,
            plan_for_pipeline,
        )

        from dlrover_tpu.parallel.grad_sync import fallback_reason

        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        sync_plan = plan_for_pipeline(
            cfg,
            sizes,
            grad_bucket_mb=grad_bucket_mb,
            slices=grad_slices,
            schedule=schedule,
            virtual=virtual,
        )
        if sync_plan is None:
            # the mesh may QUALIFY (kind "pp") while the MODEL cannot
            # pipeline at this degree — fallback_reason is empty then,
            # so name the actual cause instead of logging a reasonless
            # fallback for a "supported" mesh
            reason = fallback_reason(sizes) or (
                f"num_layers={cfg.num_layers} does not divide into "
                f"pp={sizes.get('pp')} x virtual={virtual} stages "
                f"(or the model cannot pipeline at all)"
            )
            note_gspmd_fallback(sizes, reason=reason)

    def train_step(state: TrainState, tokens, targets):
        gnorm = None
        if sync_plan is not None:
            if schedule in ("1f1b", "interleaved"):
                loss, grads, gnorm = pipeline_value_and_grad_1f1b(
                    state.params, tokens, targets, cfg, mesh,
                    num_microbatches, virtual=virtual,
                    sync_plan=sync_plan,
                )
            else:
                loss, grads, gnorm = pipeline_value_and_grad_gpipe_sync(
                    state.params, tokens, targets, cfg, mesh,
                    num_microbatches, sync_plan,
                )
        elif schedule in ("1f1b", "interleaved"):
            loss, grads = pipeline_value_and_grad_1f1b(
                state.params, tokens, targets, cfg, mesh,
                num_microbatches, virtual=virtual,
            )
        else:

            def lf(p):
                return pipeline_loss_fn(
                    p, tokens, targets, cfg, mesh, num_microbatches
                )

            loss, grads = jax.value_and_grad(lf)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(
                step=state.step + 1, params=new_params, opt_state=new_opt
            ),
            {
                "loss": loss,
                "grad_norm": (
                    gnorm
                    if gnorm is not None
                    else optax.global_norm(grads)
                ),
            },
        )

    donate_argnums = (0,) if donate else ()
    return jax.jit(train_step, donate_argnums=donate_argnums)
