"""Silent-data-corruption defense (ISSUE 20): the detection and
attribution layer in front of the repo's existing response primitives.

Every robustness path before this PR defends against failures that
*announce themselves* — crashes, torn bytes, dead heartbeats. A chip
that silently computes wrong-but-finite numbers corrupts weights for
thousands of steps before any of those fire; at fleet scale that is the
dominant undetected failure mode. Elastic-native systems treat
detect-plus-surgical-replacement as a first-class path (ElasWave
2510.00606; TorchTitan 2410.06511 couples loss-anomaly handling with
checkpoint rollback). This module supplies the three escalating tiers;
the trainer and master wire them to the response primitives that
already exist (``latest_verified_step`` rollback, rendezvous exclusion,
Brain ``node_events``, flight bundles):

- **Tier 1 — free fences** (:class:`SdcDetector`): the grad-sync
  bucket walk already computes per-bucket norms, so each device's
  LOCAL (pre-sync) grad norm rides the same shard_map out-spec at ~zero
  cost (``sync_grads(device_norms=True)``). A robust median+MAD window
  detector over the loss and the per-lane norm vector distinguishes a
  *data spike* (every lane moves together — skip-and-log, batch id
  recorded) from a *device suspect* (one lane diverges from its replica
  peers — escalate). NaN/Inf propagates into the lane norms, so the
  finite fence falls out of the same vector.
- **Tier 2 — paired audit probe** (:class:`AuditProbe`): on suspicion
  (or every ``DLROVER_TPU_SDC_AUDIT_STEPS`` steps) re-run a
  deterministic fixed-seed probe computation per device — the
  ``node_check`` matmul pattern lifted on-device — and vote with
  rotated pairings so each suspect is judged by two disjoint peers.
  Majority disagreement convicts a specific device; bitwise agreement
  clears it (a data spike that escalated by ambiguity is cleared here,
  never convicted).
- **Tier 3 — response** (trainer/master wiring, not this module):
  conviction rolls back to the latest verified checkpoint (replay
  booked to ``restart_replay``), quarantines the convicted host out of
  rendezvous, and ships a ``sdc_conviction`` node event with the vote
  matrix + norm history to the Brain.

Injection (``common/faults.py`` site ``device.sdc``, kind ``scale``)
makes the whole chain replayable: ``device.sdc:scale:@N:seed`` scales
ONE device's local gradient by a large *finite* factor from step ``N``
on (``seed % n_lanes`` picks the lane) — finite-but-wrong is the case
the detector must earn; a bit flip on f32 usually yields NaN, which the
cheap fence catches trivially. :func:`injection_plan` resolves the
armed spec once at step-build time; the probe applies the same plan to
the convicted lane's probe output, so the audit sees exactly what the
training step saw.
"""

from __future__ import annotations

import math
import os
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

ENV_ENABLED = "DLROVER_TPU_SDC"
ENV_AUDIT_STEPS = "DLROVER_TPU_SDC_AUDIT_STEPS"

_enabled_override: Optional[bool] = None


def set_enabled(on: bool):
    """Programmatic switch (the trainer's ``sdc_detect`` knob): wins
    over the env var. Must be set BEFORE the train step is built —
    ``build_train_step`` reads it at trace time to decide whether the
    per-lane norm vector rides the sync."""
    global _enabled_override
    _enabled_override = bool(on)


def enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    return os.getenv(ENV_ENABLED, "") not in ("", "0", "false")


def audit_steps_from_env(default: int = 0) -> int:
    raw = os.getenv(ENV_AUDIT_STEPS, "")
    if not raw:
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        logger.warning(f"bad {ENV_AUDIT_STEPS}={raw!r}; keeping {default}")
        return default


# ---------------------------------------------------------------------------
# injection plan (site device.sdc, kind scale)
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class InjectionPlan:
    """The realized ``device.sdc:scale`` fault: lane ``device`` scales
    its local gradient by ``factor`` from 1-based step ``from_step``
    on (sticky — a chip that goes bad stays bad until excluded)."""

    device: int
    factor: float
    from_step: int


def injection_plan(n_lanes: int) -> Optional[InjectionPlan]:
    """Resolve the armed ``device.sdc`` scale spec into a concrete
    plan, or None. Fully derived from the spec fields (no RNG stream),
    so the step builder, the audit probe and the bench all replay the
    SAME corruption: ``seed % n_lanes`` is the lane, ``@N`` is the
    onset step (default 1 = corrupt from the first step)."""
    from dlrover_tpu.common import faults

    if n_lanes <= 0:
        return None
    # touch the injector first: it performs the one-time env read, so a
    # DLROVER_TPU_FAULTS-armed spec is visible even when no other fault
    # point has fired yet in this process (faults.active() alone only
    # mirrors already-loaded state)
    inj = faults.injector()
    if not faults.active():
        return None
    for spec in inj.specs():
        if spec.site == "device.sdc" and spec.kind == "scale":
            return InjectionPlan(
                device=spec.seed % n_lanes,
                factor=faults.SCALE_FACTOR,
                from_step=spec.nth or 1,
            )
    return None


# ---------------------------------------------------------------------------
# tier 1: robust median+MAD window detector
# ---------------------------------------------------------------------------
@dataclass
class SdcConfig:
    # trailing window of CLEAN steps feeding the temporal baseline
    # (anomalous steps never enter it — a spike must not poison the
    # statistics that flagged it)
    window: int = 32
    # observations before the temporal (data-spike) test arms; the
    # cross-lane test needs no history and arms immediately
    min_history: int = 8
    # robust z (MAD-normalized) thresholds. 6 sigma on a MAD scale is
    # far outside healthy lane-to-lane spread (replica lanes see
    # different data shards, so their norms legitimately differ by
    # tens of percent — see rel_floor) but far below the injected
    # finite-corruption factor
    spike_sigma: float = 6.0
    suspect_sigma: float = 6.0
    # MAD floor as a fraction of the median: replica lanes computing
    # near-identical norms would otherwise make the z-score a
    # hair-trigger (MAD ~ 0 -> any jitter divides to infinity)
    rel_floor: float = 0.1
    # periodic tier-2 audit cadence in steps (0 = audit only on
    # suspicion); DLROVER_TPU_SDC_AUDIT_STEPS overrides
    audit_steps: int = 0


@dataclass
class SdcVerdict:
    kind: str  # "warming" | "ok" | "data_spike" | "device_suspect"
    step: int = 0
    suspects: Tuple[int, ...] = ()
    detail: str = ""
    zscores: Tuple[float, ...] = ()


def _median(xs: Sequence[float]) -> float:
    """Median of a small list. The detector runs EVERY step on a
    handful of floats — pure Python beats numpy by an order of
    magnitude at this size (no array boxing, no dispatch), which is
    what keeps the always-on fence under the tracer-overhead floor."""
    s = sorted(xs)
    n = len(s)
    m = n // 2
    return s[m] if n % 2 else 0.5 * (s[m - 1] + s[m])


def _robust_scale(
    dev: Sequence[float], center: float, rel_floor: float
) -> float:
    """1.4826*MAD with the relative + absolute floors applied."""
    mad = _median([abs(d) for d in dev])
    return max(1.4826 * mad, rel_floor * abs(center), 1e-12)


class SdcDetector:
    """The tier-1 fence: feed it one (loss, per-lane local grad norm)
    observation per step; it answers with a verdict. Host-side Python
    on a handful of floats — the steady-state cost is microseconds (the
    bench gates it under the tracer-overhead budget)."""

    def __init__(self, n_lanes: int, cfg: Optional[SdcConfig] = None):
        self.cfg = cfg or SdcConfig()
        self.n_lanes = int(n_lanes)
        self._loss_hist: List[float] = []
        self._med_hist: List[float] = []
        # trailing raw lane vectors (evidence for the flight bundle)
        self._lane_hist: List[List[float]] = []
        self._steps_seen = 0

    def reset(self):
        """Drop all history (post-rollback: the window described the
        corrupted trajectory)."""
        self._loss_hist.clear()
        self._med_hist.clear()
        self._lane_hist.clear()
        self._steps_seen = 0

    def history(self, last: int = 16) -> Dict:
        """Evidence payload for the flight bundle / Brain event."""
        return {
            "loss": [round(v, 6) for v in self._loss_hist[-last:]],
            "lane_norm_median": [
                round(v, 6) for v in self._med_hist[-last:]
            ],
            "lane_norms": [
                [round(v, 6) for v in row]
                for row in self._lane_hist[-last:]
            ],
        }

    def observe(
        self, step: int, loss: float, lane_norms: Sequence[float]
    ) -> SdcVerdict:
        cfg = self.cfg
        # one numpy touch to normalize the input (the trainer hands us a
        # device-fetched array), then pure Python: at this size the
        # array path costs 3-5x more per step than list arithmetic
        norms = (
            np.asarray(lane_norms, dtype=np.float64).reshape(-1).tolist()
        )
        n = len(norms)
        if n != self.n_lanes:
            self.n_lanes = n
        loss = float(loss)
        self._steps_seen += 1

        # -- finite fence (free: NaN/Inf propagated into the norms) ----
        bad_lanes = [
            i for i, v in enumerate(norms) if not math.isfinite(v)
        ]
        if bad_lanes or not math.isfinite(loss):
            if bad_lanes and len(bad_lanes) <= n // 2:
                return SdcVerdict(
                    kind="device_suspect",
                    step=step,
                    suspects=tuple(bad_lanes),
                    detail="non-finite lane norm",
                )
            # every lane blew up together (or only the loss did): the
            # batch, not a chip
            return SdcVerdict(
                kind="data_spike", step=step, detail="non-finite step"
            )

        med = _median(norms)
        verdict = SdcVerdict(kind="ok", step=step)

        # -- cross-lane test (device suspect): one lane vs its replica
        # peers THIS step — needs no history, so a chip bad from step 1
        # is still caught. A minority of lanes diverging is a device
        # signal; a majority moving together is the data
        if n >= 3:
            dev = [v - med for v in norms]
            scale = _robust_scale(dev, med, cfg.rel_floor)
            z = [abs(d) / scale for d in dev]
            outliers = [
                i for i, v in enumerate(z) if v > cfg.suspect_sigma
            ]
            if 0 < len(outliers) <= n // 2:
                verdict = SdcVerdict(
                    kind="device_suspect",
                    step=step,
                    suspects=tuple(outliers),
                    detail=(
                        f"lane z={[round(z[i], 1) for i in outliers]}"
                        f" vs peers (median {med:.4g})"
                    ),
                    zscores=tuple(round(v, 2) for v in z),
                )

        # -- temporal test (data spike): the whole step vs the clean
        # window — loss or the lane-median jumping while the lanes
        # agree with each other is a batch problem, not a chip
        if (
            verdict.kind == "ok"
            and len(self._med_hist) >= cfg.min_history
        ):
            lh, mh = self._loss_hist, self._med_hist
            lc, mc = _median(lh), _median(mh)
            z_loss = abs(loss - lc) / _robust_scale(
                [v - lc for v in lh], lc, cfg.rel_floor
            )
            z_med = abs(med - mc) / _robust_scale(
                [v - mc for v in mh], mc, cfg.rel_floor
            )
            if z_loss > cfg.spike_sigma or z_med > cfg.spike_sigma:
                verdict = SdcVerdict(
                    kind="data_spike",
                    step=step,
                    detail=(
                        f"loss z={z_loss:.1f} lane-median z={z_med:.1f}"
                        f" vs {len(self._med_hist)}-step window"
                    ),
                )

        if verdict.kind == "ok":
            self._loss_hist.append(loss)
            self._med_hist.append(med)
            self._lane_hist.append(norms)
            if len(self._med_hist) > cfg.window:
                del self._loss_hist[0]
                del self._med_hist[0]
                del self._lane_hist[0]
        elif self._steps_seen <= 2 and verdict.kind == "data_spike":
            # the first couple of steps have no meaningful baseline;
            # never mint a spike off them (cross-lane suspects stand —
            # they compare lanes to each other, not to history)
            verdict = SdcVerdict(kind="warming", step=step)
        return verdict


# ---------------------------------------------------------------------------
# tier 2: paired-device audit probe
# ---------------------------------------------------------------------------
@dataclass
class AuditResult:
    convicted: Tuple[int, ...]
    cleared: Tuple[int, ...]
    inconclusive: bool
    # lane -> [(peer, agreed), (peer, agreed)] — the rotated-pair vote
    # matrix (evidence riding the flight bundle + Brain event)
    votes: Dict[int, List[Tuple[int, bool]]] = field(default_factory=dict)
    digests: Tuple[str, ...] = ()


class AuditProbe:
    """Tier 2: a deterministic fixed-seed probe computation replayed on
    every device, judged by rotated paired voting.

    The probe is the ``node_check`` pattern lifted on-device: a chained
    per-round-normalized matmul on a seeded matrix, placed and executed
    on each device in turn, digested bitwise (crc32 of the result
    bytes). Deterministic inputs + deterministic kernels mean every
    healthy device produces the SAME bytes; a chip computing wrong
    numbers cannot.

    Voting mirrors ``NetworkCheckRendezvousManager.check_fault_node``'s
    two-round rotated pairing: lane ``i`` is compared against peers
    ``i+1`` and ``i+2`` (mod n) — two DISJOINT judges per suspect.
    Conviction requires BOTH peers to disagree with the suspect while
    agreeing with each other; one disagreeing pair alone cannot say
    which side is wrong. Fewer than 3 lanes is structurally
    inconclusive (no majority exists) — log, never convict.
    """

    def __init__(
        self,
        devices: Optional[Sequence] = None,
        size: int = 64,
        rounds: int = 2,
        seed: int = 1234,
    ):
        self._devices = list(devices) if devices is not None else None
        self.size = int(size)
        self.rounds = int(rounds)
        self.seed = int(seed)
        self._base: Optional[np.ndarray] = None

    def _probe_input(self) -> np.ndarray:
        if self._base is None:
            rng = np.random.default_rng(self.seed)
            self._base = rng.standard_normal(
                (self.size, self.size)
            ).astype(np.float32)
        return self._base

    def _digest(self, lane: int, device, step: int) -> int:
        import jax
        import jax.numpy as jnp

        a = jax.device_put(self._probe_input(), device)
        inv = jnp.float32(1.0 / self.size)
        for _ in range(self.rounds):
            # per-round normalized so the chain stays O(1) magnitude
            a = (a @ a.T) * inv
        out = np.asarray(jax.device_get(a))
        plan = injection_plan(self.n_lanes)
        if (
            plan is not None
            and plan.device == lane
            and step >= plan.from_step
        ):
            # the injected chip computes wrong numbers EVERYWHERE —
            # the probe must see the same corruption the train step saw
            out = out * np.float32(plan.factor)
        return zlib.crc32(out.tobytes())

    @property
    def n_lanes(self) -> int:
        return len(self.devices)

    @property
    def devices(self) -> List:
        if self._devices is None:
            import jax

            self._devices = list(jax.devices())
        return self._devices

    def run(
        self, step: int, suspects: Sequence[int] = ()
    ) -> AuditResult:
        devs = self.devices
        n = len(devs)
        digests = [self._digest(i, d, step) for i, d in enumerate(devs)]
        hexes = tuple(f"{d:08x}" for d in digests)
        if n < 3:
            logger.warning(
                f"sdc audit inconclusive: {n} lane(s) cannot form a "
                f"majority (suspects={list(suspects)})"
            )
            return AuditResult(
                convicted=(),
                cleared=(),
                inconclusive=True,
                digests=hexes,
            )
        votes: Dict[int, List[Tuple[int, bool]]] = {}
        convicted: List[int] = []
        cleared: List[int] = []
        for i in range(n):
            p1, p2 = (i + 1) % n, (i + 2) % n
            a1 = digests[i] == digests[p1]
            a2 = digests[i] == digests[p2]
            votes[i] = [(p1, a1), (p2, a2)]
            if not a1 and not a2 and digests[p1] == digests[p2]:
                convicted.append(i)
            else:
                cleared.append(i)
        if convicted:
            logger.error(
                f"sdc audit convicted lane(s) {convicted} at step "
                f"{step}: digests {list(hexes)}"
            )
        return AuditResult(
            convicted=tuple(convicted),
            cleared=tuple(cleared),
            inconclusive=False,
            votes=votes,
            digests=hexes,
        )
