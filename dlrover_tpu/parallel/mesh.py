"""Named-axis device mesh construction.

Parity: atorch ``create_parallel_group`` / ``init_distributed``
(atorch/atorch/distributed/distributed.py:321,588) — the reference builds
NCCL process groups per named dim ("tensor", "pipe", "data", …) with rank
reordering. On TPU the whole fabric is one ``jax.sharding.Mesh``: axis
order encodes which collectives ride fast ICI (innermost axes) vs DCN
(outermost, e.g. data-parallel across pod slices), and XLA/GSPMD derives
the groups from shardings — no NCCL analog needed.

Canonical axis names (any subset, sizes multiply to the device count):

- ``dp``    pure data parallel (params replicated)
- ``fsdp``  data parallel with param/optimizer sharding (ZeRO-3 analog)
- ``tp``    tensor (megatron row/col) parallel
- ``sp``    sequence/context parallel (ring attention)
- ``ep``    expert parallel (MoE all-to-all)
- ``pp``    pipeline stages
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes per named axis; unspecified axes default to 1 and are kept in
    the mesh (size-1 axes are free) so sharding rules never dangle."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1
    # axes whose communication crosses slices/hosts over DCN; they are laid
    # out outermost so ICI keeps the bandwidth-hungry collectives
    dcn_axes: Tuple[str, ...] = ()
    # number of DCN-connected slices the dp axis spans (requires "dp" in
    # dcn_axes and slices | dp). 1 keeps the historical semantics (an
    # axis in dcn_axes is *entirely* DCN); 1 < slices < dp makes dp a
    # HYBRID axis — dp factors as [slices (DCN, outermost), dp/slices
    # (ICI)], so each run of dp/slices consecutive dp coordinates is one
    # ICI-adjacent slice. grad_sync's two-level sync and the topology
    # cost model key off this factorization (dp_slices()).
    slices: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.ep * self.pp

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def dp_slices(self) -> int:
        """The dp axis's DCN slice count when it is a valid hybrid axis
        (dp = slices x per-slice-ICI-degree), else 1. slices == dp means
        every dp rank is its own slice — that is the whole-axis-DCN case
        with no ICI level, so it reports 1 (no two-level structure)."""
        if (
            "dp" in self.dcn_axes
            and 1 < self.slices < self.dp
            and self.dp % self.slices == 0
        ):
            return self.slices
        return 1

    @staticmethod
    def from_dict(d: Dict[str, int]) -> "MeshConfig":
        known = {k: v for k, v in d.items() if k in AXIS_ORDER}
        return MeshConfig(**known)


def build_mesh(
    config: MeshConfig,
    devices: Optional[Sequence] = None,
):
    """Build a ``jax.sharding.Mesh`` whose physical layout respects ICI
    topology (``mesh_utils.create_device_mesh``) with DCN axes outermost
    (``create_hybrid_device_mesh``) when requested."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    axis_names = tuple(AXIS_ORDER)
    sizes = tuple(getattr(config, a) for a in AXIS_ORDER)
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh {dict(zip(axis_names, sizes))} needs {n} devices, "
            f"have {len(devices)}"
        )
    if config.slices > 1 and (
        "dp" not in config.dcn_axes or config.dp % config.slices
    ):
        raise ValueError(
            f"slices={config.slices} needs 'dp' in dcn_axes "
            f"({config.dcn_axes}) and slices | dp (dp={config.dp})"
        )
    if config.dcn_axes:
        # per-axis (dcn_factor, ici_factor): an axis in dcn_axes is
        # entirely DCN, EXCEPT dp with slices>1, which is hybrid —
        # slices (DCN) x dp/slices (ICI), DCN factor outermost
        factors = {}
        for a in AXIS_ORDER:
            size = getattr(config, a)
            if a == "dp" and config.slices > 1:
                factors[a] = (config.slices, size // config.slices)
            elif a in config.dcn_axes:
                factors[a] = (size, 1)
            else:
                factors[a] = (1, size)
        dcn_sizes = tuple(factors[a][0] for a in AXIS_ORDER)
        ici_sizes = tuple(factors[a][1] for a in AXIS_ORDER)
        has_slice_meta = (
            getattr(list(devices)[0], "slice_index", None) is not None
        )
        if has_slice_meta:
            # real multi-slice hardware: a config/topology mismatch here
            # is a REAL error — emulating would silently route fsdp/tp
            # collectives over DCN
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_sizes,
                dcn_mesh_shape=dcn_sizes,
                devices=devices,
            )
        else:
            # CPU/virtual devices carry no slice metadata (slice_index);
            # emulate the hybrid layout — every DCN factor gets a LARGER
            # stride than every ICI factor (DCN outermost), so
            # consecutive devices ("one slice") stay adjacent on the ICI
            # factors, which is the property the hybrid mesh exists to
            # provide. Each final axis is its (dcn, ici) factor pair
            # collapsed dcn-major, so a hybrid dp axis enumerates
            # slice-major: coordinate d = slice * (dp/slices) + rank.
            arr = np.asarray(list(devices)).reshape(
                list(dcn_sizes) + list(ici_sizes)
            )
            n_ax = len(AXIS_ORDER)
            perm = []
            for i in range(n_ax):
                perm.extend([i, n_ax + i])
            dev_array = arr.transpose(perm).reshape(
                [d * i for d, i in zip(dcn_sizes, ici_sizes)]
            )
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                sizes, devices=list(devices)
            )
        except (ValueError, AssertionError):
            # CPU/virtual meshes have no physical topology metadata
            dev_array = np.asarray(list(devices)).reshape(sizes)
    return Mesh(dev_array, axis_names)


def data_axes() -> Tuple[str, ...]:
    """Mesh axes a global batch is sharded over."""
    return ("dp", "fsdp")


def batch_sharding(mesh):
    """Canonical input-batch sharding: batch dim over (dp, fsdp), sequence
    dim over sp (context parallel slices the sequence)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def process_axis_index(mesh, axis: str) -> int:
    """This process's coordinate along ``axis`` (for per-host data feeds):
    the coordinate of the first mesh device owned by this process."""
    import jax

    for idx, dev in np.ndenumerate(mesh.devices):
        if dev.process_index == jax.process_index():
            return idx[mesh.axis_names.index(axis)]
    return 0
