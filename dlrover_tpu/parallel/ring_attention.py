"""Ring attention: context parallelism over the ``sp`` mesh axis.

Parity: atorch ``DistributedSelfAttention``/``DistributedSoftmax``
(modules/distributed_transformer/distributed_attention.py:21,79) — the
reference shards KV over a sequence group, all-gathers micro-q chunks,
computes a cross-rank-stable softmax and reduce-scatters the context,
overlapping comm and compute on two CUDA streams.

The TPU-native design is a **ring**: every device keeps its own Q block
and passes KV blocks around the ``sp`` axis with ``lax.ppermute`` (one
ICI hop per step — no all-gather footprint), accumulating flash-attention
style online softmax in fp32. XLA overlaps the ``ppermute`` with the
block matmuls, which is the same comm/compute overlap the reference
hand-schedules with streams. Blockwise = native: each (q_block, kv_block)
product is one MXU-friendly matmul.

Used via ``shard_map`` with Q/K/V sharded [batch→(dp,fsdp), seq→sp,
heads→tp]; causal masking uses global positions so the result is exactly
single-device attention.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

MaskFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def _block_attn(q, k, v, mask, sm_scale):
    """One (q_block, kv_block) flash step; returns (scores_exp@v, rowmax,
    rowsum) in fp32. q:[B,Tq,H,D] k,v:[B,Tk,H,D] mask:[Tq,Tk] bool."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    s = s * sm_scale
    s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # rows with no visible keys: keep m finite so exp() stays 0, not NaN
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])  # [B,H,Tq,Tk]
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )  # fp32 accum
    return o, m_safe, l, jnp.isfinite(m)


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    mask_fn: Optional[MaskFn] = None,
):
    """Per-device body (call inside ``shard_map``).

    q/k/v: [B, T_local, H, D] — this device's sequence block. GQA is
    supported (H_kv may divide H). ``mask_fn(q_pos, k_pos)`` overrides the
    causal rule for custom masks (GLM-style, parity:
    modules/transformer/layers.py custom-mask kernels).
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)

    q_pos = my_idx * T + jnp.arange(T)

    def step(carry, j):
        o_acc, m_acc, l_acc, kv = carry
        k_blk, v_blk = kv
        blk_idx = (my_idx - j) % n
        k_pos = blk_idx * T + jnp.arange(T)
        if mask_fn is not None:
            mask = mask_fn(q_pos, k_pos)
        elif causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((T, T), dtype=bool)
        o, m, l, any_visible = _block_attn(q, k_blk, v_blk, mask, scale)
        # online-softmax merge of (o_acc,m_acc,l_acc) with (o,m,l)
        m_new = jnp.maximum(m_acc, jnp.where(any_visible, m, m_acc))
        alpha = jnp.exp(m_acc - m_new)  # rescale old
        beta = jnp.where(any_visible, jnp.exp(m - m_new), 0.0)
        l_new = l_acc * alpha + l * beta
        o_new = (
            o_acc * alpha.transpose(0, 2, 1)[..., None]
            + o * beta.transpose(0, 2, 1)[..., None]
        )
        # rotate KV one hop around the ring (overlapped by XLA with the
        # next block's matmuls)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, (k_nxt, v_nxt)), None

    o0 = jnp.zeros((B, T, H, D), jnp.float32)
    # start from a very negative (but finite) running max so the first
    # merge is exact and alpha=exp(m_acc - m_new) never produces NaN
    m0 = jnp.full((B, H, T), jnp.finfo(jnp.float32).min)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    (o, m, l, _), _ = lax.scan(
        step, (o0, m0, l0, (k, v)), jnp.arange(n)
    )
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(
    q, k, v, mesh, *, causal: bool = True, mask_fn: Optional[MaskFn] = None
):
    """Global-view wrapper: shards [B,S,H,D] over the mesh and runs the
    ring. Inputs may be any layout; outputs match q's sharding."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(("dp", "fsdp"), "sp", "tp", None)
    fn = functools.partial(
        ring_attention_local, causal=causal, mask_fn=mask_fn
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
