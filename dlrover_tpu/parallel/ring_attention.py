"""Ring attention: context parallelism over the ``sp`` mesh axis.

Parity: atorch ``DistributedSelfAttention``/``DistributedSoftmax``
(modules/distributed_transformer/distributed_attention.py:21,79) — the
reference shards KV over a sequence group, all-gathers micro-q chunks,
computes a cross-rank-stable softmax and reduce-scatters the context,
overlapping comm and compute on two CUDA streams.

The TPU-native design is a **ring**: every device keeps its own Q block
and passes KV blocks around the ``sp`` axis with ``lax.ppermute`` (one
ICI hop per step — no all-gather footprint), accumulating flash-attention
style online softmax in fp32. XLA overlaps the ``ppermute`` with the
block matmuls, which is the same comm/compute overlap the reference
hand-schedules with streams. Blockwise = native: each (q_block, kv_block)
product is one MXU-friendly matmul.

Used via ``shard_map`` with Q/K/V sharded [batch→(dp,fsdp), seq→sp,
heads→tp]; causal masking uses global positions so the result is exactly
single-device attention.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

MaskFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def _block_attn(q, k, v, mask, sm_scale):
    """One (q_block, kv_block) flash step; returns (scores_exp@v, rowmax,
    rowsum) in fp32. q:[B,Tq,H,D] k,v:[B,Tk,H,D] mask:[Tq,Tk] bool."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    s = s * sm_scale
    s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # rows with no visible keys: keep m finite so exp() stays 0, not NaN
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])  # [B,H,Tq,Tk]
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )  # fp32 accum
    return o, m_safe, l, jnp.isfinite(m)


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    mask_fn: Optional[MaskFn] = None,
):
    """Per-device body (call inside ``shard_map``).

    q/k/v: [B, T_local, H, D] — this device's sequence block. GQA is
    supported (H_kv may divide H). ``mask_fn(q_pos, k_pos)`` overrides the
    causal rule for custom masks (GLM-style, parity:
    modules/transformer/layers.py custom-mask kernels).
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = sm_scale if sm_scale is not None else 1.0 / (D**0.5)

    q_pos = my_idx * T + jnp.arange(T)

    def step(carry, j):
        o_acc, m_acc, l_acc, kv = carry
        k_blk, v_blk = kv
        blk_idx = (my_idx - j) % n
        k_pos = blk_idx * T + jnp.arange(T)
        if mask_fn is not None:
            mask = mask_fn(q_pos, k_pos)
        elif causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((T, T), dtype=bool)
        o, m, l, any_visible = _block_attn(q, k_blk, v_blk, mask, scale)
        # online-softmax merge of (o_acc,m_acc,l_acc) with (o,m,l)
        m_new = jnp.maximum(m_acc, jnp.where(any_visible, m, m_acc))
        alpha = jnp.exp(m_acc - m_new)  # rescale old
        beta = jnp.where(any_visible, jnp.exp(m - m_new), 0.0)
        l_new = l_acc * alpha + l * beta
        o_new = (
            o_acc * alpha.transpose(0, 2, 1)[..., None]
            + o * beta.transpose(0, 2, 1)[..., None]
        )
        # rotate KV one hop around the ring (overlapped by XLA with the
        # next block's matmuls)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, (k_nxt, v_nxt)), None

    o0 = jnp.zeros((B, T, H, D), jnp.float32)
    # start from a very negative (but finite) running max so the first
    # merge is exact and alpha=exp(m_acc - m_new) never produces NaN
    m0 = jnp.full((B, H, T), jnp.finfo(jnp.float32).min)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    (o, m, l, _), _ = lax.scan(
        step, (o0, m0, l0, (k, v)), jnp.arange(n)
    )
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# kernel-backed ring: flash-attention Pallas kernel per KV hop
# ---------------------------------------------------------------------------
def _merge_partials(o_a, lse_a, o_b, lse_b):
    """Online-softmax merge — the shared helper in ops.flash_attention
    (one algebra for ring hops AND chunked single-device attention)."""
    from dlrover_tpu.ops.flash_attention import merge_partials

    return merge_partials(o_a, lse_a, o_b, lse_b)


def _ring_fwd_scan(q, k, v, axis_name, causal, sm_scale, mask_fn):
    from dlrover_tpu.ops.flash_attention import NEG_INF, flash_attention_fwd

    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, j):
        o_acc, lse_acc, kv = carry
        k_blk, v_blk = kv
        blk_idx = (my_idx - j) % n
        o_j, lse_j = flash_attention_fwd(
            q,
            k_blk,
            v_blk,
            causal=causal,
            sm_scale=sm_scale,
            mask_fn=mask_fn,
            q_offset=my_idx * T,
            k_offset=blk_idx * T,
        )
        o_new, lse_new = _merge_partials(
            o_acc, lse_acc, o_j.astype(jnp.float32), lse_j
        )
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, lse_new, (k_nxt, v_nxt)), None

    o0 = jnp.zeros((B, T, H, D), jnp.float32)
    lse0 = jnp.full((B, H, T), NEG_INF, jnp.float32)
    (o, lse, _), _ = lax.scan(step, (o0, lse0, (k, v)), jnp.arange(n))
    return o.astype(q.dtype), lse


def _make_ring_flash(axis_name, causal, sm_scale, mask_fn):
    """Build the custom-vjp kernel ring for one static config.

    Forward: one flash kernel call per KV hop, partials merged with the
    online-softmax rule. Backward: a second ring pass — ``dq``
    accumulates locally; ``dk``/``dv`` partials travel *with* their KV
    block (rotated by the same ppermute), so after n hops each device
    holds the complete gradient of its own KV shard. The kernel's
    ``p = exp(s - lse_global)`` recomputation makes every per-hop
    contribution exact.
    """
    from dlrover_tpu.ops.flash_attention import flash_attention_bwd

    @jax.custom_vjp
    def ring_flash(q, k, v):
        o, _ = _ring_fwd_scan(
            q, k, v, axis_name, causal, sm_scale, mask_fn
        )
        return o

    def fwd(q, k, v):
        o, lse = _ring_fwd_scan(
            q, k, v, axis_name, causal, sm_scale, mask_fn
        )
        return o, (q, k, v, o, lse)

    def bwd(res, do):
        q, k, v, o, lse = res
        n = lax.psum(1, axis_name)
        my_idx = lax.axis_index(axis_name)
        T = q.shape[1]
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, j):
            dq_acc, kv, dkv = carry
            k_blk, v_blk = kv
            dk_acc, dv_acc = dkv
            blk_idx = (my_idx - j) % n
            dq_j, dk_j, dv_j = flash_attention_bwd(
                q,
                k_blk,
                v_blk,
                o,
                lse,
                do,
                causal=causal,
                sm_scale=sm_scale,
                mask_fn=mask_fn,
                q_offset=my_idx * T,
                k_offset=blk_idx * T,
            )
            dq_acc = dq_acc + dq_j.astype(jnp.float32)
            dk_acc = dk_acc + dk_j.astype(jnp.float32)
            dv_acc = dv_acc + dv_j.astype(jnp.float32)
            # dk/dv ride along with their kv block around the ring
            k_nxt = lax.ppermute(k_blk, axis_name, perm)
            v_nxt = lax.ppermute(v_blk, axis_name, perm)
            dk_nxt = lax.ppermute(dk_acc, axis_name, perm)
            dv_nxt = lax.ppermute(dv_acc, axis_name, perm)
            return (dq_acc, (k_nxt, v_nxt), (dk_nxt, dv_nxt)), None

        dq0 = jnp.zeros(q.shape, jnp.float32)
        dkv0 = (
            jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32),
        )
        (dq, _, (dk, dv)), _ = lax.scan(
            step, (dq0, (k, v), dkv0), jnp.arange(n)
        )
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    ring_flash.defvjp(fwd, bwd)
    return ring_flash


def ring_flash_attention_local(
    q,
    k,
    v,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    mask_fn: Optional[MaskFn] = None,
):
    """Kernel-backed per-device ring body (call inside ``shard_map``).

    Same contract as ``ring_attention_local`` but each hop's block math
    runs in the Pallas flash-attention kernel (ops/flash_attention.py);
    GQA KV stays unexpanded all the way through the ring (H_kv heads on
    the wire instead of H).
    """
    # built per call: the custom_vjp wrapper is cheap to construct, and
    # callers jit the enclosing step, so trace caching happens above us
    # (an identity-keyed cache here would leak mask_fn closures)
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    fn = _make_ring_flash(axis_name, causal, scale, mask_fn)
    return fn(q, k, v)


def ring_self_attention(
    q,
    k,
    v,
    mesh,
    *,
    causal: bool = True,
    mask_fn: Optional[MaskFn] = None,
    use_kernel: Optional[bool] = None,
):
    """Global-view wrapper: shards [B,S,H,D] over the mesh and runs the
    ring. Inputs may be any layout; outputs match q's sharding.

    ``use_kernel=None`` auto-picks the Pallas-kernel ring on TPU and the
    jnp ring elsewhere (kernels run under the slow interpreter off-TPU).
    """
    from dlrover_tpu.common.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"
    spec = P(("dp", "fsdp"), "sp", "tp", None)
    if use_kernel:
        fn = functools.partial(
            ring_flash_attention_local, causal=causal, mask_fn=mask_fn
        )
    else:
        fn = functools.partial(
            ring_attention_local, causal=causal, mask_fn=mask_fn
        )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
