"""TPU-native parallelism layer.

Replaces the reference's process-group fabric (atorch
``create_parallel_group`` distributed.py:321, megatron-style TP modules
layers.py:239-670, sequence-parallel distributed_attention.py:21, MoE
moe_layer.py:87) with a **mesh + GSPMD sharding** design: one
``jax.sharding.Mesh`` with named axes, a rule library that annotates the
pytree, and XLA inserting the collectives. Explicit collectives appear only
where the algorithm requires them (ring attention ``ppermute``, MoE
``all_to_all``) inside ``shard_map``.
"""

from dlrover_tpu.parallel.grad_sync import (  # noqa: F401
    BucketPlan,
    plan_buckets,
)
from dlrover_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
)
from dlrover_tpu.parallel.sharding_rules import (  # noqa: F401
    ShardingRules,
    apply_rules,
    logical_to_mesh_axes,
)
# NOTE: pipeline/ring_attention/moe are imported as submodules
# (dlrover_tpu.parallel.pipeline etc.) — they depend on dlrover_tpu.models,
# which itself imports this package, so re-exporting them here would cycle.
