"""Cross-host coworker data plane.

Parity: atorch feeds preprocessed batches from dedicated coworker
PODS over gRPC into the training hosts' shared memory
(atorch/atorch/distributed/distributed.py:489 ``_build_grpc_networks``,
atorch/atorch/data/shm_context.py:139,527, coworker_dataset.py). The
TPU translation keeps the same shape with two pieces:

- ``DataNodeServer`` runs on a CPU-rich data node: local coworker
  processes (the intra-node ``ShmDataFeeder``) preprocess batches, and
  a TCP server hands them to whichever trainer host asks next — the
  pull protocol load-balances and back-pressures for free, and a batch
  is handed out exactly once (global round-robin across trainer hosts
  = dynamic sharding, consistent with the master's batch-level
  dispatch model).
- ``RemoteBatchFeeder`` runs on each trainer host: fetcher processes
  pull batches over TCP and drain them into the SAME local shm ring
  the intra-node feeder uses, so the training loop's consumption path
  is identical whether batches are produced on-host or across DCN.

Discovery is master-mediated: data nodes register
``data_node/<name> -> host:port`` in the master KV store
(master/kv_store.py) and trainers look the addresses up — no extra
service, and the master's failover snapshot carries the registry.

The wire format is pickle-free (length-prefixed JSON tree spec + raw
array bytes): the network boundary has the same trust model as
``common/comm.py``'s restricted unpickler — a compromised peer must
not get arbitrary-object deserialization.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

_LEN = struct.Struct("<Q")
_GET = b"GET\n"
KV_PREFIX = "data_node/"


# ---------------------------------------------------------------------------
# pickle-free batch wire format
# ---------------------------------------------------------------------------
def _encode_tree(obj: Any, arrays: List[np.ndarray]):
    """Batch pytree -> JSON-able spec; arrays collected by position."""
    if isinstance(obj, dict):
        return {
            "t": "dict",
            "k": list(obj.keys()),
            "v": [_encode_tree(obj[k], arrays) for k in obj],
        }
    if isinstance(obj, (list, tuple)):
        return {
            "t": "list" if isinstance(obj, list) else "tuple",
            "v": [_encode_tree(x, arrays) for x in obj],
        }
    if isinstance(obj, (np.ndarray, np.generic)):
        arr = np.asarray(obj)
        # reshape back: ascontiguousarray promotes 0-d to (1,)
        arrays.append(np.ascontiguousarray(arr).reshape(arr.shape))
        # dtype/shape live ONLY in the header's arrays list (one
        # source of truth for decoding)
        return {"t": "arr", "i": len(arrays) - 1}
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return {"t": "val", "v": obj}
    raise TypeError(
        f"unsupported leaf {type(obj).__name__} in batch (numpy arrays, "
        f"scalars and dict/list/tuple nesting only — the wire format is "
        f"deliberately pickle-free)"
    )


def _decode_tree(spec: Any, arrays: List[np.ndarray]):
    t = spec["t"]
    if t == "dict":
        return {
            k: _decode_tree(v, arrays)
            for k, v in zip(spec["k"], spec["v"])
        }
    if t in ("list", "tuple"):
        out = [_decode_tree(v, arrays) for v in spec["v"]]
        return out if t == "list" else tuple(out)
    if t == "arr":
        return arrays[spec["i"]]
    return spec["v"]


def encode_batch(batch: Any) -> bytes:
    arrays: List[np.ndarray] = []
    spec = _encode_tree(batch, arrays)
    header = json.dumps(
        {
            "spec": spec,
            "arrays": [
                {"d": a.dtype.str, "s": list(a.shape)} for a in arrays
            ],
        }
    ).encode()
    parts = [_LEN.pack(len(header)), header]
    for a in arrays:
        parts.append(a.tobytes())
    return b"".join(parts)


def decode_batch(payload: bytes) -> Any:
    (hlen,) = _LEN.unpack_from(payload, 0)
    header = json.loads(payload[_LEN.size : _LEN.size + hlen])
    off = _LEN.size + hlen
    arrays = []
    for meta in header["arrays"]:
        # the peer is untrusted (the whole wire format is pickle-free
        # for that reason) — header fields get the same skepticism: a
        # negative dim makes np.prod negative and frombuffer(count=-1)
        # consume the rest of the payload, silently desyncing every
        # later array into garbage instead of a loud error
        dt = np.dtype(meta["d"])
        if dt.hasobject:
            raise ValueError(
                "batch header declares an object dtype (arbitrary-"
                "object deserialization is exactly what this format "
                "forbids)"
            )
        shape = tuple(meta["s"])
        if any(
            not isinstance(d, int) or isinstance(d, bool) or d < 0
            for d in shape
        ):
            raise ValueError(f"batch header has invalid dims {shape!r}")
        count = int(np.prod(shape))  # () -> 1, any 0-dim -> 0
        if off + count * dt.itemsize > len(payload):
            raise ValueError(
                f"batch header declares {count * dt.itemsize} bytes at "
                f"offset {off} but the payload holds {len(payload)}"
            )
        arrays.append(
            np.frombuffer(payload, dt, count=count, offset=off)
            .reshape(shape)
            .copy()
        )
        off += count * dt.itemsize
    return _decode_tree(header["spec"], arrays)


# ---------------------------------------------------------------------------
# data-node server
# ---------------------------------------------------------------------------
def _default_advertise_host() -> str:
    try:
        import socket as _s

        host = _s.gethostbyname(_s.gethostname())
        if not host.startswith("127."):
            return host
    except OSError:
        pass
    logger.warning(
        "data node advertising loopback (no resolvable host address; "
        "set DLROVER_TPU_NODE_IP for cross-host discovery)"
    )
    return "127.0.0.1"


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class DataNodeServer:
    """Serve batches from ``source`` (any iterator of batch pytrees —
    typically a local ``ShmDataFeeder`` whose coworker processes do the
    preprocessing) to trainer hosts over TCP.

    Each ``GET`` pops the next batch under a lock: N trainer hosts
    pulling concurrently partition the stream without coordination.
    After exhaustion every GET answers a 0-length frame (end of
    stream)."""

    def __init__(
        self,
        source: Iterator[Any],
        host: str = "0.0.0.0",
        port: int = 0,
        name: str = "data0",
        master_client=None,
        advertise_host: Optional[str] = None,
    ):
        self._source = iter(source)
        self._lock = threading.Lock()
        self._done = False
        # batches popped-but-undelivered (trainer died mid-send) are
        # requeued here so a surviving trainer gets them
        self._retry: List[bytes] = []
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self.name = name
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"datanode-{name}"
        )
        self._accept_thread.start()
        if master_client is not None:
            self.register(master_client, advertise_host)

    def register(
        self, master_client, advertise_host: Optional[str] = None
    ):
        """Publish ``data_node/<name> -> host:port`` in the master KV
        store so trainers can discover this node. The advertised host
        must be reachable from the TRAINER hosts: explicit argument,
        then ``DLROVER_TPU_NODE_IP``, then this host's resolved
        address (loopback only as a last resort)."""
        import os

        host = (
            advertise_host
            or os.getenv("DLROVER_TPU_NODE_IP")
            or _default_advertise_host()
        )
        master_client.kv_store_set(
            KV_PREFIX + self.name, f"{host}:{self.port}".encode()
        )

    def _next_payload(self) -> bytes:
        with self._lock:
            if self._retry:
                return self._retry.pop()
            if self._done:
                return b""
            try:
                batch = next(self._source)
            except StopIteration:
                self._done = True
                return b""
        return encode_batch(batch)

    def _serve_conn(self, conn: socket.socket):
        payload = None
        try:
            with conn:
                while not self._stop.is_set():
                    req = _recv_exact(conn, len(_GET))
                    if req != _GET:
                        logger.warning(
                            f"data node {self.name}: bad request {req!r}"
                        )
                        return
                    try:
                        payload = self._next_payload()
                    except TypeError as e:
                        # encode_batch rejected an unsupported leaf: the
                        # popped batch is unsendable (and lost), so log
                        # the cause server-side and close the stream
                        # with the 0-length EOF frame — the client sees
                        # a deliberate protocol end, not an abrupt reset
                        # it would misread as a network failure
                        logger.error(
                            f"data node {self.name}: batch not "
                            f"encodable ({e}); ending this stream with "
                            f"EOF"
                        )
                        conn.sendall(_LEN.pack(0))
                        return
                    conn.sendall(_LEN.pack(len(payload)) + payload)
                    if not payload:
                        return
                    payload = None  # delivered
        except (ConnectionError, OSError):
            # trainer went away mid-delivery: requeue the popped batch
            # for a surviving trainer (redelivery is safe — the dead
            # trainer never consumed it)
            if payload:
                with self._lock:
                    self._retry.append(payload)
                logger.warning(
                    f"data node {self.name}: trainer dropped mid-send; "
                    f"requeued its batch"
                )
        finally:
            self._conns.discard(conn)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self._conns.add(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            # reap finished connection threads as new ones arrive
            self._threads = [
                th for th in self._threads if th.is_alive()
            ] + [t]

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # unblock threads parked in _recv_exact on idle connections
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# trainer-side remote feeder
# ---------------------------------------------------------------------------
def _pull_stream(worker_id: int, addrs: List[str]) -> Iterator[Any]:
    """Coworker-process body: pull batches from this worker's data node
    until end-of-stream. Runs inside a ``ShmDataFeeder`` worker process,
    so decode + network wait never touch the trainer's GIL.

    A timeout or connection failure RAISES (after a log line) instead of
    ending the stream: the feeder's liveness poll then reports the dead
    fetcher loudly, rather than silently truncating the epoch."""
    import os

    timeout = float(os.getenv("DLROVER_TPU_FEED_TIMEOUT", "600"))
    addr = addrs[worker_id % len(addrs)]
    host, port = addr.rsplit(":", 1)
    conn = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        while True:
            conn.sendall(_GET)
            try:
                (n,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
                if n == 0:
                    return
                yield decode_batch(_recv_exact(conn, n))
            except (socket.timeout, ConnectionError, OSError) as e:
                logger.error(
                    f"remote feed fetcher {worker_id}: data node "
                    f"{addr} failed mid-stream ({e!r}); aborting so the "
                    f"truncation is loud, not silent"
                )
                raise
    finally:
        conn.close()


def discover_data_nodes(
    master_client, names: Optional[List[str]] = None,
    timeout: float = 60.0,
) -> List[str]:
    """Resolve registered data-node addresses from the master KV store.
    With ``names`` given, waits for exactly those registrations."""
    import time as _time

    if names is None:
        names = ["data0"]
    deadline = _time.time() + timeout
    addrs = []
    for name in names:
        while True:
            raw = master_client.kv_store_get(KV_PREFIX + name)
            if raw:
                addrs.append(raw.decode())
                break
            if _time.time() > deadline:
                raise TimeoutError(
                    f"data node {name!r} never registered in master KV"
                )
            _time.sleep(0.3)
    return addrs


class RemoteBatchFeeder:
    """Trainer-host facade: fetcher processes pull from ``addrs`` and
    drain into the local shm ring; iterate it like the intra-node
    ``ShmDataFeeder`` (same consumption path, ref shm_context.py:527).
    """

    def __init__(
        self,
        addrs: List[str],
        fetchers_per_node: int = 1,
        slot_bytes: int = 16 << 20,
        slots_per_worker: int = 2,
        name: str = "",
    ):
        import functools

        from dlrover_tpu.data.shm_feed import ShmDataFeeder

        self._feeder = ShmDataFeeder(
            functools.partial(_pull_stream, addrs=list(addrs)),
            num_workers=max(1, len(addrs) * fetchers_per_node),
            slot_bytes=slot_bytes,
            slots_per_worker=slots_per_worker,
            name=name,
        )

    def __iter__(self):
        return iter(self._feeder)

    def close(self):
        self._feeder.close()
