"""Memory-mapped token corpus — the pretraining data path.

Parity: the reference's trainer datasets read pre-tokenized corpora
(dlrover/trainer elastic dataset utilities; the llama2 example feeds
tokenized files). The TPU-host-friendly layout is one flat binary file
of token ids opened with ``np.memmap``: zero parse cost, O(1) random
access by window index (what the ElasticDistributedSampler shards and
resumes over), and the OS page cache does the staging.

Layout: little-endian unsigned ids, dtype inferred from a tiny JSON
header sidecar (``<path>.meta.json``) written by ``write_tokens`` —
uint16 for vocabularies < 65536 (GPT-2's 50257 fits), uint32 otherwise.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np


def write_tokens(path: str, tokens: np.ndarray) -> str:
    """Persist a 1-D token array as ``<path>`` + ``<path>.meta.json``.
    Returns ``path``. (The tokenizer step of a data pipeline.)"""
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise ValueError(f"tokens must be 1-D, got shape {tokens.shape}")
    if tokens.size and int(tokens.min()) < 0:
        raise ValueError("token ids must be non-negative")
    dtype = np.uint16 if (tokens.size == 0 or int(tokens.max()) < 65536) else np.uint32
    # meta FIRST and atomically: a reader (or crash) between the two
    # replaces must never pair new data with a stale dtype — decoding
    # uint16 bytes as uint32 is silent garbage. Meta-then-data means the
    # worst interleaving is old data read with new meta, which fails
    # loudly (size mismatch) instead of silently.
    meta = {"dtype": np.dtype(dtype).name, "count": int(tokens.size)}
    mtmp = f"{path}.meta.json.tmp.{os.getpid()}"
    with open(mtmp, "w") as f:
        json.dump(meta, f)
    os.replace(mtmp, f"{path}.meta.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    tokens.astype(dtype).tofile(tmp)
    os.replace(tmp, path)
    return path


class MemmapTokenDataset:
    """Fixed-length next-token windows over a memmapped token file.

    Items are ``{"x": [seq_len] int32, "y": [seq_len] int32}`` with
    ``y`` the one-step-shifted continuation — directly consumable by
    ``ElasticTrainer`` (and shardable/resumable through its sampler).

    ``stride`` defaults to ``seq_len`` (disjoint windows, one epoch =
    one pass over the corpus); smaller strides oversample boundaries.
    """

    def __init__(
        self,
        path: str,
        seq_len: int,
        stride: Optional[int] = None,
        dtype: Optional[str] = None,
    ):
        self.seq_len = seq_len
        self.stride = stride or seq_len
        if self.stride <= 0 or seq_len <= 0:
            raise ValueError("seq_len and stride must be positive")
        count = None
        if dtype is None:
            try:
                with open(f"{path}.meta.json") as f:
                    meta = json.load(f)
                dtype = meta["dtype"]
                count = meta.get("count")
            except (OSError, ValueError, KeyError):
                dtype = "uint16"  # the GPT-2-vocab default layout
        self._data = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        if count is not None and len(self._data) != count:
            # meta/data skew (caught mid-rewrite): decoding with the
            # wrong dtype would be silent garbage — fail loudly instead
            raise ValueError(
                f"{path}: meta says {count} tokens but the file decodes "
                f"to {len(self._data)} as {dtype} — corpus mid-rewrite "
                "or dtype mismatch"
            )
        # each item needs seq_len + 1 tokens (x and the shifted y)
        usable = len(self._data) - (seq_len + 1)
        self._n = 0 if usable < 0 else usable // self.stride + 1
        if self._n == 0:
            raise ValueError(
                f"{path}: {len(self._data)} tokens < seq_len+1="
                f"{seq_len + 1}"
            )

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> Dict[str, np.ndarray]:
        if not 0 <= i < self._n:
            raise IndexError(i)
        start = i * self.stride
        window = np.asarray(
            self._data[start : start + self.seq_len + 1], dtype=np.int32
        )
        return {"x": window[:-1], "y": window[1:]}
