"""Memory-mapped token corpus — the pretraining data path.

Parity: the reference's trainer datasets read pre-tokenized corpora
(dlrover/trainer elastic dataset utilities; the llama2 example feeds
tokenized files). The TPU-host-friendly layout is one flat binary file
of token ids opened with ``np.memmap``: zero parse cost, O(1) random
access by window index (what the ElasticDistributedSampler shards and
resumes over), and the OS page cache does the staging.

Layout: ``<path>.meta.json`` names the generation-suffixed data file it
belongs to (``data_file``) plus dtype/count — the meta replace is the
atomic commit point, and every reader pairs a meta with exactly the
data file it names, so a rewrite can never hand a reader mismatched
dtype/bytes. Plain headerless files (nanoGPT-style ``.bin`` with no
meta) open too, defaulting to uint16.
"""

from __future__ import annotations

import json
import os
import re
import secrets
from typing import Dict, Optional

import numpy as np

from dlrover_tpu.common import storage


def write_tokens(path: str, tokens: np.ndarray) -> str:
    """Persist a 1-D token array as ``<path>.g<nonce>`` +
    ``<path>.meta.json`` (the atomic commit), GC'ing older generations.
    Returns ``path``. (The tokenizer step of a data pipeline.)"""
    tokens = np.asarray(tokens)
    if tokens.ndim != 1:
        raise ValueError(f"tokens must be 1-D, got shape {tokens.shape}")
    if tokens.size and int(tokens.min()) < 0:
        raise ValueError("token ids must be non-negative")
    dtype = (
        np.uint16
        if (tokens.size == 0 or int(tokens.max()) < 65536)
        else np.uint32
    )
    gen = f"{os.path.basename(path)}.g{secrets.token_hex(4)}"
    data_path = os.path.join(os.path.dirname(path) or ".", gen)
    # a materialized dataset claims durability: fsync data before the
    # meta commit below, or a crash can commit a generation whose token
    # bytes never hit the platter (graftlint durable-rename)
    storage.durable_replace(
        data_path, lambda f: tokens.astype(dtype).tofile(f), mode="wb"
    )
    meta = {
        "dtype": np.dtype(dtype).name,
        "count": int(tokens.size),
        "data_file": gen,
    }
    storage.durable_replace(
        f"{path}.meta.json", lambda f: json.dump(meta, f)
    )  # the commit point
    _gc_generations(path)
    return path


_GEN_RE = re.compile(r"\.g[0-9a-f]{8}$")


def _gc_generations(path: str) -> None:
    """Best-effort GC of superseded generations. Keeps whatever the
    CURRENT meta names (re-read after our commit — if a concurrent
    writer won the race, its generation is the one spared, never
    deleted), matches ONLY the exact ``.g<8 hex>`` suffix (a sibling
    ``corpus.bin.gz`` is not a generation), and never touches tmp
    files. Concurrent writers are tolerated; one writer per corpus is
    still the intended discipline."""
    base = os.path.basename(path)
    dirname = os.path.dirname(path) or "."
    try:
        with open(f"{path}.meta.json") as f:
            keep = {json.load(f)["data_file"]}
    except (OSError, ValueError, KeyError):
        return  # cannot tell what is live: delete nothing
    for name in os.listdir(dirname):
        if (
            name.startswith(f"{base}.g")
            and _GEN_RE.search(name)
            and name not in keep
        ):
            try:
                os.unlink(os.path.join(dirname, name))
            except OSError:
                pass


class MemmapTokenDataset:
    """Fixed-length next-token windows over a memmapped token file.

    Items are ``{"x": [seq_len] int32, "y": [seq_len] int32}`` with
    ``y`` the one-step-shifted continuation — directly consumable by
    ``ElasticTrainer`` (and shardable/resumable through its sampler).

    ``stride`` defaults to ``seq_len`` (disjoint windows, one epoch =
    one pass over the corpus); smaller strides oversample boundaries.
    """

    def __init__(
        self,
        path: str,
        seq_len: int,
        stride: Optional[int] = None,
        dtype: Optional[str] = None,
    ):
        self.seq_len = seq_len
        self.stride = stride or seq_len
        if self.stride <= 0 or seq_len <= 0:
            raise ValueError("seq_len and stride must be positive")
        dtype_override = dtype
        # one retry: a concurrent rewrite can GC the generation between
        # our meta read and the memmap open — re-reading the meta then
        # names the NEW generation
        for attempt in (0, 1):
            data_path, count, dtype = path, None, dtype_override
            try:
                with open(f"{path}.meta.json") as f:
                    meta = json.load(f)
                # explicit dtype= overrides the meta's (and disables the
                # count check, whose unit is meta-dtype tokens), but the
                # generation the meta names is still the data location
                if dtype is None:
                    dtype = meta["dtype"]
                    count = meta.get("count")
                if "data_file" in meta:
                    data_path = os.path.join(
                        os.path.dirname(path) or ".", meta["data_file"]
                    )
            except FileNotFoundError:
                # headerless corpus (e.g. a nanoGPT .bin): GPT-2-vocab
                # uint16 is the conventional layout
                dtype = dtype or "uint16"
            except (OSError, ValueError, KeyError) as e:
                # a PRESENT but unreadable meta must fail loudly — a
                # uint16 fallback would silently decode garbage
                raise ValueError(
                    f"{path}.meta.json exists but is unreadable: {e!r}"
                ) from e
            try:
                self._data = np.memmap(
                    data_path, dtype=np.dtype(dtype), mode="r"
                )
                break
            except FileNotFoundError:
                if attempt:
                    raise
        if count is not None and len(self._data) != count:
            raise ValueError(
                f"{data_path}: meta says {count} tokens but the file "
                f"decodes to {len(self._data)} as {dtype}"
            )
        # each item needs seq_len + 1 tokens (x and the shifted y)
        usable = len(self._data) - (seq_len + 1)
        self._n = 0 if usable < 0 else usable // self.stride + 1
        if self._n == 0:
            raise ValueError(
                f"{data_path}: {len(self._data)} tokens < seq_len+1="
                f"{seq_len + 1}"
            )

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> Dict[str, np.ndarray]:
        if not 0 <= i < self._n:
            raise IndexError(i)
        start = i * self.stride
        window = np.asarray(
            self._data[start : start + self.seq_len + 1], dtype=np.int32
        )
        return {"x": window[:-1], "y": window[1:]}
