"""Coworker shared-memory batch feed.

Parity: atorch ``ShmDataContext`` (atorch/atorch/data/shm_context.py:139,
527) — "coworker" processes preprocess batches on spare host cores and
hand them to the training process through shared memory, so tokenization
/augmentation never steals time from the accelerator step. The reference
moves torch tensors over gRPC or shm; here batches are numpy pytrees in
a ring of POSIX shm slots (the same tracker-free ``SharedMemory`` flash
checkpoint uses) with two ``SharedQueue``s as ready/free lists —
single-writer protocols end to end, no locks in the hot path.

On TPU hosts this is the input half of the standard recipe: coworkers
fill batches → trainer turns them into device arrays
(``shard_batch`` / ``make_array_from_process_local_data``) while the
previous step is still running on the chip.
"""

from __future__ import annotations

import multiprocessing
import os
import pickle
import struct
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import (
    SharedMemory,
    SharedQueue,
    create_shared_memory,
)

_HEADER = struct.Struct("<Q")  # payload byte length


class StopSentinel:
    """Returned by ``ShmBatchReader.get`` when a worker's stream ended
    (a plain tuple could collide with a user batch)."""

    def __init__(self, worker_id: int):
        self.worker_id = worker_id


def _flatten(batch: Any) -> bytes:
    """Batch pytree (dicts/tuples of numpy arrays) → bytes. Arrays are
    serialized with np.save semantics via pickle protocol 5 out-of-band
    free; plain pickle is fine here because both ends are our own
    processes (the restricted unpickler guards the *network* boundary,
    not host-local shm between a parent and its children)."""
    return pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)


def _unflatten(payload: bytes) -> Any:
    return pickle.loads(payload)


class ShmBatchWriter:
    """Producer side: owns nothing; leases slots from the free queue."""

    def __init__(self, name: str, slot_bytes: int):
        self._slot_bytes = slot_bytes
        self._free = SharedQueue(f"{name}_free")
        self._ready = SharedQueue(f"{name}_ready")
        self._segments: Dict[int, SharedMemory] = {}
        self._name = name

    def _segment(self, slot: int) -> SharedMemory:
        if slot not in self._segments:
            self._segments[slot] = SharedMemory(f"{self._name}_slot{slot}")
        return self._segments[slot]

    def put(self, batch: Any, timeout: float = 60.0):
        payload = _flatten(batch)
        need = _HEADER.size + len(payload)
        if need > self._slot_bytes:
            raise ValueError(
                f"batch needs {need} bytes > slot size {self._slot_bytes}"
            )
        slot = self._free.get(timeout=timeout)
        seg = self._segment(slot)
        seg.buf[: _HEADER.size] = _HEADER.pack(len(payload))
        seg.buf[_HEADER.size : need] = payload
        self._ready.put(slot)

    def close(self):
        for seg in self._segments.values():
            seg.close()
        self._free.close()
        self._ready.close()


class ShmBatchReader:
    """Consumer side: creates the ring (K slots + queues), yields
    batches, recycles slots."""

    # stop sentinels are negative and identify the worker: -(wid+1).
    # Anonymous STOPs would double-count a worker that both posted its
    # STOP (finally:) and exited nonzero (seen by the liveness poll).
    @staticmethod
    def stop_token(worker_id: int) -> int:
        return -(worker_id + 1)

    def __init__(self, name: str, slot_bytes: int, num_slots: int = 4):
        self._name = name
        self._slot_bytes = slot_bytes
        self._free = SharedQueue(f"{name}_free", create=True)
        self._ready = SharedQueue(f"{name}_ready", create=True)
        self._segments: List[SharedMemory] = []
        for slot in range(num_slots):
            # create_shared_memory tolerates a stale same-name segment
            # from a crashed previous run (tracker-free shm outlives its
            # creator by design)
            seg = create_shared_memory(
                f"{name}_slot{slot}", size=slot_bytes
            )
            if seg is None:
                raise OSError(f"cannot create shm {name}_slot{slot}")
            self._segments.append(seg)
            self._free.put(slot)

    def get(self, timeout: float = 60.0):
        """Next batch, or a ``StopSentinel`` when a worker finished."""
        slot = self._ready.get(timeout=timeout)
        if slot < 0:
            return StopSentinel(-slot - 1)
        seg = self._segments[slot]
        (n,) = _HEADER.unpack(bytes(seg.buf[: _HEADER.size]))
        batch = _unflatten(bytes(seg.buf[_HEADER.size : _HEADER.size + n]))
        self._free.put(slot)  # recycle AFTER the copy out of shm
        return batch

    def close(self):
        for seg in self._segments:
            seg.close()
            seg.unlink()
        self._free.close()
        self._ready.close()


def _worker_main(
    name: str,
    slot_bytes: int,
    produce_fn: Callable[[int], Iterator[Any]],
    worker_id: int,
):
    import queue as _queue

    writer = ShmBatchWriter(name, slot_bytes)
    clean = False
    try:
        for batch in produce_fn(worker_id):
            while True:
                try:
                    writer.put(batch)
                    break
                except _queue.Empty:
                    # all slots leased while the trainer stalls (XLA
                    # compile routinely exceeds the lease timeout on the
                    # first step) — keep waiting, don't die
                    logger.info(
                        f"shm feed worker {worker_id}: ring full, "
                        f"trainer busy; retrying"
                    )
        clean = True
    finally:
        # STOP only on clean exhaustion: a producer that DIED (network
        # fetch failure, crash) must be reported by the reader's
        # liveness poll as a dead worker, not read as a finished stream
        # — silent epoch truncation is the failure mode this guards
        if clean:
            writer._ready.put(ShmBatchReader.stop_token(worker_id))
        writer.close()


class ShmDataFeeder:
    """Trainer-facing facade: spawn N coworker processes running
    ``produce_fn(worker_id) -> iterator of batches``; iterate batches in
    the training loop. The iterator ends when every coworker's stream
    is exhausted."""

    def __init__(
        self,
        produce_fn: Callable[[int], Iterator[Any]],
        num_workers: int = 1,
        slot_bytes: int = 16 << 20,
        slots_per_worker: int = 2,
        name: str = "",
    ):
        self._name = name or f"shmfeed_{os.getpid()}_{id(self):x}"
        self._reader = ShmBatchReader(
            self._name,
            slot_bytes,
            num_slots=max(2, slots_per_worker * num_workers),
        )
        # spawn, not fork: the trainer process carries jax/XLA threads,
        # and forking a multi-threaded process can deadlock the child
        ctx = multiprocessing.get_context("spawn")
        self._procs: List = []
        for w in range(num_workers):
            p = ctx.Process(
                target=_worker_main,
                args=(self._name, slot_bytes, produce_fn, w),
                daemon=True,
            )
            p.start()
            self._procs.append(p)

    def __iter__(self) -> Iterator[Any]:
        # generator-local liveness (re-iterating must not silently yield
        # an empty epoch); workers that die WITHOUT posting STOP (OOM
        # kill, SIGKILL — the chaos this framework exists for) are
        # detected by polling exit codes instead of hanging forever
        import queue as _queue

        finished: set = set()  # stop-posted OR observed dead, deduped
        while len(finished) < len(self._procs):
            try:
                batch = self._reader.get(timeout=5.0)
            except _queue.Empty:
                for i, p in enumerate(self._procs):
                    if i not in finished and p.exitcode not in (None, 0):
                        logger.warning(
                            f"shm feed worker {i} died "
                            f"(exitcode {p.exitcode}); its remaining "
                            f"batches are lost"
                        )
                        finished.add(i)
                if all(p.exitcode is not None for p in self._procs):
                    # every worker exited and the queue has been dry for
                    # a full timeout: nothing more is coming (covers
                    # re-iterating an already-drained single-pass feeder)
                    return
                continue
            if isinstance(batch, StopSentinel):
                finished.add(batch.worker_id)
                continue
            yield batch

    def close(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=5)
        self._reader.close()
