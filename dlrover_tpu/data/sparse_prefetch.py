"""Overlapped sparse-row pipeline: fault-in for step N+1 rides the host
link while step N computes.

This is the PR-1 ``DevicePrefetcher`` shape applied to embedding rows
instead of batches: the producer thread pulls ``(ids, batch)`` N+1 from
the source iterator, dedups the ids and calls
``DeviceSparseEmbedding.prepare`` — the host-tier gather of missing
rows (the slow leg: C++ hash probes, possibly a disk fault-in, then the
H2D dispatch) — concurrently with the train thread's compute of step N.
By the time the consumer asks for step N+1, every unique id is already
device-resident and the step's gather is a pure HBM Pallas kernel.

The other half of the overlap is the scatter-back: LRU spills leave the
device as async D2H handoffs to ``DeviceSparseEmbedding``'s drain
thread, so neither direction of the host link ever sits on the step's
critical path. Both directions are priced through the PR-6 ``LinkModel``
host leg (``stats.host_leg_s``), which is how the dry-runner and the
Brain see the pipeline's real cost instead of a hidden constant.

Error/exhaustion semantics match ``DevicePrefetcher``: every prepared
step before a failure is delivered first, then the original exception
re-raises from ``__next__``; ``close()`` is idempotent and never blocks
on a wedged source.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Iterator, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.obs.trace import span

# buffer entry kinds: ("step", ids, batch, prep) | ("err", exc) | ("end",)


class SparseRowPipeline:
    """Wrap an ``(ids, batch)`` iterator with a depth-``depth`` buffer
    of prepared steps (unique ids deduped and device-resident).

    ``depth=2`` is classic double buffering: one step computing, one
    being faulted in.
    """

    def __init__(
        self,
        source: Iterator[Tuple[np.ndarray, Any]],
        embedding,
        depth: int = 2,
    ):
        self._src = iter(source)
        self._emb = embedding
        self._depth = max(1, int(depth))
        self._cond = threading.Condition()
        self._buf: deque = deque()
        self._closed = False
        self.prepared_steps = 0
        self.prepare_wait_s = 0.0  # consumer stalls on an unready prep
        self.prepare_waits = 0
        self._thread = threading.Thread(
            target=self._produce, daemon=True, name="sparse-row-prefetch"
        )
        self._thread.start()

    # -- producer ------------------------------------------------------
    def _produce(self):
        while True:
            with self._cond:
                while not self._closed and len(self._buf) >= self._depth:
                    self._cond.wait()
                if self._closed:
                    return
            try:
                ids, batch = next(self._src)
            except StopIteration:
                entry = ("end",)
            except BaseException as e:  # noqa: BLE001 — must propagate
                entry = ("err", e)
            else:
                try:
                    # the overlap: host gather + H2D for step N+1 runs
                    # here while the consumer computes step N (the C++
                    # gather and numpy legs release the GIL)
                    with span("emb_fault_in"):
                        prep = self._emb.prepare(ids)
                    entry = ("step", ids, batch, prep)
                except BaseException as e:  # noqa: BLE001
                    entry = ("err", e)
            with self._cond:
                if self._closed:
                    # close() raced this prepare: the consumer will
                    # never see it, so its pins go back here
                    if entry[0] == "step":
                        self._release(entry[3])
                    return
                self._buf.append(entry)
                self.prepared_steps += entry[0] == "step"
                self._cond.notify_all()
                if entry[0] in ("end", "err"):
                    return

    # -- consumer ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        with self._cond:
            waited = None
            if not self._buf:
                t0 = time.perf_counter()
                while not self._buf:
                    if self._closed:
                        raise RuntimeError(
                            "SparseRowPipeline is closed"
                        )
                    self._cond.wait()
                waited = time.perf_counter() - t0
            head = self._buf[0]
            if head[0] == "end":
                raise StopIteration
            if head[0] == "err":
                # terminal: the same error on every retry
                raise head[1]
            if waited is not None:
                self.prepare_wait_s += waited
                self.prepare_waits += 1
            self._buf.popleft()
            self._cond.notify_all()
            return head[1], head[2], head[3]

    def buffered_steps(self) -> int:
        with self._cond:
            return sum(1 for e in self._buf if e[0] == "step")

    def _release(self, prep):
        try:
            self._emb.release(prep)
        except Exception:  # teardown must not raise past close()
            pass

    def close(self):
        """Stop the producer and drop the buffer — RELEASING the pins
        of every undelivered prepared step (a consumer that breaks out
        of the loop early, or an exception mid-step, must not leave
        un-evictable ghost-pinned slots behind). Safe to call twice; a
        producer wedged in a blocking source read is a daemon thread
        and cannot stall the caller's teardown."""
        with self._cond:
            self._closed = True
            dropped = [e for e in self._buf if e[0] == "step"]
            self._buf.clear()
            self._cond.notify_all()
        for entry in dropped:
            self._release(entry[3])
        self._thread.join(timeout=1.0)
        if self.prepare_waits:
            logger.info(
                f"sparse pipeline: {self.prepare_waits} consumer "
                f"stalls, {self.prepare_wait_s * 1e3:.1f} ms total "
                f"(raise depth or the HBM budget if this is hot)"
            )
