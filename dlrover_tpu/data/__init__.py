"""Host-side data plane (parity: atorch data/ — shm coworker feeds,
elastic datasets)."""

from dlrover_tpu.data.prefetch import (  # noqa: F401
    DevicePrefetcher,
    sharded_placement,
)
from dlrover_tpu.data.shm_feed import (  # noqa: F401
    ShmBatchReader,
    ShmBatchWriter,
    ShmDataFeeder,
)
from dlrover_tpu.data.token_dataset import (  # noqa: F401
    MemmapTokenDataset,
    write_tokens,
)
