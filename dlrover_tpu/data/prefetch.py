"""Double-buffered device prefetcher: overlap input transfer with compute.

The train loop's input path was fully serial: pull the next host batch
from the feed iterator (shm ring / remote feed / token dataset), turn it
into device arrays, then run the step. Both host legs ride the critical
path even though ``jax.device_put`` dispatches asynchronously and the
feed iterator's cost is pure host work. ``DevicePrefetcher`` moves both
off the step cadence (the TorchTitan/ATorch input-pipelining recipe):

- a producer thread pulls batch N+1 from the source iterator and issues
  its device placement while batch N computes on the chip;
- placement is pluggable, so the sharded-batch path
  (``make_array_from_process_local_data`` over the live mesh) composes
  with pjit exactly like the synchronous path did — see
  ``sharded_placement``;
- the buffer survives elastic resizes: ``reprime(new_placement)`` drops
  the *device* copies but keeps the buffered host batches and re-places
  them under the new mesh, so a world change costs a re-transfer, never
  lost samples;
- exhaustion and producer exceptions propagate to the consumer in
  order: every batch yielded before the failure is delivered first,
  then the original exception is re-raised from ``__next__``.

Stats land in an ``accel.profiler.PipelineStats`` record (hits = the
batch was already device-placed when the step asked for it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Optional

from dlrover_tpu.accel.profiler import PipelineStats
from dlrover_tpu.common import faults
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.obs.trace import span

# buffer entry kinds: ("batch", host, device) | ("perr", host, exc)
# (placement failed; host kept so reprime can retry) | ("err", exc)
# (source raised) | ("end",)


def _default_placement(batch: Any):
    import jax

    return jax.device_put(batch)


def sharded_placement(mesh) -> Callable[[Any], Any]:
    """Placement fn for the mesh/pjit path: every array leaf becomes a
    global ``jax.Array`` sharded like a training batch (same layout as
    ``models.train.shard_batch``). Build a fresh one after an elastic
    resize and hand it to ``reprime``."""
    import jax
    import numpy as np

    from dlrover_tpu.parallel.mesh import batch_sharding

    sharding = batch_sharding(mesh)

    def place(batch: Any):
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            ),
            batch,
        )

    return place


class DevicePrefetcher:
    """Wrap any batch iterator with a depth-``depth`` device-side buffer.

    Iterate it exactly like the source; batches come back device-placed
    (whatever ``placement`` returns). ``depth=2`` is classic double
    buffering: one batch computing, one in flight.
    """

    def __init__(
        self,
        source: Iterator[Any],
        placement: Optional[Callable[[Any], Any]] = None,
        depth: int = 2,
        stats: Optional[PipelineStats] = None,
    ):
        self._src = iter(source)
        self._place = placement or _default_placement
        self._depth = max(1, int(depth))
        self.stats = stats if stats is not None else PipelineStats()
        self._cond = threading.Condition()
        self._buf: deque = deque()
        self._gen = 0  # bumped by reprime; in-flight placements re-check
        self._closed = False
        self._producer_done = False
        # True while the producer is inside next(self._src): the source
        # cursor may have advanced for a batch not yet in the buffer
        self._pulling = False
        self._thread = threading.Thread(
            target=self._produce, daemon=True, name="device-prefetch"
        )
        self._thread.start()

    # -- producer ------------------------------------------------------
    def _entry(self, host: Any, place: Callable[[Any], Any]):
        try:
            # the h2d span rides the producer thread: a trace shows the
            # placement overlapping the consumer's compute span
            with span("h2d"):
                return ("batch", host, place(host))
        except Exception as e:  # placement failure: host batch survives
            return ("perr", host, e)

    def _produce(self):
        while True:
            with self._cond:
                while not self._closed and len(self._buf) >= self._depth:
                    self._cond.wait()
                if self._closed:
                    return
                gen, place = self._gen, self._place
                self._pulling = True
            # the slow legs (source pull + device placement dispatch)
            # run OUTSIDE the lock so the consumer never blocks on them
            pull_sp = span("prefetch_pull")
            try:
                # fault point prefetch.pull: an injected OSError rides the
                # normal producer-error path — delivered to the consumer
                # in order, after every batch pulled before it
                faults.fire("prefetch.pull")
                host = next(self._src)
            except StopIteration:
                pull_sp.end()
                entry = ("end",)
            except BaseException as e:  # noqa: BLE001 — must propagate
                pull_sp.end()
                entry = ("err", e)
            else:
                pull_sp.end()
                entry = self._entry(host, place)
            with self._cond:
                self._pulling = False
                if entry[0] in ("batch", "perr") and self._gen != gen:
                    # a reprime raced this placement: the device copy
                    # targets the old world — re-place under the new one
                    entry = self._entry(entry[1], self._place)
                self._buf.append(entry)
                self._cond.notify_all()
                if entry[0] in ("end", "err"):
                    self._producer_done = True
                    return

    # -- consumer ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        with self._cond:
            waited = None
            if not self._buf:
                t0 = time.perf_counter()
                while not self._buf:
                    if self._closed:
                        raise RuntimeError("DevicePrefetcher is closed")
                    self._cond.wait()
                waited = time.perf_counter() - t0
            head = self._buf[0]
            kind = head[0]
            if kind == "batch":
                # hit/miss counts batch deliveries only — the final
                # wait for the end sentinel is not a pipeline stall
                if waited is None:
                    self.stats.prefetch_hits += 1
                else:
                    self.stats.prefetch_misses += 1
                    self.stats.prefetch_wait_s += waited
            if kind == "end":
                # leave the sentinel: repeated next() keeps raising
                raise StopIteration
            if kind == "err":
                # source failure is terminal — keep it at the head so
                # the caller sees the SAME error on every retry
                raise head[1]
            if kind == "perr":
                # placement failure is retryable: reprime() re-places
                # the kept host batch (elastic resize recovery)
                raise head[2]
            self._buf.popleft()
            self._cond.notify_all()  # wake the producer to top up
            return head[2]

    def buffered_batches(self) -> int:
        """Batches pulled from the source but not yet consumed. A
        checkpointing train loop rewinds its sampler snapshot by this
        count — the source's cursor ran ahead of what actually
        trained. A pull in flight counts as one: the source may have
        advanced for it already (if it hadn't yet, the over-rewind
        repeats one batch, the safe direction)."""
        with self._cond:
            return (1 if self._pulling else 0) + sum(
                1 for e in self._buf if e[0] in ("batch", "perr")
            )

    # -- elasticity ----------------------------------------------------
    def reprime(
        self, placement: Optional[Callable[[Any], Any]] = None
    ) -> int:
        """World changed: drop every buffered *device* copy and re-place
        the kept host batches under ``placement`` (or the existing one).
        No sample is lost — order is preserved. Returns the number of
        batches re-placed."""
        with self._cond:
            if placement is not None:
                self._place = placement
            self._gen += 1
            place = self._place
            n = 0
            rebuilt: deque = deque()
            for entry in self._buf:
                if entry[0] in ("batch", "perr"):
                    rebuilt.append(self._entry(entry[1], place))
                    n += 1
                else:
                    rebuilt.append(entry)
            self._buf = rebuilt
            self.stats.prefetch_reprimes += 1
            self._cond.notify_all()
        if n:
            logger.info(
                f"prefetcher reprimed: {n} buffered batches re-placed "
                f"for the new world"
            )
        return n

    def close(self):
        """Stop the producer and drop the buffer. Safe to call twice.
        The producer thread is a daemon, so a source blocked in a
        network read cannot wedge interpreter exit."""
        with self._cond:
            self._closed = True
            self._buf.clear()
            self._cond.notify_all()
        # short join: a producer wedged in a blocking source read is a
        # daemon thread and must not stall the caller's teardown
        self._thread.join(timeout=1.0)
