"""Node health-check workload: timed matmul + cross-host collective.

Parity: dlrover/trainer/torch/node_check/nvidia_gpu.py:26 and utils.py:59-90
— the reference times a bf16 matmul plus 10 rounds of a 16M-element
allgather over NCCL; slow/failed nodes are bisected by the master's paired
rendezvous. The TPU version exercises the same two failure surfaces:

- **chip compute**: a jitted bf16 matmul big enough to hit the MXU;
- **ICI/DCN path**: a jitted ``psum`` across every process of the paired
  group (XLA collective over the real interconnect when multi-host).

Fault injection for tests mirrors ``MOCK_ERR_RANK`` (utils.py:50):
``DLROVER_TPU_MOCK_ERR_RANK=<process_id>`` makes that rank raise.
"""

from __future__ import annotations

import json
import os
import sys
import time


def write_result(elapsed: float, path: str = ""):
    path = path or os.getenv("DLROVER_TPU_CHECK_RESULT_FILE", "")
    if not path:
        return
    local_rank = os.getenv("DLROVER_TPU_LOCAL_RANK", "0")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(f"{path}.{local_rank}", "w") as f:
        json.dump({"elapsed": elapsed}, f)


def matmul_rounds(rounds: int = 3, size: int = 1024):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def mm(a):
        return a @ a

    a = jnp.ones((size, size), dtype=jnp.bfloat16)
    mm(a).block_until_ready()  # compile outside the timed region
    t0 = time.monotonic()
    for _ in range(rounds):
        a = mm(a)
    a.block_until_ready()
    return time.monotonic() - t0


def collective_rounds(ctx, rounds: int = 10, elems: int = 1 << 20):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    n = jax.device_count()
    local = np.ones(
        (elems // n * jax.local_device_count(),), np.float32
    )
    x = jax.make_array_from_process_local_data(sharding, local)

    @jax.jit
    def allreduce(v):
        return jnp.sum(v) * jnp.ones_like(v)

    allreduce(x).block_until_ready()
    t0 = time.monotonic()
    for _ in range(rounds):
        x = allreduce(x)
    x.block_until_ready()
    return time.monotonic() - t0


def main() -> int:
    from dlrover_tpu.trainer.elastic.distributed import init_elastic

    ctx = init_elastic()
    mock_err = os.getenv("DLROVER_TPU_MOCK_ERR_RANK", "")
    if mock_err and int(mock_err) == ctx.process_id:
        raise RuntimeError(f"mock error on rank {ctx.process_id}")
    t = matmul_rounds()
    if ctx.is_distributed:
        t += collective_rounds(ctx)
    mock_slow = os.getenv("DLROVER_TPU_MOCK_SLOW_RANK", "")
    if mock_slow and int(mock_slow) == ctx.process_id:
        time.sleep(2.0)
        t += 2.0
    write_result(t)
    return 0


if __name__ == "__main__":
    sys.exit(main())
