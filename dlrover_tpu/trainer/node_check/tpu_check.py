"""Node health-check workload: timed matmul + cross-host collective.

Parity: dlrover/trainer/torch/node_check/nvidia_gpu.py:26 and utils.py:59-90
— the reference times a bf16 matmul plus 10 rounds of a 16M-element
allgather over NCCL; slow/failed nodes are bisected by the master's paired
rendezvous. The TPU version exercises the same two failure surfaces:

- **chip compute**: a jitted bf16 matmul big enough to hit the MXU;
- **ICI/DCN path**: a jitted ``psum`` across every process of the paired
  group (XLA collective over the real interconnect when multi-host).

Fault injection for tests mirrors ``MOCK_ERR_RANK`` (utils.py:50):
``DLROVER_TPU_MOCK_ERR_RANK=<process_id>`` makes that rank raise.
"""

from __future__ import annotations

import json
import os
import sys
import time


def write_result(elapsed: float, path: str = ""):
    path = path or os.getenv("DLROVER_TPU_CHECK_RESULT_FILE", "")
    if not path:
        return
    local_rank = os.getenv("DLROVER_TPU_LOCAL_RANK", "0")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(f"{path}.{local_rank}", "w") as f:
        json.dump({"elapsed": elapsed}, f)


def _workload_scale():
    """(matmul_size, matmul_rounds, collective_elems, collective_rounds).

    On an accelerator the load must *sustain* the MXU and the interconnect
    long enough that a degraded chip/link separates from healthy noise —
    the reference's check is 10 rounds of a 16M-element allgather plus a
    matmul (node_check/utils.py:59-90), not a one-shot kernel. 8192^2 bf16
    matmuls (~1.1 TFLOP each) x 30 chained rounds ≈ tens of TFLOPs of MXU
    time; 16M fp32 elements x 10 chained collectives ≈ 640 MB moved.
    On CPU (tests, smoke runs) the same shapes would dominate the suite,
    so they drop to token sizes. Env overrides for either case:
    DLROVER_TPU_CHECK_{MM_SIZE,MM_ROUNDS,COLL_ELEMS,COLL_ROUNDS}.
    """
    import jax

    on_accel = jax.devices()[0].platform != "cpu"
    mm_size = 8192 if on_accel else 256
    mm_rounds = 30 if on_accel else 3
    elems = (1 << 24) if on_accel else (1 << 16)
    coll_rounds = 10 if on_accel else 3
    mm_size = int(os.getenv("DLROVER_TPU_CHECK_MM_SIZE", mm_size))
    mm_rounds = int(os.getenv("DLROVER_TPU_CHECK_MM_ROUNDS", mm_rounds))
    elems = int(os.getenv("DLROVER_TPU_CHECK_COLL_ELEMS", elems))
    coll_rounds = int(os.getenv("DLROVER_TPU_CHECK_COLL_ROUNDS", coll_rounds))
    return mm_size, mm_rounds, elems, coll_rounds


def matmul_rounds(rounds: int, size: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def mm(a):
        # normalize so chained rounds stay ~1.0 (bf16 ones would hit inf
        # after two rounds; keep the MXU on real numbers)
        return (a @ a) * jnp.bfloat16(1.0 / size)

    a = jnp.ones((size, size), dtype=jnp.bfloat16)
    b = mm(a)  # compile outside the timed region
    float(jnp.sum(b))
    t0 = time.monotonic()
    for _ in range(rounds):
        a = mm(a)
    # fetch a scalar that depends on the whole chain: on tunneled
    # runtimes block_until_ready can return before execution finishes,
    # which would time dispatch instead of the MXU (bench.py hit the
    # same artifact)
    float(jnp.sum(a))
    return time.monotonic() - t0


def collective_rounds(ctx, rounds: int, elems: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    n = jax.device_count()
    local = np.ones(
        (elems // n * jax.local_device_count(),), np.float32
    )
    x = jax.make_array_from_process_local_data(sharding, local)

    @jax.jit
    def allreduce(v):
        return jnp.sum(v) / v.size * jnp.ones_like(v)

    allreduce(x).block_until_ready()
    t0 = time.monotonic()
    for _ in range(rounds):
        x = allreduce(x)
    x.block_until_ready()
    # force local completion of the chained collectives (see
    # matmul_rounds: block_until_ready alone can return early)
    np.asarray(x.addressable_shards[0].data[:1])
    return time.monotonic() - t0


def main() -> int:
    from dlrover_tpu.trainer.elastic.distributed import init_elastic

    ctx = init_elastic()
    mock_err = os.getenv("DLROVER_TPU_MOCK_ERR_RANK", "")
    if mock_err and int(mock_err) == ctx.process_id:
        raise RuntimeError(f"mock error on rank {ctx.process_id}")
    mm_size, mm_rounds, elems, coll_rounds = _workload_scale()
    t = matmul_rounds(mm_rounds, mm_size)
    if ctx.is_distributed:
        t += collective_rounds(ctx, coll_rounds, elems)
    mock_slow = os.getenv("DLROVER_TPU_MOCK_SLOW_RANK", "")
    if mock_slow and int(mock_slow) == ctx.process_id:
        time.sleep(2.0)
        t += 2.0
    write_result(t)
    return 0


if __name__ == "__main__":
    sys.exit(main())
