"""SparseTrainer: elastic sparse (embedding/recommender) training.

Parity: the reference's TF-PS path — EstimatorExecutor + PS failover
(dlrover/trainer/tensorflow/executor/estimator_executor.py:52,
failover/tensorflow_failover.py:33) over TFPlus KvVariable embeddings.
The TPU shape replaces the parameter-server fleet with the host-side
``ShardedKvEmbedding`` store (C++; ops/embedding): the DENSE model
trains on the chip under jit, the SPARSE embedding rows live in host
memory with fused native optimizers, and elasticity means

- checkpoint = dense pytree (flash ckpt) + embedding export (npz);
- failover = watch the master's PS cluster version; on a bump (a
  reshard happened elsewhere, or we are a restarted worker) re-import
  the embedding state before continuing — the analog of the reference's
  relaunch-aware session refresh (tensorflow_failover.py:91).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.embedding import ShardedKvEmbedding


class SparseTrainer:
    """Embedding-store-backed training loop with elastic checkpointing.

    ``dense_step(dense_params, rows, batch) ->
    (dense_params, row_grads, metrics)`` is the user's jitted dense
    computation; the trainer owns the gather → step → fused-sparse-update
    cycle, checkpoints, and cluster-version failover.
    """

    def __init__(
        self,
        embedding: ShardedKvEmbedding,
        dense_params: Any,
        dense_step: Callable,
        ckpt_dir: str = "",
        sparse_optimizer: str = "adagrad",
        sparse_lr: float = 0.05,
        master_client=None,
    ):
        self.embedding = embedding
        self.dense_params = dense_params
        self._dense_step = dense_step
        self._ckpt_dir = ckpt_dir
        self._opt = sparse_optimizer
        self._lr = sparse_lr
        self._client = master_client
        self._cluster_version = (
            master_client.get_cluster_version() if master_client else 0
        )
        self.step = 0

    # -- sparse update dispatch ----------------------------------------
    def _apply_sparse(self, keys, grads):
        if self._opt == "adagrad":
            self.embedding.sparse_adagrad(keys, grads, lr=self._lr)
        elif self._opt == "adam":
            self.embedding.sparse_adam(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "momentum":
            self.embedding.sparse_momentum(keys, grads, lr=self._lr)
        elif self._opt == "group_ftrl":
            self.embedding.sparse_group_ftrl(keys, grads, alpha=self._lr)
        elif self._opt == "group_adam":
            self.embedding.sparse_group_adam(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "lamb":
            self.embedding.sparse_lamb(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "adabelief":
            self.embedding.sparse_adabelief(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "amsgrad":
            self.embedding.sparse_amsgrad(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        else:
            raise ValueError(f"unknown sparse optimizer {self._opt!r}")

    # -- failover -------------------------------------------------------
    def check_failover(self) -> bool:
        """True if the PS cluster version moved and state was reloaded
        (parity: ps_addresses_changed → session refresh)."""
        if self._client is None:
            return False
        version = self._client.get_cluster_version()
        if version == self._cluster_version:
            return False
        logger.warning(
            f"embedding cluster version {self._cluster_version} -> "
            f"{version}: reloading sparse state"
        )
        self._cluster_version = version
        self.restore_embedding()
        return True

    # -- train loop -----------------------------------------------------
    def train_step(self, ids: np.ndarray, batch: Any) -> Dict:
        """One cycle: gather rows → dense step on device → fused sparse
        update on host."""
        rows = self.embedding.gather(ids)
        self.dense_params, row_grads, metrics = self._dense_step(
            self.dense_params, rows, batch
        )
        self._apply_sparse(ids, np.asarray(row_grads))
        self.step += 1
        return metrics

    # -- checkpoint -----------------------------------------------------
    def _emb_path(self) -> str:
        return os.path.join(self._ckpt_dir, "embedding_state.npz")

    def save_embedding(self):
        if not self._ckpt_dir:
            return
        os.makedirs(self._ckpt_dir, exist_ok=True)
        state = self.embedding.export_state()
        # np.savez appends .npz to names without it — keep the suffix on
        # the temp file so the atomic rename targets what was written
        tmp = self._emb_path().replace(".npz", f".tmp{os.getpid()}.npz")
        np.savez(tmp, step=self.step, **state)
        os.replace(tmp, self._emb_path())
        logger.info(
            f"saved embedding state ({len(state['keys'])} rows) at "
            f"step {self.step}"
        )

    def restore_embedding(self) -> bool:
        path = self._emb_path()
        if not os.path.exists(path):
            return False
        data = dict(np.load(path))
        self.step = int(data.pop("step", 0))
        self.embedding.import_state(data)
        logger.info(
            f"restored embedding state ({len(data['keys'])} rows) at "
            f"step {self.step}"
        )
        return True
