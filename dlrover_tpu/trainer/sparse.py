"""SparseTrainer: elastic sparse (embedding/recommender) training.

Parity: the reference's TF-PS path — EstimatorExecutor + PS failover
(dlrover/trainer/tensorflow/executor/estimator_executor.py:52,
failover/tensorflow_failover.py:33) over TFPlus KvVariable embeddings.
The TPU shape replaces the parameter-server fleet with the host-side
``ShardedKvEmbedding`` store (C++; ops/embedding): the DENSE model
trains on the chip under jit, the SPARSE embedding rows live in host
memory with fused native optimizers, and elasticity means

- checkpoint = dense pytree + embedding export (npz, crc-verified with
  rollback to the previous good file — a torn export must never
  restore silently);
- failover = watch the master's PS cluster version; on a bump (a
  reshard happened elsewhere, or we are a restarted worker) refresh
  the embedding state before continuing — the analog of the
  reference's relaunch-aware session refresh
  (tensorflow_failover.py:91). With a reshard target the refresh is a
  WARM id-range redistribution (move only re-routed rows) instead of
  a full npz re-import; either way the window is booked to the goodput
  ledger (``restart_replay``) instead of vanishing from the wall-time
  closure.

Two train cycles:

- **host cycle** (``train_step``): host gather → device dense step →
  host fused sparse update — every row crosses the host link every
  step (the full fused-optimizer family is available);
- **device cycle** (``train_step_device`` / ``run(overlapped=True)``):
  the embedding is a :class:`DeviceSparseEmbedding` — gathers are HBM
  Pallas kernels, the sparse update runs on device, and with the
  :class:`SparseRowPipeline` the host link only carries fault-ins for
  step N+1 (overlapping step N's compute) and async spill-backs.
"""

from __future__ import annotations

import io
import os
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common import faults
from dlrover_tpu.common.storage import fsync_dir
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.embedding import ShardedKvEmbedding
from dlrover_tpu.ops.embedding.device_tier import DeviceSparseEmbedding


def _book_replay(t0_ns: int):
    """Attribute a state-refresh window (re-import or warm reshard) to
    the goodput ledger so it cannot vanish from the wall-time closure."""
    try:
        from dlrover_tpu.obs.goodput import default_ledger

        ledger = default_ledger()
        if ledger is not None:
            ledger.mark_interval(
                "restart_replay", t0_ns, time.monotonic_ns()
            )
    except Exception:  # accounting must never break the refresh itself
        pass


class SparseTrainer:
    """Embedding-store-backed training loop with elastic checkpointing.

    ``dense_step(dense_params, rows, batch) ->
    (dense_params, row_grads, metrics)`` is the user's jitted dense
    computation; the trainer owns the gather → step → sparse-update
    cycle, checkpoints, and cluster-version failover.

    ``embedding`` may be a host store (``ShardedKvEmbedding`` /
    tiered) for the classic host cycle, or a
    :class:`DeviceSparseEmbedding` to enable the device cycle.
    ``target_shards_fn`` (e.g. a master query) makes a cluster-version
    bump warm-reshard to that shard count instead of re-importing.
    """

    def __init__(
        self,
        embedding,
        dense_params: Any,
        dense_step: Callable,
        ckpt_dir: str = "",
        sparse_optimizer: str = "adagrad",
        sparse_lr: float = 0.05,
        master_client=None,
        target_shards_fn: Optional[Callable[[], int]] = None,
    ):
        self.embedding = embedding
        self.dense_params = dense_params
        self._dense_step = dense_step
        self._ckpt_dir = ckpt_dir
        self._opt = sparse_optimizer
        self._lr = sparse_lr
        self._client = master_client
        self._target_shards_fn = target_shards_fn
        self._cluster_version = (
            self._poll_cluster_version(initial=True)
            if master_client
            else 0
        )
        self.step = 0

    @property
    def device_mode(self) -> bool:
        return isinstance(self.embedding, DeviceSparseEmbedding)

    # -- sparse update dispatch (host cycle) ---------------------------
    def _apply_sparse(self, keys, grads):
        if self._opt == "adagrad":
            self.embedding.sparse_adagrad(keys, grads, lr=self._lr)
        elif self._opt == "adam":
            self.embedding.sparse_adam(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "momentum":
            self.embedding.sparse_momentum(keys, grads, lr=self._lr)
        elif self._opt == "group_ftrl":
            self.embedding.sparse_group_ftrl(keys, grads, alpha=self._lr)
        elif self._opt == "group_adam":
            self.embedding.sparse_group_adam(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "lamb":
            self.embedding.sparse_lamb(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "adabelief":
            self.embedding.sparse_adabelief(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        elif self._opt == "amsgrad":
            self.embedding.sparse_amsgrad(
                keys, grads, lr=self._lr, step=self.step + 1
            )
        else:
            raise ValueError(f"unknown sparse optimizer {self._opt!r}")

    # -- failover -------------------------------------------------------
    def _poll_cluster_version(self, initial: bool = False) -> int:
        """One cluster-version read over the client. A real
        ``MasterClient`` already retries with full jitter inside
        ``_call``; when the budget is exhausted anyway (master restart
        in flight) the poll degrades to "no change" instead of killing
        the train loop — the next poll sees the bump."""
        try:
            return self._client.get_cluster_version()
        except (ConnectionError, OSError) as e:
            if initial:
                raise
            logger.warning(
                f"cluster-version poll failed ({e!r}); keeping version "
                f"{self._cluster_version} until the master answers"
            )
            return self._cluster_version

    def check_failover(self) -> bool:
        """True if the PS cluster version moved and state was refreshed
        (parity: ps_addresses_changed → session refresh). The refresh
        is a WARM move-only reshard when a target shard count is known
        (``target_shards_fn``), else the npz re-import; both windows
        are booked to the goodput ledger as ``restart_replay``."""
        if self._client is None:
            return False
        version = self._poll_cluster_version()
        if version == self._cluster_version:
            return False
        logger.warning(
            f"embedding cluster version {self._cluster_version} -> "
            f"{version}: refreshing sparse state"
        )
        self._cluster_version = version
        t0 = time.monotonic_ns()
        try:
            target = (
                self._target_shards_fn()
                if self._target_shards_fn is not None
                else None
            )
            if target and hasattr(self.embedding, "warm_reshard"):
                report = self.embedding.warm_reshard(int(target))
                logger.info(
                    f"warm embedding reshard on version bump: "
                    f"{report.describe()}"
                )
            else:
                self.restore_embedding()
        finally:
            _book_replay(t0)
        return True

    # -- train loop -----------------------------------------------------
    def train_step(self, ids: np.ndarray, batch: Any) -> Dict:
        """One HOST cycle: gather rows → dense step on device → fused
        sparse update on host."""
        rows = self.embedding.gather(ids)
        self.dense_params, row_grads, metrics = self._dense_step(
            self.dense_params, rows, batch
        )
        self._apply_sparse(ids, np.asarray(row_grads))
        self.step += 1
        return metrics

    def train_step_device(
        self, ids: np.ndarray, batch: Any, prep=None
    ) -> Dict:
        """One DEVICE cycle: HBM gather → dense step → on-device sparse
        update. ``prep`` usually comes from the row pipeline one step
        ahead; a stale prep (the tier was flushed/resharded in between)
        is transparently re-prepared."""
        emb = self.embedding
        if prep is None:
            prep = emb.prepare(ids)
        try:
            try:
                rows = emb.gather_for(prep)
            except RuntimeError:  # stale generation → re-prepare
                prep = emb.prepare(ids)
                rows = emb.gather_for(prep)
            self.dense_params, row_grads, metrics = self._dense_step(
                self.dense_params, rows, batch
            )
            emb.apply_grads(prep, row_grads, step=self.step + 1)
        finally:
            emb.release(prep)  # no-op when apply_grads got there
        self.step += 1
        return metrics

    def run(
        self,
        data_iter,
        num_steps: Optional[int] = None,
        overlapped: bool = True,
        pipeline_depth: int = 2,
    ) -> List[Dict]:
        """Drive ``data_iter`` of ``(ids, batch)`` pairs. In device
        mode with ``overlapped=True`` the row pipeline faults step
        N+1's rows in while step N computes; otherwise the synchronous
        cycle runs (host cycle for host stores, inline-prepare device
        cycle for a device embedding)."""
        metrics: List[Dict] = []
        if self.device_mode and overlapped:
            from dlrover_tpu.data.sparse_prefetch import SparseRowPipeline

            pipe = SparseRowPipeline(
                data_iter, self.embedding, depth=pipeline_depth
            )
            try:
                for ids, batch, prep in pipe:
                    metrics.append(
                        self.train_step_device(ids, batch, prep)
                    )
                    if num_steps and len(metrics) >= num_steps:
                        break
            finally:
                pipe.close()
            return metrics
        for ids, batch in data_iter:
            if self.device_mode:
                metrics.append(self.train_step_device(ids, batch))
            else:
                metrics.append(self.train_step(ids, batch))
            if num_steps and len(metrics) >= num_steps:
                break
        return metrics

    # -- telemetry ------------------------------------------------------
    def telemetry(self) -> Dict[str, float]:
        """Per-table hot-tier scalars (+ trainer step), published to
        the obs registry; with a master client they also ride
        ``report_train_metrics`` to the master's collector → Brain
        ``job_metrics`` alongside loss/lr."""
        scalars: Dict[str, float] = {"sparse_step": float(self.step)}
        if self.device_mode:
            scalars.update(self.embedding.export_metrics())
        return scalars

    def report_telemetry(self, extra: Optional[Dict] = None):
        scalars = self.telemetry()
        if extra:
            scalars.update(extra)
        if self._client is not None and hasattr(
            self._client, "report_train_metrics"
        ):
            try:
                self._client.report_train_metrics(self.step, scalars)
            except (ConnectionError, OSError) as e:
                logger.warning(f"telemetry report failed: {e!r}")
        return scalars

    # -- checkpoint -----------------------------------------------------
    def _emb_path(self) -> str:
        return os.path.join(self._ckpt_dir, "embedding_state.npz")

    @staticmethod
    def _prev_path(path: str) -> str:
        return path.replace(".npz", ".prev.npz")

    @staticmethod
    def _meta_path(path: str) -> str:
        return path + ".meta"

    def _dense_leaves(self) -> Dict[str, np.ndarray]:
        import jax

        leaves = jax.tree_util.tree_leaves(self.dense_params)
        return {
            f"__dense_{i}": np.asarray(leaf)
            for i, leaf in enumerate(leaves)
        }

    def _restore_dense(self, data: Dict[str, np.ndarray]):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(self.dense_params)
        saved = [
            data.pop(k)
            for k in sorted(
                (k for k in data if k.startswith("__dense_")),
                key=lambda k: int(k.rsplit("_", 1)[1]),
            )
        ]
        if not saved:
            return
        if len(saved) != len(leaves):
            logger.warning(
                f"checkpoint dense leaf count {len(saved)} != current "
                f"{len(leaves)}; keeping in-memory dense params"
            )
            return
        import jax.numpy as jnp

        self.dense_params = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(s) for s in saved]
        )

    def save_embedding(self):
        """crc-verified atomic save: the npz blob's whole-file crc32
        plus per-record crcs are written to a ``.meta`` sidecar BEFORE
        any byte can be corrupted in flight (the PR-5 writer-side-crc
        rule), and the previous good file is kept for rollback. A
        device-tier embedding is flushed first so device-resident
        training is in the export."""
        if not self._ckpt_dir:
            return
        os.makedirs(self._ckpt_dir, exist_ok=True)
        state = dict(self.embedding.export_state())
        records = {**state, **self._dense_leaves()}
        buf = io.BytesIO()
        np.savez(buf, step=np.int64(self.step), **records)
        blob = buf.getvalue()
        import json

        meta = {
            "crc32": zlib.crc32(blob),
            "nbytes": len(blob),
            "records": {
                name: zlib.crc32(np.ascontiguousarray(arr).tobytes())
                for name, arr in records.items()
            },
            "step": int(self.step),
        }
        # fault site embedding.export: data kinds corrupt the payload
        # AFTER the crcs were computed — exactly a torn/bit-rotted
        # write, which restore must detect and roll back from
        blob = faults.corrupt("embedding.export", blob)
        path = self._emb_path()
        if os.path.exists(path):
            os.replace(path, self._prev_path(path))
            if os.path.exists(self._meta_path(path)):
                os.replace(
                    self._meta_path(path),
                    self._meta_path(self._prev_path(path)),
                )
        tmp = path.replace(".npz", f".tmp{os.getpid()}.npz")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())  # a "saved" checkpoint is durable
        with open(self._meta_path(path) + ".tmp", "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(self._meta_path(path) + ".tmp", self._meta_path(path))
        os.replace(tmp, path)
        # both renames' directory entries must be durable before this
        # save is treated as the rollback target
        fsync_dir(os.path.dirname(path) or ".")
        logger.info(
            f"saved embedding state ({len(state['keys'])} rows, "
            f"crc {meta['crc32']:08x}) at step {self.step}"
        )

    def _load_verified(self, path: str) -> Optional[Dict]:
        """Load + verify one checkpoint file; None when absent, raises
        ``ValueError`` on corruption (caller quarantines)."""
        import json

        if not os.path.exists(path):
            return None
        faults.fire("embedding.import")
        with open(path, "rb") as f:
            blob = f.read()
        meta = None
        if os.path.exists(self._meta_path(path)):
            try:
                with open(self._meta_path(path)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = None
        if meta is not None:
            if len(blob) != meta["nbytes"] or (
                zlib.crc32(blob) != meta["crc32"]
            ):
                raise ValueError(
                    f"embedding checkpoint {path} fails crc/length "
                    f"verification (torn or corrupted write)"
                )
        try:
            data = dict(np.load(io.BytesIO(blob)))
        except Exception as e:  # torn zip on legacy (meta-less) files
            raise ValueError(f"embedding checkpoint {path} unreadable: {e!r}")
        if meta is not None:
            for name, crc in meta["records"].items():
                if name not in data or (
                    zlib.crc32(
                        np.ascontiguousarray(data[name]).tobytes()
                    )
                    != crc
                ):
                    raise ValueError(
                        f"embedding checkpoint {path}: record "
                        f"{name!r} fails crc verification"
                    )
        return data

    def _quarantine(self, path: str):
        for p in (path, self._meta_path(path)):
            if os.path.exists(p):
                os.replace(p, p + ".corrupt")
        logger.error(
            f"embedding checkpoint {path} quarantined to "
            f"{path}.corrupt"
        )

    def restore_embedding(self) -> bool:
        """Restore the newest VERIFIED embedding checkpoint: the
        current file, else (after quarantining it) the kept previous
        one — a torn export rolls back instead of restoring silently."""
        path = self._emb_path()
        for candidate in (path, self._prev_path(path)):
            try:
                data = self._load_verified(candidate)
            except ValueError as e:
                logger.error(str(e))
                self._quarantine(candidate)
                continue
            if data is None:
                continue
            self.step = int(data.pop("step", 0))
            self._restore_dense(data)
            self.embedding.import_state(data)
            logger.info(
                f"restored embedding state ({len(data['keys'])} rows) "
                f"at step {self.step}"
                + (
                    " [rolled back to previous good file]"
                    if candidate != path
                    else ""
                )
            )
            return True
        return False
