"""``dlrover-tpu-run``: the elastic launcher (torchrun-superset analog).

Parity: dlrover/trainer/torch/elastic_run.py:124-371 — on the first node it
spawns a local job master when none is provided
(``_launch_dlrover_local_master:230``), then runs the per-host elastic
agent which rendezvouses through the master and supervises the training
processes. Flags mirror the reference's additions: ``--network-check``,
``--node-unit``, ``--max-restarts``, plus TPU-specific ``--device-spec``.

Usage:
    dlrover-tpu-run --nnodes=1 --nproc-per-node=2 train.py [args...]
    dlrover-tpu-run --nnodes=2:4 --network-check train.py   # elastic range
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time
from typing import Optional, Tuple

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.training_agent import (
    ElasticTrainingAgent,
    WorkerSpec,
    WorkerState,
    die_with_parent_hook,
)
from dlrover_tpu.ckpt.saver import AsyncCheckpointSaver
from dlrover_tpu.common import comm
from dlrover_tpu.utils.env import child_env
from dlrover_tpu.common.constants import NodeEnv, RendezvousName
from dlrover_tpu.common.log import default_logger as logger


def parse_args(argv=None):
    p = argparse.ArgumentParser("dlrover-tpu-run")
    p.add_argument(
        "--nnodes",
        type=str,
        default="1",
        help="node count, fixed ('2') or elastic range ('2:4')",
    )
    p.add_argument("--nproc-per-node", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument(
        "--master-addr",
        type=str,
        default="",
        help="existing master host:port; empty => node 0 spawns one",
    )
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--monitor-interval", type=float, default=3.0)
    p.add_argument(
        "--rdzv-waiting-timeout",
        type=float,
        default=5.0,
        help="lastcall seconds to wait for more nodes past min",
    )
    p.add_argument(
        "--node-unit",
        type=int,
        default=1,
        help="hosts per TPU slice; worlds are multiples of this",
    )
    p.add_argument(
        "--network-check",
        action="store_true",
        help="run the paired node health check before training",
    )
    p.add_argument(
        "--exclude-straggler",
        action="store_true",
        help="a straggler verdict from the network check removes the "
        "node from the job instead of only warning",
    )
    p.add_argument(
        "--auto-config",
        action="store_true",
        help="infer nnodes from NODE_NUM, nproc-per-node from the local "
        "TPU count, and enable network-check for jobs of >=4 nodes "
        "(parity: dlrover-run --auto-config)",
    )
    p.add_argument(
        "--device-spec",
        type=str,
        default="",
        help="'cpu:8' for CPU-hosted virtual devices, default: real TPU",
    )
    p.add_argument(
        "--job-name",
        type=str,
        default="",
        help="namespaces IPC sockets/shm so jobs on one host don't collide",
    )
    p.add_argument("--log-dir", type=str, default="")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def parse_nnodes(spec: str) -> Tuple[int, int]:
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return int(lo), int(hi)
    n = int(spec)
    return n, n


def launch_local_master(node_num: int) -> Tuple[subprocess.Popen, str]:
    """Parity: _launch_dlrover_local_master elastic_run.py:230."""
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--node_num",
            str(node_num),
        ],
        stdout=subprocess.PIPE,
        stderr=None,
        text=True,
        env=child_env(),
        # a SIGKILL'd launcher must not orphan the job master it spawned
        # (see agent/training_agent._die_with_parent)
        preexec_fn=die_with_parent_hook(),
    )
    # Read the address line on a thread so a wedged master (alive but never
    # printing its address) cannot block the launcher past the deadline; the
    # thread keeps draining stdout afterwards so the pipe never fills up.
    box: dict = {}
    got = threading.Event()

    def _reader():
        for line in proc.stdout:
            if not got.is_set() and line.startswith(
                "DLROVER_TPU_MASTER_ADDR="
            ):
                box["addr"] = line.strip().split("=", 1)[1]
                got.set()
        got.set()

    threading.Thread(target=_reader, daemon=True).start()
    got.wait(timeout=30)
    addr = box.get("addr", "")
    if not addr:
        proc.terminate()
        raise RuntimeError("local master failed to start")
    return proc, addr


def _run_network_check(args, client: MasterClient) -> bool:
    """Run the node health check before training (parity:
    NetworkCheckElasticAgent training.py:799 + run_network_check:1014).
    The check rendezvous was already configured via RendezvousParamsReport."""
    from dlrover_tpu.agent.node_check_agent import run_network_check

    return run_network_check(
        node_rank=args.node_rank,
        nproc_per_node=args.nproc_per_node,
        client=client,
        device_spec=args.device_spec,
        exclude_straggler=args.exclude_straggler,
    )


def auto_configure(args):
    """--auto-config (parity: elastic_run.py:33-40 + ElasticLaunchConfig
    .auto_configure_params training.py:140): nnodes from the platform's
    NODE_NUM env (the operator sets it on every pod), nproc-per-node
    from the locally visible accelerator count, and network-check on
    for jobs of >= 4 nodes."""
    try:
        node_num = int(os.getenv(NodeEnv.NODE_NUM, "0") or "0")
    except ValueError:
        node_num = 0  # templated-but-unset env: fall back to --nnodes
    if node_num > 0:
        args.nnodes = str(node_num)
    from dlrover_tpu.utils.device import local_device_count

    n = local_device_count(args.device_spec)
    if n > 0:
        args.nproc_per_node = n
    # gate on the RESOLVED min_nodes, not only the env-derived node_num:
    # `--auto-config --nnodes=8` without the platform env must still turn
    # the health check on (parity: training.py:154 gates on min_nodes)
    min_nodes, _ = parse_nnodes(args.nnodes)
    if min_nodes >= 4:
        args.network_check = True
    logger.info(
        f"auto-config: nnodes={args.nnodes} "
        f"nproc_per_node={args.nproc_per_node} "
        f"network_check={args.network_check}"
    )
    return args


def run(args) -> int:
    if args.job_name:
        os.environ[NodeEnv.JOB_NAME] = args.job_name
    if getattr(args, "auto_config", False):
        args = auto_configure(args)
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    master_proc: Optional[subprocess.Popen] = None
    master_addr = args.master_addr or os.getenv(NodeEnv.MASTER_ADDR, "")
    if not master_addr:
        if args.node_rank != 0:
            raise SystemExit(
                "--master-addr is required on non-zero node ranks"
            )
        master_proc, master_addr = launch_local_master(max_nodes)
        logger.info(f"spawned local master at {master_addr}")
    os.environ[NodeEnv.MASTER_ADDR] = master_addr

    client = MasterClient(
        master_addr, node_id=args.node_rank, node_type="worker"
    )
    # configure both rendezvous
    for name in (
        RendezvousName.ELASTIC_TRAINING,
        RendezvousName.NETWORK_CHECK,
    ):
        client.report(
            comm.RendezvousParamsReport(
                rdzv_name=name,
                min_nodes=min_nodes,
                max_nodes=max_nodes,
                waiting_timeout=args.rdzv_waiting_timeout,
                node_unit=args.node_unit,
            )
        )

    monitors = []
    try:
        if args.network_check:
            ok = _run_network_check(args, client)
            if not ok:
                logger.error("this node failed the network check")
                return 3

        spec = WorkerSpec(
            entrypoint=args.training_script,
            args=list(args.training_script_args),
            nproc_per_node=args.nproc_per_node,
            max_restarts=args.max_restarts,
            monitor_interval=args.monitor_interval,
            log_dir=args.log_dir,
            device_spec=args.device_spec,
        )
        # Flash-checkpoint saver must own its IPC endpoints before workers
        # spawn (parity: start_async_saving_ckpt ckpt_saver.py:405); it also
        # persists shm before any elastic restart ("save at breakpoint").
        saver = AsyncCheckpointSaver.start_async_saving_ckpt(
            local_shard_num=args.nproc_per_node, node_rank=args.node_rank
        )
        # degraded-checkpoint-mode (and recovery) node events reach the
        # master: a job silently running shm-only would lose everything
        # on the next node death without anyone being told
        saver.set_event_reporter(
            lambda event, msg: client.report_failure(
                f"{event}: {msg}", level="warning"
            )
        )
        # agent-side daemons (parity: launch_agent starts the monitors at
        # training.py:721). Default: the aggregation tier — ONE
        # delta-encoded RPC per tick coalescing telemetry/step/resource
        # and the command + paral-config poll legs (docs/control-plane.md).
        # DLROVER_TPU_AGENT_BATCH=0 falls back to the legacy per-channel
        # daemons (mixed-version fleets against an old master).
        if os.getenv("DLROVER_TPU_AGENT_BATCH", "1").strip().lower() not in (
            "0", "false", "no", "off"
        ):
            from dlrover_tpu.agent.aggregator import (
                AgentReportBatcher,
                host_resource_fn,
            )

            monitors += [
                AgentReportBatcher(
                    client, resource_fn=host_resource_fn(client.node_id)
                ),
            ]
        else:
            from dlrover_tpu.agent.monitor import (
                ParalConfigTuner,
                ResourceMonitor,
                TrainingMonitor,
                WorkerCommandRelay,
            )

            monitors += [
                ResourceMonitor(client),
                TrainingMonitor(client),
                ParalConfigTuner(client),
                # master->worker forensics channel: flight-dump /
                # profile requests land in the command file the
                # trainer polls
                WorkerCommandRelay(client),
            ]
        for m in monitors:
            m.start()
        agent = ElasticTrainingAgent(
            node_rank=args.node_rank, spec=spec, client=client
        )
        # restart-path persist: the agent survives, so the global commit
        # runs on its own thread — a dead peer's missing done files must
        # not stall re-rendezvous (sync commit is for SIGTERM/close only)
        agent.set_checkpoint_hook(
            lambda: saver.save_shm_to_storage(sync_commit=False)
        )
        result = agent.run()
        logger.info(
            f"agent finished: {result.state} after "
            f"{result.restarts} restarts"
        )
        return 0 if result.state == WorkerState.SUCCEEDED else 1
    finally:
        for m in monitors:
            m.stop()
        AsyncCheckpointSaver.reset()
        client.close()
        if master_proc is not None:
            master_proc.terminate()
            try:
                master_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master_proc.kill()


def main(argv=None) -> int:
    return run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
