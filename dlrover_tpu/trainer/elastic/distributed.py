"""Training-process bootstrap: wire a JAX process into the elastic job.

The TPU analog of torch's ``init_process_group`` + env:// rendezvous
(reference: the env torchelastic exports and training.py:462 rank
assignment): the agent exports ``NodeEnv`` vars computed from the
master-assigned comm world; ``init_elastic()`` consumes them and calls
``jax.distributed.initialize``. Our master owns coordinator address
assignment and restart, which is the elasticity seam JAX itself lacks
(SURVEY.md §5 "Distributed communication backend").
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.utils.device import configure_devices


@dataclass
class ElasticContext:
    process_id: int = 0
    num_processes: int = 1
    node_rank: int = 0
    node_num: int = 1
    local_rank: int = 0
    local_world_size: int = 1
    restart_count: int = 0
    rdzv_round: int = 0
    coordinator_addr: str = ""
    master_addr: str = ""

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def in_elastic_job(self) -> bool:
        return bool(self.master_addr)


def elastic_context() -> ElasticContext:
    return ElasticContext(
        process_id=int(os.getenv(NodeEnv.PROCESS_ID, "0")),
        num_processes=int(os.getenv(NodeEnv.NUM_PROCESSES, "1")),
        node_rank=int(os.getenv(NodeEnv.NODE_RANK, "0")),
        node_num=int(os.getenv(NodeEnv.NODE_NUM, "1")),
        local_rank=int(os.getenv("DLROVER_TPU_LOCAL_RANK", "0")),
        local_world_size=int(os.getenv("DLROVER_TPU_LOCAL_WORLD_SIZE", "1")),
        restart_count=int(os.getenv(NodeEnv.RESTART_COUNT, "0")),
        rdzv_round=int(os.getenv("DLROVER_TPU_RDZV_ROUND", "0")),
        coordinator_addr=os.getenv(NodeEnv.COORDINATOR_ADDR, ""),
        master_addr=os.getenv(NodeEnv.MASTER_ADDR, ""),
    )


_initialized = False


def enable_compile_cache(cache_dir: str = "") -> str:
    """Point JAX's persistent compilation cache at a job-stable dir.

    The elasticity hard part SURVEY.md §7 calls out: a restarted worker's
    first step recompiles the whole train program (tens of seconds to
    minutes at scale) — pure goodput loss. With the persistent cache, a
    restart into the SAME world size replays the compiled executable from
    disk, and each previously-seen world size after a scale event is a
    cache hit too (entries are keyed on the program, which includes mesh
    shape). Returns the cache dir in use, "" when disabled via
    ``DLROVER_TPU_COMPILE_CACHE=off``.
    """
    env = os.getenv("DLROVER_TPU_COMPILE_CACHE", "")
    if env == "off":
        return ""
    cache_dir = env or cache_dir or "/tmp/dlrover_tpu/compile_cache"
    from dlrover_tpu.common.jax_compat import (
        enable_persistent_compilation_cache,
    )

    os.makedirs(cache_dir, exist_ok=True)
    # cache everything that took meaningful compile time, not only the
    # multi-minute programs (defaults skip sub-second compiles); the
    # knobs are version-guarded in jax_compat
    if not enable_persistent_compilation_cache(
        cache_dir, min_compile_secs=0.5, min_entry_bytes=0
    ):
        return ""
    return cache_dir


def init_elastic(timeout_secs: int = 300) -> ElasticContext:
    """Configure devices and join the JAX distributed system.

    Safe to call for single-process jobs (no-op init). Fast re-init after a
    restart is just process re-exec + this call — the agent already
    re-assigned ``process_id``/``coordinator_addr`` for the new world;
    the persistent compilation cache turns the post-restart recompile
    into a disk read.
    """
    global _initialized
    ctx = elastic_context()
    configure_devices()  # honors DLROVER_TPU_DEVICE_SPEC before backend init
    enable_compile_cache()
    if ctx.is_distributed and not _initialized:
        import jax

        logger.info(
            f"jax.distributed.initialize(coordinator="
            f"{ctx.coordinator_addr}, n={ctx.num_processes}, "
            f"id={ctx.process_id})"
        )
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator_addr,
            num_processes=ctx.num_processes,
            process_id=ctx.process_id,
            initialization_timeout=timeout_secs,
        )
        _initialized = True
    return ctx
