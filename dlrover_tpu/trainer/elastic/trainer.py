"""ElasticTrainer: the user-facing training loop.

Parity: dlrover/trainer/torch/elastic/trainer.py:48 (ElasticTrainer
wrapping model/optimizer/dataloader for elasticity) and ATorch's
HF-style ``AtorchTrainer`` (atorch/trainer/atorch_trainer.py:127). One
facade owns the full elastic story so a user train script collapses to
~30 lines:

- strategy: an explicit ``Strategy`` or the auto_accelerate search picks
  the mesh/remat/microbatching (donation off — flash staging reads the
  state after the step);
- data: ``ElasticDataLoader`` + ``ElasticDistributedSampler`` (resumes
  mid-epoch across world-size changes, honors master-retuned batch size);
- checkpoint: flash save every ``save_memory_interval`` steps (ms-scale,
  shm), persisted every ``save_storage_interval`` steps; sampler state
  rides the train state so restore is exactly-once over the data;
- monitoring: every step publishes the global step for the agent's
  TrainingMonitor (feeds master hang detection / auto-scaling).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import numpy as np

from dlrover_tpu.accel.accelerate import AccelerateResult, auto_accelerate
from dlrover_tpu.accel.strategy import Strategy
from dlrover_tpu.agent.monitor import report_runtime_metrics
from dlrover_tpu.common import faults, storage
from dlrover_tpu.ckpt.checkpointer import FlashCheckpointer, StorageType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.models.config import TransformerConfig
from dlrover_tpu.models.train import shard_batch
from dlrover_tpu.obs.flight_recorder import (
    ProfilerCapture,
    default_recorder,
)
from dlrover_tpu.obs.audit import (
    StepAuditor,
    StepBudget,
    install_default_auditor,
    load_audit_calibration,
)
from dlrover_tpu.obs.goodput import GoodputLedger, install_default_ledger
from dlrover_tpu.obs.metrics import default_registry, fold_pipeline_stats
from dlrover_tpu.obs.trace import SpanHeartbeat, span
from dlrover_tpu.parallel import transfer_sched
from dlrover_tpu.trainer.elastic.dataloader import ElasticDataLoader
from dlrover_tpu.trainer.elastic.sampler import ElasticDistributedSampler


@dataclass
class TrainerConfig:
    batch_size: int = 8
    seq_len: int = 128
    ckpt_dir: str = ""
    save_memory_interval: int = 50
    save_storage_interval: int = 500
    report_metrics: bool = True
    log_interval: int = 10
    # eval loop: 0 disables; otherwise run ``eval_steps`` batches of the
    # eval dataset every ``eval_interval`` optimizer steps
    eval_interval: int = 0
    eval_steps: int = 50
    # >1: split each batch into K sequential microbatches per optimizer
    # update (batch_size must divide by K)
    grad_accum: int = 1
    # save-strategy / early-stop hooks (ref atorch_trainer.py save_
    # strategy + EarlyStoppingCallback): save_best persists the best-
    # eval checkpoint to its OWN directory (ckpt_dir/best — the
    # periodic saves must never supersede it) with the best loss in a
    # sidecar so restarts don't regress it; early_stopping_patience
    # stops training after that many consecutive evals without
    # improvement (0 = never). Both need eval_interval + eval_dataset.
    save_best: bool = False
    # best-saves block on the disk commit; during the steep-improvement
    # phase evals improve every time, so persist at most this often
    save_best_min_interval_s: float = 60.0
    early_stopping_patience: int = 0
    # -- overlapped host<->device pipeline -----------------------------
    # device prefetch depth (0 disables): a producer thread pulls batch
    # N+1 from the dataloader and places it on device while batch N
    # computes; 2 = classic double buffering
    prefetch: int = 2
    # in-memory flash saves stage device->shm in fixed-size chunks
    # interleaved between steps instead of one big drain (the commit
    # barrier is the only blocking point)
    chunked_staging: bool = True
    stage_chunk_mb: int = 64
    # critical-path budget per step for draining stage chunks
    stage_budget_ms: float = 5.0
    # run the state+input-donating train step whenever no checkpoint
    # staging is reading the state buffers (HBM reuse; the safe
    # non-donating twin runs while staging is in flight)
    donation_aware: bool = True
    # -- elastic-resize fast path --------------------------------------
    # pre-lower the train step for the master's predicted next world
    # sizes (candidate_worker_counts in the paral config) on a
    # background thread, so the resize that lands finds its executable
    # already in the compile cache
    speculative_compile: bool = True
    # wall-clock cap per candidate batch for that background thread
    # (docs/elastic-resize.md: the speculative-compile budget knob)
    spec_compile_budget_s: float = 120.0
    # -- overlap-scheduled gradient sync (parallel/grad_sync.py) -------
    # bucketed per-bucket collectives under shard_map (pure-dp RS+AG,
    # dp x fsdp ZeRO reduce-scatter into the shard layout, dp x tp/sp
    # bucketed dp sync under the GSPMD submesh): independent
    # collectives XLA can overlap with backward compute, and
    # grad_accum syncs once per optimizer step
    comm_overlap: bool = False
    # "none" | "int8" | "int8_topk" | "auto": compressed collective
    # payloads with error feedback (implies comm_overlap's explicit
    # sync path; dp/fsdp plans only — tp plans run uncompressed).
    # "int8_topk" also ships only the top-k blocks of the cross-slice
    # DCN shard; "auto" resolves per mesh from the measured ICI:DCN
    # ratio (grad_sync.resolve_auto_compress)
    grad_compress: str = "none"
    # requested DCN block density under int8_topk/auto
    grad_topk_density: float = 0.25
    # target sync bucket size, MiB; 0 = auto-size per link from the
    # measured topology.LinkModel (DCN-leg target on multi-slice
    # meshes, ICI otherwise)
    grad_bucket_mb: int = 4
    # micro-batch rebalance on indivisible worker counts (ISSUE 13):
    # instead of idling surplus ranks, pad the batch with zero-weight
    # rows so it divides over ALL ranks — the dry-runner prices both
    # options (accel/dry_runner.price_rebalance_options) and the
    # cheaper wins; the pads land on the trailing ranks (the elastic
    # data layer's slice_throughput_weights dealing already skews the
    # REAL rows toward the faster slices). grad_accum>1 keeps the
    # idle-ranks behavior (pads would multiply across microbatches).
    mb_rebalance: bool = True
    # >0: every this many steps, fold the measured per-expert routing
    # load (moe_expert_load) into the CapacityRebalancer and — when
    # the re-split changed — rebuild the step with the new
    # cfg.capacity_splits (a recompile through the AOT cache,
    # amortized over the interval). 0 = static capacity_factor.
    moe_rebalance_interval: int = 0
    # -- eviction grace-window drain -----------------------------------
    # default grace window (seconds) for an eviction notice that does
    # not carry its own (SIGTERM, an `evict` command with arg=0);
    # DLROVER_TPU_EVICTION_DEADLINE_S overrides at construction
    eviction_grace_s: float = 30.0
    # the emergency DISK persist is skipped when less than this remains
    # of the grace window after the shm commit — the degraded-mode shm
    # handoff (agent persists shm on restart) already covers it
    eviction_persist_floor_s: float = 5.0
    # -- silent-data-corruption defense (parallel/sdc.py, ISSUE 20) ----
    # tier-1 fence: per-lane local grad norms ride the sync out-spec
    # and a robust median+MAD detector classifies each step (data
    # spike: skip-and-log; device suspect: escalate to the paired
    # audit probe; conviction: verified rollback + quarantine halt).
    # DLROVER_TPU_SDC=1 enables without the knob; explicit dp-family
    # sync plans only (comm_overlap/grad_compress — the per-lane
    # vector falls out of the bucket walk there)
    sdc_detect: bool = False
    sdc_window: int = 32  # clean-step window behind the temporal test
    sdc_min_history: int = 8  # observations before that test arms
    sdc_spike_sigma: float = 6.0  # temporal (data-spike) threshold
    sdc_suspect_sigma: float = 6.0  # cross-lane (device) threshold
    # >0: also audit every N steps regardless of suspicion (a chip can
    # be wrong in ways the norm fence misses);
    # DLROVER_TPU_SDC_AUDIT_STEPS overrides
    sdc_audit_steps: int = 0


def build_optimizer(
    name: str = "adamw",
    lr: float = 3e-4,
    schedule: str = "constant",
    warmup_steps: int = 0,
    total_steps: int = 10_000,
    weight_decay: float = 0.0,
    **kwargs,
):
    """Optimizer + LR schedule, retune-compatible (the AtorchTrainer
    ``lr_scheduler_type`` surface, ref atorch_trainer.py:127).

    The returned transform is built with ``optax.inject_hyperparams`` so
    two knobs stay live in ``opt_state.hyperparams``:

    - ``learning_rate`` — driven per-step by the chosen schedule
      ("constant" | "cosine" | "linear"; warmup_steps prepends a linear
      warmup);
    - ``retune_scale`` — the master's batch-size linear-scaling factor
      (ElasticTrainer._apply_lr_scale writes it), COMPOSED with the
      schedule instead of being overwritten by it.
    """
    import optax

    if schedule == "constant":
        lr_fn = (
            optax.linear_schedule(0.0, lr, warmup_steps)
            if warmup_steps
            else lr
        )
    elif schedule == "cosine":
        # warmup_steps=0 means NO warmup: start at peak (forcing a
        # 1-step warmup would make the first update a dead lr=0 step)
        lr_fn = (
            optax.warmup_cosine_decay_schedule(
                init_value=0.0,
                peak_value=lr,
                warmup_steps=warmup_steps,
                decay_steps=total_steps,
            )
            if warmup_steps
            else optax.cosine_decay_schedule(lr, total_steps)
        )
    elif schedule == "linear":
        decay = optax.linear_schedule(
            lr, 0.0, max(total_steps - warmup_steps, 1)
        )
        lr_fn = (
            optax.join_schedules(
                [optax.linear_schedule(0.0, lr, warmup_steps), decay],
                [warmup_steps],
            )
            if warmup_steps
            else decay
        )
    else:
        raise ValueError(f"unknown lr schedule {schedule!r}")

    if name not in (
        "adamw", "adam", "sgd", "agd", "adamw_8bit", "adamw_8bit_flat"
    ):
        raise ValueError(f"unknown optimizer {name!r}")

    def make(learning_rate, retune_scale):
        # weight_decay applies to EVERY optimizer: decoupled (after the
        # adaptive direction) for adamw/adam/agd/8bit, classic
        # L2-into-update for sgd. add_decayed_weights(0.0) is a no-op.
        if name == "adamw":
            opt = optax.adamw(
                learning_rate, weight_decay=weight_decay, **kwargs
            )
        elif name == "adam":
            opt = optax.chain(
                optax.scale_by_adam(**kwargs),
                optax.add_decayed_weights(weight_decay),
                optax.scale_by_learning_rate(learning_rate),
            )
        elif name == "agd":
            from dlrover_tpu.ops.optimizers import agd

            opt = agd(
                learning_rate, weight_decay=weight_decay, **kwargs
            )
        elif name == "adamw_8bit":
            from dlrover_tpu.ops.quantized_optim import adamw_8bit

            opt = adamw_8bit(
                learning_rate, weight_decay=weight_decay, **kwargs
            )
        elif name == "adamw_8bit_flat":
            from dlrover_tpu.ops.quantized_optim import adamw_8bit_flat

            opt = adamw_8bit_flat(
                learning_rate, weight_decay=weight_decay, **kwargs
            )
        else:
            opt = optax.chain(
                optax.add_decayed_weights(weight_decay),
                optax.sgd(learning_rate, **kwargs),
            )
        return optax.chain(opt, optax.scale(retune_scale))

    return optax.inject_hyperparams(make)(
        learning_rate=lr_fn, retune_scale=1.0
    )


def _dense_eval_loss(params, x, y, cfg, mesh):
    """PURE NLL — no MoE aux regularizers, so eval_loss/ppl are
    comparable across parallelism modes and configs. One definition for
    every mesh the trainer ever evaluates on (the pp path wraps the
    pipeline's own loss instead)."""
    from dlrover_tpu.models.transformer import forward, token_nll

    logits, _ = forward(params, x, cfg, mesh)
    return token_nll(logits, y)


class ElasticTrainer:
    def __init__(
        self,
        model_cfg: TransformerConfig,
        tx,
        dataset,
        trainer_cfg: Optional[TrainerConfig] = None,
        strategy: Optional[Strategy] = None,
        devices=None,
        collate_fn: Optional[Callable] = None,
        metrics_hook: Optional[Callable[[int, Dict], None]] = None,
        eval_dataset=None,
    ):
        import jax

        self.tcfg = trainer_cfg or TrainerConfig()
        self._metrics_hook = metrics_hook
        # kept for the resize path: a new mesh rebuilds the accel
        # artifacts from the SAME model config and optimizer
        self._model_cfg = model_cfg
        self._tx = tx
        # SDC defense must be switched on BEFORE the step is built:
        # build_train_step reads the module switch at trace time to
        # decide whether the per-lane norm vector rides the sync (the
        # module-level switch covers the donating twin, the dry-runner
        # and resize rebuilds consistently — no signature threading)
        if self.tcfg.sdc_detect:
            from dlrover_tpu.parallel import sdc as _sdc

            _sdc.set_enabled(True)
        # async flash staging reads state buffers after the step returns,
        # so the production step must NOT donate them
        self.accel: AccelerateResult = auto_accelerate(
            model_cfg,
            tx,
            batch=self.tcfg.batch_size,
            seq=self.tcfg.seq_len,
            devices=devices,
            strategy=strategy,
            donate=False,
            grad_accum=self.tcfg.grad_accum,
            optimizations=self._grad_sync_opt_names(),
            # bucket size only when the trainer's knobs own the sync
            # config — an explicit Strategy's own grad_bucket_mb wins
            # otherwise
            grad_bucket_mb=(
                self.tcfg.grad_bucket_mb
                if self._grad_sync_opt_names()
                else None
            ),
        )
        self.cfg = self.accel.cfg
        self.mesh = self.accel.mesh
        self._step_fn = self.accel.step_fn
        # donation-aware stepping: the donating twin runs whenever no
        # async staging reads the state; flip back to the safe step for
        # the staging window (a donated buffer mid-D2H is a crash)
        self._donating_step_fn = (
            self.accel.donating_step_fn
            if self.tcfg.donation_aware
            else None
        )
        from dlrover_tpu.accel.compile_cache import CompileCache
        from dlrover_tpu.accel.profiler import PipelineStats

        self.pipeline_stats = PipelineStats()
        # AOT executables keyed by (mesh, shapes, donation, strategy):
        # the first step on any mesh lands here, so a later resize back
        # to that mesh skips the XLA compile entirely
        self._compile_cache = CompileCache(stats=self.pipeline_stats)
        self._spec_compiler = None
        self._batch_avals = None  # ((shape, dtype), ...) of (x, y)
        self._aot_primed = False
        # the AOT executable + the exact batch shapes it was lowered
        # for; other shapes (short final batch, master-retuned batch
        # size) fall through to the retracing jit wrapper
        self._aot_exec = None
        self._aot_shapes = None
        self._last_candidates = None
        self._prefetcher = None
        self._stager = None
        # -- unified telemetry (obs/): spans + metrics registry --------
        self._registry = default_registry()
        self._step_time_hist = self._registry.histogram(
            "dlrover_step_time_seconds", "optimizer-step wall time"
        )
        self._step_time_sum = 0.0
        self._step_time_n = 0
        self._train_tid: Optional[int] = None
        # hang attribution: a background heartbeat publishes the train
        # thread's current open span into the runtime-metrics file even
        # while the loop is wedged inside one (obs/trace.SpanHeartbeat →
        # agent TrainingMonitor → master hang report)
        self._span_heartbeat = (
            SpanHeartbeat(tid_fn=lambda: self._train_tid)
            if self.tcfg.report_metrics
            else None
        )
        if self._span_heartbeat is not None:
            self._span_heartbeat.start()
        # -- goodput ledger + crash forensics (obs/goodput, obs/
        # flight_recorder): every second of this trainer's wall time is
        # attributed to the closed taxonomy and exported at log
        # cadence; the flight recorder dumps a bundle on crash, hang
        # (its own watchdog thread) or degraded-mode entry, and the
        # master can request dumps/profiles via the command file
        self._goodput = install_default_ledger(
            GoodputLedger(tid_fn=lambda: self._train_tid)
        )
        # step-budget auditor (obs/audit): reconciles the pricing
        # side's per-component StepBudget against the span stream each
        # step — drift reprices, sustained regressions alarm with the
        # component named and a flight bundle captured
        self._auditor = install_default_auditor(
            StepAuditor(
                tid_fn=lambda: self._train_tid,
                on_alarm=self._on_audit_alarm,
            )
        )
        self._replay_until_step: Optional[int] = None
        self._flight = default_recorder()
        self._flight.set_identity(
            node_id=int(os.getenv("DLROVER_TPU_NODE_ID", "0") or 0),
            job_name=os.getenv("DLROVER_TPU_JOB_NAME", ""),
            mesh=str(self.accel.strategy.mesh.axis_sizes()),
            model=type(model_cfg).__name__,
        )
        if self.tcfg.report_metrics:
            self._flight.start_watchdog(
                hang_dump_after_s=float(
                    os.getenv("DLROVER_TPU_HANG_DUMP_AFTER_S", "120")
                ),
                tid_fn=lambda: self._train_tid,
            )
        self._profiler_capture = ProfilerCapture()
        # the command file outlives a worker restart, but its commands
        # target the PREVIOUS incarnation (dump THAT process, profile
        # THAT hang) — start past them instead of replaying stale
        # forensics against a healthy fresh process
        from dlrover_tpu.agent.monitor import last_command_id

        self._last_command_id = last_command_id()
        # -- eviction grace-window drain -------------------------------
        # a preemption notice (SIGTERM / env deadline / master `evict`
        # command) flips the event; the train loop drains at the next
        # step boundary: finish the step, emergency shm checkpoint,
        # report + flush forensics, exit clean (docs/fault-injection.md)
        env_grace = os.getenv("DLROVER_TPU_EVICTION_DEADLINE_S", "")
        if env_grace:
            try:
                self.tcfg.eviction_grace_s = float(env_grace)
            except ValueError:
                logger.warning(
                    f"bad DLROVER_TPU_EVICTION_DEADLINE_S={env_grace!r};"
                    f" keeping {self.tcfg.eviction_grace_s}s"
                )
        self._evict_event = threading.Event()
        self._evict_deadline: Optional[float] = None  # monotonic
        self._evict_grace_s = 0.0
        self._evict_reason = ""
        self.evicted = False
        self.eviction_drain_ms = 0.0
        # event-reporter seam (the PR-5 saver pattern): in the agent
        # architecture the monitor file carries the notice; in-process
        # callers (bench, chaos harness, tests) wire this to
        # MasterClient.report_failure / report_eviction_notice directly
        self._event_reporter: Optional[Callable[[str, str], None]] = None
        if env_grace:
            # a platform that exports the deadline env expects SIGTERM
            # to mean "drain now" — install the handler automatically
            self.install_eviction_handler()
        self.state = self.accel.init_fn(jax.random.PRNGKey(0))
        self._grad_sync_plan = None
        # MoE capacity rebalancer (ISSUE 13): folds the measured
        # per-expert routing load into a periodic capacity re-split
        # (cfg.capacity_splits) — each applied re-split is a step
        # rebuild through the AOT cache
        self._moe_rebalancer = None
        if (
            self._model_cfg.num_experts
            and self.tcfg.moe_rebalance_interval > 0
        ):
            from dlrover_tpu.parallel.moe import CapacityRebalancer

            self._moe_rebalancer = CapacityRebalancer(
                self._model_cfg.num_experts,
                capacity_factor=self._model_cfg.capacity_factor,
                top_k=self._model_cfg.moe_top_k,
            )
        # measured link-cost model (parallel/topology.py): probe once
        # per device fingerprint (warm restarts hit the JSON cache);
        # the dry-runner and the auto bucket sizer price wire time
        # from it instead of the flat-ICI constant
        self._link_fp: Optional[str] = None
        self._setup_link_model()
        self._setup_grad_sync()
        self._setup_sdc()
        self._audit_cal_loaded = False
        self._setup_audit_budget()
        self._state_nbytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(self.state)
            if hasattr(x, "dtype")
        )
        from dlrover_tpu.ops.quantized_optim import Adam8FlatState

        m = self.accel.strategy.mesh
        has_flat = any(
            isinstance(x, Adam8FlatState)
            for x in jax.tree_util.tree_leaves(
                self.state.opt_state,
                is_leaf=lambda x: isinstance(x, Adam8FlatState),
            )
        )
        if max(m.fsdp, m.tp, m.ep, m.sp, m.pp) > 1 and has_flat:
            # the flat optimizer concatenates every big leaf per step:
            # on a model-sharded mesh that forces cross-shard
            # all-gathers and replicates the packed moment buffers,
            # silently defeating ZeRO/TP sharding
            raise ValueError(
                "adamw_8bit_flat is for replicated/dp-only states; use "
                "adamw_8bit (per-leaf) with fsdp/tp/ep/sp/pp sharding"
            )

        self.sampler = ElasticDistributedSampler(
            len(dataset), shuffle=True
        )
        self.dataloader = ElasticDataLoader(
            dataset,
            batch_size=self.tcfg.batch_size,
            sampler=self.sampler,
            collate_fn=collate_fn,
        )
        self._eval_dataset = eval_dataset
        self._collate_fn = collate_fn
        self._eval_step_fn = None  # built lazily on first evaluate()
        self._ckptr: Optional[FlashCheckpointer] = None
        self._best_ckptr: Optional[FlashCheckpointer] = None
        # the historical best survives restarts via a sidecar; a fresh
        # run starts at +inf
        self._best_eval_loss = float("inf")
        self._last_best_save = 0.0
        if self.tcfg.ckpt_dir:
            self._ckptr = FlashCheckpointer(self.tcfg.ckpt_dir)
            self._maybe_restore()
            if self.tcfg.save_best:
                self._best_dir = os.path.join(
                    self.tcfg.ckpt_dir, "best"
                )
                self._best_ckptr = FlashCheckpointer(self._best_dir)
                self._best_eval_loss = self._load_best_sidecar()

    # -- measured link-cost model (parallel/topology.py) ----------------
    def _setup_link_model(self):
        """Probe (or reuse) the per-link bandwidth model for the
        CURRENT device world. Called at startup and after every
        resize; the probe itself runs only when the device fingerprint
        actually changed (docs/elastic-resize.md invalidation rule) —
        a resize back onto the same hardware, and any warm restart,
        reuses the persisted cache without touching the devices."""
        from dlrover_tpu.parallel import topology

        try:
            devices = list(self.mesh.devices.flatten())
            fp = topology.device_fingerprint(devices)
            if fp == self._link_fp:
                logger.info(
                    f"link model: device fingerprint unchanged ({fp}),"
                    f" keeping the current probe"
                )
                return
            model = topology.probe_link_model(
                mesh_config=self.accel.strategy.mesh, devices=devices
            )
            self._link_fp = fp
            topology.export_link_metrics(model, self._registry)
            # same fingerprint discipline for the arbiter calibration:
            # measure (or reuse) the per-rail hidden fraction so the
            # dry-runner prices host traffic from observation instead
            # of the documented constant
            from dlrover_tpu.parallel import transfer_sched

            transfer_sched.ensure_calibrated()
        except Exception as e:  # the probe must never kill training
            logger.warning(f"link-model probe failed: {e!r}")

    def apply_slice_throughput(self, step_times_s) -> None:
        """Heterogeneous per-slice data weighting (arXiv 2602.18007):
        per-slice step times → normalized throughput weights → unequal
        per-replica shards in the elastic sampler (a slice twice as
        fast consumes twice the data, so the fast slices stop waiting
        at the sync point). ``step_times_s``: one entry per DCN slice,
        e.g. from the master's straggler attribution. No-op (reset to
        equal shards) when the mesh has no multi-slice structure."""
        from dlrover_tpu.parallel import topology

        slices = self.accel.strategy.mesh.dp_slices()
        reps = self.sampler.num_replicas
        if slices <= 1 or len(step_times_s) != slices or reps % slices:
            # NOT silent: the in-process trainer's own sampler is
            # single-replica (one process consumes the whole global
            # batch — there are no per-replica shards to reweight;
            # multi-worker data planes construct per-rank samplers and
            # call set_throughput_weights on those), and a mismatched
            # slice count means the caller's view of the mesh is stale
            if slices > 1:
                logger.warning(
                    f"slice throughput weighting not applied: "
                    f"{slices} slices, {len(step_times_s)} step times, "
                    f"{reps} sampler replicas (need len(times) == "
                    f"slices and slices | replicas); resetting to "
                    f"equal shards"
                )
            self.sampler.set_throughput_weights(None)
            return
        w = topology.slice_throughput_weights(step_times_s)
        per = reps // slices
        # replicas are slice-major (mesh.py hybrid dp layout): replica
        # r lives in slice r // per and splits its slice's share evenly
        self.sampler.set_throughput_weights(
            [w[r // per] / per for r in range(reps)]
        )
        logger.info(
            f"slice throughput weights applied: {[round(x, 3) for x in w]}"
        )

    # -- overlap-scheduled gradient sync -------------------------------
    def _grad_sync_opt_names(self) -> tuple:
        """Named optimizations the trainer's grad-sync knobs translate
        to (accel/opt_lib.py) — stamped onto the explicit strategy or
        every search candidate by ``auto_accelerate``."""
        names = ()
        if self.tcfg.comm_overlap:
            names += ("comm_overlap",)
        if self.tcfg.grad_compress == "auto":
            names += ("grad_compress_auto",)
        elif self.tcfg.grad_compress != "none":
            names += ("grad_compress",)
        return names

    def _setup_grad_sync(self, measure: bool = True):
        """(Re)plan the bucketed sync for the CURRENT mesh: resolve the
        plan, attach the error-feedback residual when compressing, and
        surface the plan's wire accounting through PipelineStats. A
        resize re-runs this — bucket padding and the residual's shapes
        depend on the dp degree, so the plan is per-world —
        with ``measure=False``: the timing probe compiles a standalone
        sync program, which must not ride the resize downtime window."""
        from dlrover_tpu.parallel.grad_sync import (
            ensure_residual,
            estimate_overlap_pct,
            export_compress_metrics,
            measure_sync_legs_ms,
            measure_sync_ms,
            resolve_plan,
        )

        plan = resolve_plan(self.cfg, self.accel.strategy)
        self._grad_sync_plan = plan
        stats = self.pipeline_stats
        # the chosen path is visible state, not an HLO-only fact: the
        # bench and the metrics registry (grad_sync_explicit gauge via
        # fold_pipeline_stats) can now see a mesh losing the fast path
        stats.grad_sync_path = "explicit" if plan is not None else "gspmd"
        # mode/density gauges cover the plan-None case too (mode 0 =
        # uncompressed GSPMD), so a downgrade is visible as a gauge
        # step-change rather than a silently missing series
        export_compress_metrics(plan, self._registry)
        if plan is None:
            # resolve_plan already emitted the once-per-mesh fallback
            # log when the explicit path was requested — the single
            # gate owns that visibility
            return
        self.state = ensure_residual(self.state, plan, self.mesh)
        stats.grad_bytes_raw = plan.raw_bytes
        stats.grad_bytes_wire = plan.wire_bytes
        stats.comm_overlap_pct = estimate_overlap_pct(
            self.accel.strategy
        )
        if measure:
            try:
                # the sync's standalone roofline (one small compile;
                # the in-step cost is this minus what the scheduler
                # overlaps), split per link class for two-level plans.
                # Two-level: the legs probe already times the full
                # sync for its "all" leg — reuse ici+dcn as the total
                # instead of compiling and timing it a second time
                if plan.two_level:
                    stats.grad_sync_ici_ms, stats.grad_sync_dcn_ms = (
                        measure_sync_legs_ms(plan, self.mesh, iters=3)
                    )
                    stats.grad_sync_ms = (
                        stats.grad_sync_ici_ms + stats.grad_sync_dcn_ms
                    )
                else:
                    stats.grad_sync_ms = measure_sync_ms(
                        plan, self.mesh, iters=3
                    )
                    stats.grad_sync_ici_ms = stats.grad_sync_ms
                    stats.grad_sync_dcn_ms = 0.0
            except Exception as e:
                logger.warning(
                    f"grad-sync timing probe failed: {e!r}"
                )
        logger.info(f"grad sync: {plan.describe()}")

    # -- step-budget audit (obs/audit.py) -------------------------------
    def _setup_audit_budget(self):
        """Assemble the per-component :class:`StepBudget` for the
        CURRENT world and hand it to the auditor. Called at startup and
        after every resize (the ici/dcn split and the host-transfer
        demand are per-world facts). Components the trainer cannot
        price cheaply (compute, data_wait) stay 0.0 — the auditor
        adopts their warmup-mean observation as the budget instead."""
        import jax

        from dlrover_tpu.parallel import transfer_sched
        from dlrover_tpu.parallel.grad_sync import (
            OVERLAP_HIDDEN_FRACTION,
            comm_time_legs_s,
        )

        try:
            if not self._audit_cal_loaded and self._link_fp:
                # warm restart on the same hardware: start from the
                # persisted per-component drift instead of re-learning
                cal = load_audit_calibration(self._link_fp)
                if cal is not None:
                    self._auditor.apply_calibration(cal)
                self._audit_cal_loaded = True
            budget = StepBudget()
            param_bytes = 0
            itemsize = 4
            for x in jax.tree_util.tree_leaves(self.state.params):
                if hasattr(x, "dtype"):
                    param_bytes += x.size * x.dtype.itemsize
                    itemsize = x.dtype.itemsize
            ici_s, dcn_s = comm_time_legs_s(
                param_bytes,
                self.accel.strategy,
                grad_itemsize=itemsize,
            )
            # the explicit bucketed path overlaps most of the wire time
            # behind compute; only the exposed remainder is step time
            exposed = (
                1.0 - OVERLAP_HIDDEN_FRACTION
                if self._grad_sync_plan is not None
                else 1.0
            )
            budget.set_component("ici_sync", ici_s * exposed, "priced")
            budget.set_component("dcn_sync", dcn_s * exposed, "priced")
            budget.set_component(
                "host_xfer",
                transfer_sched.aggregate_host_exposed_s(),
                "priced",
            )
            self._auditor.set_budget(budget)
            # the sync legs run inside the jitted step (no per-step
            # spans) — feed the probe-measured wall times as the
            # standing observation for those components
            stats = self.pipeline_stats
            if stats.grad_sync_ici_ms:
                self._auditor.set_measured(
                    "ici_sync", stats.grad_sync_ici_ms / 1e3 * exposed
                )
            if stats.grad_sync_dcn_ms:
                self._auditor.set_measured(
                    "dcn_sync", stats.grad_sync_dcn_ms / 1e3 * exposed
                )
        except Exception as e:
            logger.warning(f"audit budget assembly failed: {e!r}")

    def _on_audit_alarm(self, component: str, ratio: float, detail: str):
        """Sustained regression: capture forensics at the moment the
        detector fires, and leave a breadcrumb in the recorder's event
        log so later dumps carry the attribution too."""
        self._flight.note_event("audit_regression", detail)
        self._flight.dump(
            "audit_regression",
            extra={
                "component": component,
                "ratio": round(ratio, 3),
                "detail": detail,
            },
        )

    # -- silent-data-corruption defense (parallel/sdc.py, ISSUE 20) ----
    def _setup_sdc(self):
        """Build the tier-1 detector + tier-2 probe for the CURRENT
        world (lane count = the sync plan's device total). Re-run after
        a resize — the lane axis is per-world. Detection needs the
        explicit dp-family sync path: that is where the per-lane norm
        vector falls out of the bucket walk for free."""
        from dlrover_tpu.parallel import sdc as sdc_mod

        self._sdc: Optional[sdc_mod.SdcDetector] = None
        self._sdc_probe = None
        # 1-step-delayed (step, loss_ref, norms_ref): the freshly
        # dispatched step's outputs stay on device; the PREVIOUS
        # step's are already materialized by dispatch depth, so the
        # fetch adds no host sync to the critical path
        self._sdc_pending = None
        self._sdc_halt = False
        self.sdc_convicted: tuple = ()
        self.sdc_detect_step: Optional[int] = None
        if not (self.tcfg.sdc_detect or sdc_mod.enabled()):
            return
        plan = self._grad_sync_plan
        if (
            plan is None
            or getattr(plan, "three_d", False)
            or getattr(plan, "kind", "") == "ep"
        ):
            logger.warning(
                "sdc detection requested but this mesh has no per-lane"
                " norm path (needs the explicit dp/ZeRO/tp sync plan —"
                " comm_overlap or grad_compress); fences disabled"
            )
            return
        cfg = sdc_mod.SdcConfig(
            window=self.tcfg.sdc_window,
            min_history=self.tcfg.sdc_min_history,
            spike_sigma=self.tcfg.sdc_spike_sigma,
            suspect_sigma=self.tcfg.sdc_suspect_sigma,
            audit_steps=sdc_mod.audit_steps_from_env(
                self.tcfg.sdc_audit_steps
            ),
        )
        self._sdc = sdc_mod.SdcDetector(plan.total, cfg)
        # lane i of the norm vector is device i of the mesh's stacked
        # data axes — the probe must vote over the same ordering
        self._sdc_probe = sdc_mod.AuditProbe(
            devices=list(self.mesh.devices.flatten())
        )
        logger.info(
            f"sdc defense armed: {plan.total} lanes, window "
            f"{cfg.window}, suspect sigma {cfg.suspect_sigma}, audit "
            f"cadence {cfg.audit_steps or 'on-suspicion'}"
        )

    def _sdc_step(self, step: int, metrics: Dict, dev_norms):
        """One detector observation per step (1-step delayed). Tier-1
        verdicts route: data spike → count + log + black-box event
        (never escalates — satellite 3's false-positive gate); device
        suspect → tier-2 paired audit; audit conviction → tier-3
        response (:meth:`_sdc_convict`)."""
        # graftlint fault-site coverage + control-kind composability:
        # device.sdc control kinds (delay — "the bad chip is also
        # slow") fire here; the scale kind itself is a data kind baked
        # into the step at trace time (models/train.py)
        faults.fire("device.sdc")
        pending, self._sdc_pending = self._sdc_pending, (
            (step, metrics.get("loss"), dev_norms)
            if dev_norms is not None
            else None
        )
        if pending is None:
            return
        p_step, p_loss, p_norms = pending
        try:
            loss = float(p_loss)
            norms = np.asarray(p_norms, dtype=np.float64).reshape(-1)
        except Exception as e:
            logger.warning(
                f"sdc: fetching step {p_step} telemetry failed: {e!r}"
            )
            return
        verdict = self._sdc.observe(p_step, loss, norms)
        suspects: tuple = ()
        if verdict.kind == "data_spike":
            self._registry.counter(
                "dlrover_sdc_data_spikes_total",
                "steps classified as data spikes (skipped, not escalated)",
            ).inc()
            detail = (
                f"step {p_step} (batch at sampler position "
                f"{self.sampler.state_dict().get('completed_num', -1)})"
                f": {verdict.detail}"
            )
            self._flight.note_event("sdc_data_spike", detail)
            logger.warning(f"sdc data spike, skip-and-log: {detail}")
        elif verdict.kind == "device_suspect":
            self._registry.counter(
                "dlrover_sdc_suspicions_total",
                "tier-1 device-suspect verdicts (escalated to audit)",
            ).inc()
            if self.sdc_detect_step is None:
                self.sdc_detect_step = p_step
            logger.warning(
                f"sdc device suspect at step {p_step}: lanes "
                f"{list(verdict.suspects)} ({verdict.detail})"
            )
            suspects = verdict.suspects
        cadence = self._sdc.cfg.audit_steps
        if suspects or (cadence and p_step % cadence == 0):
            self._registry.counter(
                "dlrover_sdc_audits_run_total",
                "tier-2 paired-device audit probes executed",
            ).inc()
            result = self._sdc_probe.run(p_step, suspects=suspects)
            if result.convicted:
                self._sdc_convict(p_step, result, verdict)
            elif suspects and not result.inconclusive:
                logger.info(
                    f"sdc audit cleared lanes {list(suspects)} at step "
                    f"{p_step} (bitwise agreement across rotated pairs)"
                )

    def _sdc_convict(self, step: int, result, verdict):
        """Tier-3 response: evidence bundle (norm history + vote
        matrix), ``sdc_conviction`` event to the master/Brain, verified
        rollback with the downtime booked to ``restart_replay``, then
        HALT this incarnation — the injected corruption is baked into
        the compiled step (exactly like a real bad chip is baked into
        the hardware), so the quarantine-drain model applies: the
        master excludes the convicted host and the next world
        re-assembles without it."""
        import json as _json

        from dlrover_tpu.parallel.grad_sync import ensure_residual

        self.sdc_convicted = tuple(result.convicted)
        evidence = {
            "step": step,
            "convicted": list(result.convicted),
            "votes": {
                str(lane): [[p, bool(a)] for p, a in vv]
                for lane, vv in result.votes.items()
            },
            "digests": list(result.digests),
            "suspect_detail": verdict.detail if verdict else "",
            "norm_history": self._sdc.history(),
        }
        self._registry.counter(
            "dlrover_sdc_convictions_total",
            "devices convicted by the paired audit vote",
        ).inc(len(result.convicted))
        self._flight.note_event(
            "sdc_conviction",
            f"lanes {list(result.convicted)} at step {step}",
        )
        self._flight.dump("sdc_conviction", extra=evidence, force=True)
        if self._event_reporter is not None:
            try:
                self._event_reporter(
                    "sdc_conviction", _json.dumps(evidence)
                )
            except Exception as e:
                logger.warning(f"sdc conviction report failed: {e!r}")
        # PR-19 interop: the rollback stall and the replayed window are
        # deliberate — the hang watchdog must not dump a bundle for
        # them, and the step auditor must not reconcile pre-rollback
        # spans against the post-rollback budget
        self._flight.suppress_watchdog(120.0)
        rolled_to = -1
        if self._ckptr is not None:
            self._goodput.replay_begin()
            try:
                tgt, restored = self._ckptr.load_checkpoint(
                    self._ckpt_state()
                )
                if restored is not None and tgt >= 0:
                    self.state = ensure_residual(
                        restored["train"], self._grad_sync_plan, self.mesh
                    )
                    self.sampler.load_state_dict(restored["sampler"])
                    rolled_to = tgt
                    lost = max(0, step - tgt)
                    self._registry.gauge(
                        "dlrover_sdc_rollback_steps_lost",
                        "steps discarded by the last SDC rollback",
                    ).set(lost)
                else:
                    logger.error(
                        "sdc conviction: no verified checkpoint to "
                        "roll back to — halting with corrupt state "
                        "DISCARDED by the restart"
                    )
            finally:
                self._goodput.replay_end()
        logger.error(
            f"sdc conviction at step {step}: lanes "
            f"{list(result.convicted)} convicted"
            + (
                f"; rolled back to verified step {rolled_to}"
                if rolled_to >= 0
                else ""
            )
            + "; halting for quarantine-drain"
        )
        # the detector's window described the corrupted trajectory and
        # the auditor's recorded spans the pre-rollback incarnation
        self._sdc.reset()
        self._auditor.skip_to_now()
        self._sdc_pending = None
        self._sdc_halt = True

    def _maybe_rebalance_experts(self, load) -> bool:
        """Fold one measured per-expert routing-load vector into the
        ``CapacityRebalancer``; when the re-split changed, rebuild the
        train step with the new ``cfg.capacity_splits`` (static
        shapes — one recompile through the AOT cache, amortized over
        ``moe_rebalance_interval``). Returns True when a re-split was
        applied."""
        from dataclasses import replace as dc_replace

        reb = self._moe_rebalancer
        if reb is None:
            return False
        reb.observe(np.asarray(load))
        m = self.accel.strategy.mesh
        shards = max(m.dp * m.fsdp * m.sp, 1)
        tokens = max(
            1, self.tcfg.batch_size * self.tcfg.seq_len // shards
        )
        splits = reb.splits(tokens)
        if tuple(splits) == tuple(self._model_cfg.capacity_splits):
            return False
        self._model_cfg = dc_replace(
            self._model_cfg, capacity_splits=splits
        )
        logger.info(
            f"moe capacity re-split #"
            f"{self.pipeline_stats.moe_capacity_resplits + 1}: "
            f"{splits} (load EMA "
            f"{np.round(reb.load, 3).tolist()}); rebuilding the step"
        )
        devices = list(self.mesh.devices.flatten())
        accel = auto_accelerate(
            self._model_cfg,
            self._tx,
            batch=self.tcfg.batch_size,
            seq=self.tcfg.seq_len,
            devices=devices,
            strategy=self.accel.strategy,
            donate=False,
            grad_accum=self.tcfg.grad_accum,
        )
        self.accel = accel
        self.cfg = accel.cfg
        self._step_fn = accel.step_fn
        self._donating_step_fn = (
            accel.donating_step_fn
            if self.tcfg.donation_aware
            else None
        )
        self._eval_step_fn = None
        self._aot_exec = self._aot_shapes = None
        self._aot_primed = False
        self.pipeline_stats.moe_capacity_resplits += 1
        self._registry.gauge(
            "dlrover_moe_capacity_resplits",
            "applied MoE capacity re-splits",
        ).set(float(self.pipeline_stats.moe_capacity_resplits))
        return True

    def measure_realized_overlap(self, iters: int = 3) -> Optional[float]:
        """A/B-measure how much of the sync's wire time the scheduler
        actually hides. The baseline twin uses GSPMD's monolithic
        schedule, which serializes its sync after the last backward op
        (the PR-3 premise this whole module exists to fix) — so the
        *sync-free* step time is approximately ``baseline -
        standalone_roofline``, and the explicit step's exposed sync is
        what it runs above that. Writes ``PipelineStats.overlap_pct_
        measured`` (the measured twin of the analytic
        ``comm_overlap_pct``) and returns it. Opt-in — it costs one
        extra step compile, so it is a diagnostic call / bench hook,
        not startup work."""
        import jax

        from dlrover_tpu.models.train import build_train_step
        from dlrover_tpu.parallel.grad_sync import (
            measured_overlap_pct,
            strip_residual,
        )

        plan = self._grad_sync_plan
        stats = self.pipeline_stats
        if plan is None or not stats.grad_sync_ms:
            return None
        s = self.accel.strategy
        base_step = build_train_step(
            self.cfg, self.mesh, self._tx, donate=False,
            grad_accum=s.grad_accum, batch_pad=s.batch_pad,
        )
        rng = np.random.default_rng(0)
        x = rng.integers(
            0, self.cfg.vocab_size,
            (self.tcfg.batch_size + s.batch_pad, self.tcfg.seq_len),
        ).astype(np.int32)
        b = shard_batch({"x": x, "y": x}, self.mesh)

        def _time(fn, state):
            st, _ = fn(state, b["x"], b["y"])  # compile + warmup
            jax.block_until_ready(st.params)
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                st, _ = fn(state, b["x"], b["y"])
                jax.block_until_ready(st.params)
                times.append(time.perf_counter() - t0)
            return float(np.median(times) * 1e3)

        with span("grad_sync_overlap_probe"):
            with_ms = _time(self._step_fn, self.state)
            gspmd_ms = _time(
                base_step, strip_residual(self.state)
            )
        # the GSPMD baseline carries its own monolithic sync fully
        # serialized; subtracting the standalone roofline approximates
        # the sync-free step the pure function normalizes against
        stats.overlap_pct_measured = measured_overlap_pct(
            stats.grad_sync_ms, with_ms,
            gspmd_ms - stats.grad_sync_ms,
        )
        logger.info(
            f"grad sync realized overlap: {stats.overlap_pct_measured}%"
            f" (step {with_ms:.2f} ms explicit vs {gspmd_ms:.2f} ms "
            f"gspmd, standalone {stats.grad_sync_ms:.2f} ms)"
        )
        return stats.overlap_pct_measured

    # -- checkpoint ----------------------------------------------------
    def _rewound_sampler_state(self, samp: Dict, buffered: int) -> Dict:
        """Sampler state rewound by ``buffered`` prefetched batches: the
        prefetcher's source cursor ran ahead of what actually trained,
        so a restore (or a resize that drops the buffer) must replay
        those batches instead of skipping them."""
        samp = dict(samp)
        # owned samples to replay; the sampler converts to global
        # positions per its dealing mode (equal round-robin vs
        # throughput-weighted)
        completed = self.sampler.rewound_completed(
            samp["completed_num"],
            buffered * self.dataloader.batch_size,
        )
        if completed < 0 and samp["epoch"] > 0:
            # the sampler already rolled over (its iterator exhausts
            # depth batches before the consumer does) but the buffered
            # epoch-tail has not trained: rewind ACROSS the rollover,
            # or a restore would skip it
            samp["epoch"] -= 1
            completed += self.sampler._epoch_total()
        # a short final batch makes the rewind an over-estimate;
        # clamping repeats a few samples, which is the safe direction
        # (never skip)
        samp["completed_num"] = max(0, completed)
        return samp

    def _ckpt_state(self):
        from dlrover_tpu.parallel.grad_sync import strip_residual

        samp = self.sampler.state_dict()
        buffered = (
            self._prefetcher.buffered_batches()
            if self._prefetcher is not None
            else 0
        )
        if buffered:
            # rewind the SNAPSHOT (never the live sampler)
            samp = self._rewound_sampler_state(samp, buffered)
        # the error-feedback residual never enters checkpoints: it is
        # per-device noise state tied to the current bucket plan, and
        # dropping it costs one EF-less step after restore, not
        # correctness — while keeping every checkpoint readable by
        # runs with different (or no) grad-sync settings
        return {"train": strip_residual(self.state), "sampler": samp}

    def _maybe_restore(self):
        from dlrover_tpu.agent.monitor import read_runtime_metrics
        from dlrover_tpu.parallel.grad_sync import ensure_residual

        step, restored = self._ckptr.load_checkpoint(self._ckpt_state())
        if restored is not None and step >= 0:
            self.state = ensure_residual(
                restored["train"], self._grad_sync_plan, self.mesh
            )
            self.sampler.load_state_dict(restored["sampler"])
            logger.info(f"resumed from flash checkpoint step {step}")
            # restart-replay accounting: the runtime-metrics file
            # outlives the previous incarnation, so the step it had
            # already published tells us how much progress this restore
            # lost — steps up to it re-earn old work and the goodput
            # ledger books that wall time as restart_replay, not
            # productive_compute
            if self.tcfg.report_metrics:
                prev_step = int(
                    read_runtime_metrics().get("global_step", -1) or -1
                )
                if prev_step > step:
                    self._replay_until_step = prev_step
                    self._goodput.replay_begin()
                    logger.info(
                        f"replaying lost progress: steps {step}.."
                        f"{prev_step} count as restart_replay"
                    )

    def save(self, storage: StorageType = StorageType.MEMORY) -> bool:
        if self._ckptr is None:
            return False
        return self._ckptr.save_checkpoint(
            self.global_step, self._ckpt_state(), storage
        )

    # -- eviction grace-window drain -----------------------------------
    def set_event_reporter(self, reporter: Callable[[str, str], None]):
        """``reporter(event, detail)`` mirrors trainer incidents (the
        ``eviction`` node event) to the master — same seam shape as the
        checkpoint saver's (``MasterClient.report_failure`` at WARNING
        level, or ``report_eviction_notice``)."""
        self._event_reporter = reporter

    def install_eviction_handler(self, grace_s: Optional[float] = None):
        """Register a SIGTERM handler that enters the drain state
        machine (signal-safe: it only sets flags; all real work happens
        at the next step boundary on the train thread). Chains to any
        previous handler. No-op off the main thread — the platform
        signal lands on the main thread anyway."""
        import signal

        grace = (
            float(grace_s)
            if grace_s is not None
            else self.tcfg.eviction_grace_s
        )
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _handler(signum, frame):
                self.request_eviction(grace, reason="sigterm")
                if callable(prev) and prev not in (
                    signal.SIG_IGN, signal.SIG_DFL
                ):
                    prev(signum, frame)

            signal.signal(signal.SIGTERM, _handler)
            logger.info(
                f"eviction SIGTERM handler installed (grace {grace}s)"
            )
        except ValueError:
            # signal.signal only works on the main thread; a trainer
            # constructed elsewhere still drains via the command
            # channel / request_eviction
            logger.warning(
                "not on the main thread: SIGTERM eviction handler not "
                "installed (the `evict` worker command still works)"
            )

    def request_eviction(
        self, grace_s: Optional[float] = None, reason: str = "notice"
    ):
        """Enter the drain state machine at the next step boundary.
        Idempotent (the first notice's deadline stands — a second,
        tighter notice may shorten it but never extend it); safe to
        call from signal handlers and foreign threads."""
        grace = (
            float(grace_s)
            if grace_s is not None and grace_s > 0
            else self.tcfg.eviction_grace_s
        )
        deadline = time.monotonic() + grace
        if self._evict_deadline is None or deadline < self._evict_deadline:
            self._evict_deadline = deadline
            self._evict_grace_s = grace
        if not self._evict_event.is_set():
            self._evict_reason = reason
            self._evict_event.set()
            logger.warning(
                f"eviction notice ({reason}): draining within "
                f"{grace:.1f}s"
            )

    @property
    def eviction_pending(self) -> bool:
        return self._evict_event.is_set() and not self.evicted

    def _drain_for_eviction(self):
        """The drain itself, run on the train thread once the in-flight
        step finished: (1) suppress the hang watchdog — the long stall
        ahead is deliberate; (2) announce the notice (metrics file +
        event seam) so the master can pre-arm the resize while we
        drain; (3) emergency shm checkpoint of the CURRENT step via the
        ChunkedStager fast path, budgeted to the grace window; (4) DISK
        persist only if the window comfortably allows (shm handoff
        covers the tight case); (5) book the whole window to the
        ``eviction`` goodput category and flush flight recorder +
        runtime metrics before returning control to the caller."""
        t0 = time.perf_counter()
        t0_ns = time.monotonic_ns()
        deadline = self._evict_deadline or (
            time.monotonic() + self.tcfg.eviction_grace_s
        )
        grace = self._evict_grace_s or self.tcfg.eviction_grace_s
        step = self.global_step
        self._goodput.eviction_begin()
        try:
            # the drain runs no train compute: mark the arbiter idle so
            # a co-located serving plane may soak the grace window (its
            # transfers stay BACKGROUND — the emergency stage's
            # EMERGENCY chunks still preempt them on the rails)
            transfer_sched.note_compute(False)
            self._flight.suppress_watchdog(grace + 60.0)
            self._flight.note_event(
                "eviction",
                f"{self._evict_reason}: grace={grace:.1f}s step={step}",
            )
            # announce FIRST: the master's proactive resize (rendezvous
            # exclusion, speculative n-1 compile) runs while we drain
            if self.tcfg.report_metrics:
                report_runtime_metrics(
                    step,
                    eviction_pending=1.0,
                    eviction_grace_s=float(grace),
                )
            if self._event_reporter is not None:
                try:
                    self._event_reporter(
                        "eviction",
                        f"grace={grace:.1f}s step={step} "
                        f"reason={self._evict_reason}",
                    )
                except Exception as e:
                    logger.warning(f"eviction event report failed: {e!r}")
            # the prefetcher's lookahead dies with us; the checkpoint's
            # sampler snapshot rewinds it (same contract as _ckpt_state)
            committed = False
            persisted = False
            if self._ckptr is not None:
                # a half-staged OLDER step holds the shard lock; the
                # emergency save wants the CURRENT step (nobody saw the
                # stale stage — abort is safe)
                self._abort_stager()
                try:
                    # EMERGENCY link priority: this drain races a platform
                    # kill — its chunks preempt any in-flight background
                    # spill/stage at their next chunk boundary
                    stager = self._ckptr.begin_chunked_save(
                        step,
                        self._ckpt_state(),
                        chunk_bytes=self.tcfg.stage_chunk_mb << 20,
                        priority=transfer_sched.Priority.EMERGENCY,
                    )
                    if stager is not None:
                        # leave a commit-sized margin before the deadline
                        while (
                            not stager.done
                            and time.monotonic() < deadline - 0.5
                        ):
                            stager.advance(
                                budget_s=0.05, stats=self.pipeline_stats
                            )
                        if stager.done:
                            committed = stager.commit(
                                stats=self.pipeline_stats
                            )
                        else:
                            # the window closed mid-stage: commit() would
                            # drain the whole backlog UNBOUNDED and the
                            # platform's kill would land mid-commit —
                            # losing not just this checkpoint but the
                            # forensics flush below. Abort; the previous
                            # committed step stands (bounded loss <= one
                            # save interval, the same contract as a hard
                            # kill)
                            stager.abort()
                            logger.warning(
                                f"eviction: emergency stage incomplete at "
                                f"the deadline; aborted — the previous "
                                f"committed step stands"
                            )
                    else:
                        # saver busy with an uncommitted save: the plain
                        # memory save path skips-never-blocks too
                        committed = self.save(StorageType.MEMORY)
                except Exception as e:
                    logger.error(f"eviction emergency save failed: {e!r}")
                remaining = deadline - time.monotonic()
                if committed and not self._ckptr.engine._agent_mode:
                    # the sync (no-agent) engine's commit already wrote
                    # storage — the shm/persist split only exists under an
                    # agent saver
                    persisted = True
                elif committed and remaining > self.tcfg.eviction_persist_floor_s:
                    try:
                        persisted = self.save(StorageType.DISK)
                    except Exception as e:
                        logger.warning(
                            f"eviction persist skipped ({e!r}); shm "
                            f"handoff covers it"
                        )
                elif committed:
                    logger.info(
                        f"eviction: {remaining:.1f}s left of the grace "
                        f"window — skipping the DISK persist (shm handoff "
                        f"covers it)"
                    )
            self._close_prefetcher()
        finally:
            # the episode MUST close on every path (graftlint
            # span-leak): an exception escaping the drain used to
            # leak the eviction episode open, and the goodput
            # ledger then booked every later second to `eviction`
            drain_ms = (time.perf_counter() - t0) * 1e3
            self.eviction_drain_ms = drain_ms
            self._goodput.eviction_end()
            self.evicted = True
        # flush: goodput + registry + the final runtime-metrics write
        # (carries the measured drain latency the master forwards to
        # the Brain's dwell pricing)
        self._report_metrics(
            step,
            {
                "eviction_pending": 1.0,
                "eviction_grace_s": float(grace),
                "eviction_drain_ms": round(drain_ms, 1),
            },
        )
        if self._event_reporter is not None:
            try:
                self._event_reporter(
                    "eviction",
                    f"grace={grace:.1f}s step={step} "
                    f"drain_ms={drain_ms:.0f} "
                    f"committed={int(committed)} "
                    f"persisted={int(persisted)}",
                )
            except Exception as e:
                logger.warning(f"eviction event report failed: {e!r}")
        self._flight.note_event(
            "eviction_drained",
            f"step={step} drain_ms={drain_ms:.0f} "
            f"committed={int(committed)} persisted={int(persisted)}",
        )
        self._flight.dump(
            "eviction",
            extra={
                "step": step,
                "grace_s": grace,
                "drain_ms": drain_ms,
                "committed": committed,
                "persisted": persisted,
                "eviction_interval": [t0_ns, time.monotonic_ns()],
            },
            force=True,
        )
        logger.warning(
            f"eviction drain complete at step {step}: "
            f"{drain_ms:.0f} ms of a {grace:.1f}s window "
            f"(shm commit={'ok' if committed else 'FAILED'}, "
            f"persist={'ok' if persisted else 'skipped'})"
        )

    # -- loop ----------------------------------------------------------
    @property
    def global_step(self) -> int:
        return int(self.state.step)

    def _device_batch(self, batch, for_eval: bool = False):
        if isinstance(batch, dict):
            bx, by = batch["x"], batch["y"]
        else:  # tuple/list samples from the default collate
            bx, by = batch[0], batch[1]
        pad = self.accel.strategy.batch_pad
        if pad and for_eval:
            # the eval loss takes no row weights, so zero-pad rows
            # would bias it (and save-best/early-stopping built on
            # it); TRIM to the largest shardable row count instead —
            # unbiased, a few samples lighter
            m = self.accel.strategy.mesh
            shards = max(m.dp * m.fsdp, 1)
            n = (int(np.asarray(bx).shape[0]) // shards) * shards
            if n > 0:
                bx = np.asarray(bx)[:n]
                by = np.asarray(by)[:n]
        elif pad:
            # micro-batch rebalance: zero rows appended so the batch
            # divides over ALL ranks; the step's pad_row_weights zero
            # them out of the loss, so gradients match the real batch
            from dlrover_tpu.models.train import pad_batch_rows

            n = int(np.asarray(bx).shape[0]) + pad
            bx = pad_batch_rows(bx, n)
            by = pad_batch_rows(by, n)
        if self.accel.strategy.mesh.pp > 1:
            return bx, by  # pipeline step takes host arrays
        sharded = shard_batch({"x": bx, "y": by}, self.mesh)
        return sharded["x"], sharded["y"]

    # -- eval ----------------------------------------------------------
    def _build_eval_step(self):
        """Eval loss step, memoized per mesh through the compile cache:
        a resize invalidates the stale wrapper, but resizing back to a
        previously-seen mesh reuses the jitted step instead of
        re-tracing (the old behavior re-``jax.jit``-ed after every
        mesh change)."""
        import jax

        from dlrover_tpu.accel.compile_cache import (
            fingerprint,
            mesh_signature,
        )

        cfg, mesh, strategy = self.cfg, self.mesh, self.accel.strategy
        key = fingerprint(
            "eval_step",
            strategy.to_json(),
            mesh_signature(mesh),
            repr(cfg),
        )

        def build():
            if strategy.mesh.pp > 1:
                from dlrover_tpu.parallel.pipeline import (
                    pipeline_loss_fn,
                )

                mb = strategy.num_microbatches
                # the state layout is [pp, v, lc] iff the TRAINING
                # schedule is interleaved — eval must read the same
                # layout. The schedule may live in pp_schedule OR
                # (pre-apply) only in opts; resolved_virtual() honors
                # both sources
                virtual = strategy.resolved_virtual()

                def eval_loss(params, x, y):
                    return pipeline_loss_fn(
                        params, x, y, cfg, mesh, mb, virtual=virtual
                    )

            else:

                def eval_loss(params, x, y):
                    return _dense_eval_loss(params, x, y, cfg, mesh)

            return jax.jit(eval_loss)

        fn, _ = self._compile_cache.get_or_build(key, build)
        return fn

    def _eval_batches(self, max_batches: int):
        """Sequential fixed-size batches over the eval set (no sampler
        elasticity — eval restarts from the top every call)."""
        bs = self.tcfg.batch_size
        n = len(self._eval_dataset)
        for start in range(0, min(max_batches * bs, n - bs + 1), bs):
            rows = [self._eval_dataset[i] for i in range(start, start + bs)]
            if self._collate_fn is not None:
                yield self._collate_fn(rows)
            elif isinstance(rows[0], dict):
                yield {
                    k: np.stack([r[k] for r in rows]) for k in rows[0]
                }
            else:
                yield tuple(
                    np.stack([r[j] for r in rows])
                    for j in range(len(rows[0]))
                )

    def evaluate(self, max_batches: Optional[int] = None) -> Dict[str, float]:
        """Run the eval set through a grad-free sharded loss step.
        Returns {"eval_loss": mean NLL, "eval_ppl": exp(mean NLL)}."""
        if self._eval_dataset is None:
            raise ValueError("ElasticTrainer built without eval_dataset")
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        max_batches = max_batches or self.tcfg.eval_steps
        losses = []
        for batch in self._eval_batches(max_batches):
            x, y = self._device_batch(batch, for_eval=True)
            losses.append(float(self._eval_step_fn(self.state.params, x, y)))
        if not losses:
            # a silent NaN here would poison every later metrics report
            raise ValueError(
                f"eval dataset ({len(self._eval_dataset)} rows) yields "
                f"zero batches of size {self.tcfg.batch_size}"
            )
        mean = float(np.mean(losses))
        return {
            "eval_loss": mean,
            "eval_ppl": float(np.exp(min(mean, 20.0))),
        }

    def _best_sidecar_path(self) -> str:
        return os.path.join(self._best_dir, "best_eval.json")

    def _load_best_sidecar(self) -> float:
        import json

        try:
            with open(self._best_sidecar_path()) as f:
                return float(json.load(f)["eval_loss"])
        except (OSError, ValueError, KeyError):
            return float("inf")

    def _after_eval(self, step: int) -> bool:
        """save-best / early-stopping bookkeeping; True = stop now.

        Two distinct "best" trackers on purpose:

        - ``_run_best_eval_loss`` (reset every train() call) drives the
          patience counter — a restarted run that is still improving
          run-locally must not be stopped just because it hasn't yet
          beaten the historical best it restarted below;
        - ``_best_eval_loss`` is the best PERSISTED loss (sidecar) and
          only advances when a checkpoint actually commits — a save
          skipped by the rate limit stays beatable, so the next
          improvement past the window persists instead of being lost.
        """
        import json

        loss = self._last_eval.get("eval_loss", float("inf"))
        if loss < self._run_best_eval_loss:
            self._run_best_eval_loss = loss
            self._evals_since_best = 0
        else:
            self._evals_since_best += 1
        if (
            self._best_ckptr is not None
            and loss < self._best_eval_loss
            and time.time() - self._last_best_save
            >= self.tcfg.save_best_min_interval_s
        ):
            logger.info(
                f"step {step}: new best eval_loss={loss:.4f}; "
                f"persisting to {self._best_dir}"
            )
            if self._best_ckptr.save_checkpoint(
                step, self._ckpt_state(), StorageType.DISK
            ):
                # the sidecar records the PERSISTED best — written only
                # after the commit, so a crash mid-save cannot leave it
                # claiming a checkpoint that isn't there; durable
                # (fsync-before-rename) because its whole contract is
                # being as durable as the checkpoint it describes
                # (graftlint durable-rename)
                storage.durable_replace(
                    self._best_sidecar_path(),
                    lambda f: json.dump(
                        {"eval_loss": loss, "step": step}, f
                    ),
                )
                self._best_eval_loss = loss
                self._last_best_save = time.time()
        return (
            self.tcfg.early_stopping_patience > 0
            and self._evals_since_best >= self.tcfg.early_stopping_patience
        )

    def current_lr(self) -> Optional[float]:
        """The live EFFECTIVE learning rate (schedule value x the
        master's retune scale) when the optimizer was built with
        ``build_optimizer`` / ``optax.inject_hyperparams``."""
        hp = getattr(self.state.opt_state, "hyperparams", None)
        if hp and "learning_rate" in hp:
            lr = float(hp["learning_rate"])
            if "retune_scale" in hp:
                lr *= float(hp["retune_scale"])
            return lr
        return None

    # -- pipelined transfers -------------------------------------------
    def _epoch_batches(self, num_steps: int):
        """One epoch's (x, y) device batches, prefetched when enabled.

        The prefetcher's source is capped at the steps remaining so its
        lookahead never pulls samples past the run's end from the
        sampler; what it does buffer is rewound in ``_ckpt_state``."""
        import itertools

        src = iter(self.dataloader)
        if self.tcfg.prefetch <= 0:
            self._prefetcher = None
            return (self._device_batch(b) for b in src)
        from dlrover_tpu.data.prefetch import DevicePrefetcher

        self._prefetcher = DevicePrefetcher(
            itertools.islice(
                src, max(num_steps - self.global_step, 0)
            ),
            placement=self._device_batch,
            depth=self.tcfg.prefetch,
            stats=self.pipeline_stats,
        )
        return self._prefetcher

    def _close_prefetcher(self):
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None

    def _step_cache_key(self, strategy, mesh, state_like, batch_like):
        """Compile-cache key of the SAFE train step for one world:
        (strategy fingerprint, mesh shape + device assignment, abstract
        state/batch shapes, donation signature). ``state_like`` and
        ``batch_like`` may be concrete arrays or ShapeDtypeStructs —
        both produce the same key (``tree_signature`` drops
        weak_type), so a speculative pre-lower from specs collides
        with the resize that consumes it. The job-name salt keeps two
        jobs sharing one on-disk cache apart (a key assumes tx was
        constructed identically, which holds within one SPMD job)."""
        from dlrover_tpu.accel.compile_cache import (
            fingerprint,
            mesh_signature,
            tree_signature,
        )
        from dlrover_tpu.common.constants import NodeEnv

        return fingerprint(
            "train_step",
            strategy.to_json(),
            mesh_signature(mesh),
            tree_signature(state_like),
            tree_signature(batch_like),
            "donate=0",
            os.getenv(NodeEnv.JOB_NAME, ""),
        )

    def _batch_specs(self, mesh, strategy=None):
        """Abstract (x, y) for AOT lowering on ``mesh``, from the REAL
        batch avals recorded at the first step — re-padded for the
        target ``strategy``'s micro-batch rebalance (batch_pad differs
        per world, so the same real batch lowers to different physical
        shapes on different strategies)."""
        import jax

        from dlrover_tpu.parallel.mesh import batch_sharding

        pad = int(getattr(strategy, "batch_pad", 0) or 0)
        sh = batch_sharding(mesh)
        return tuple(
            jax.ShapeDtypeStruct(
                (shape[0] + pad,) + tuple(shape[1:]),
                np.dtype(dt),
                sharding=sh,
            )
            for shape, dt in self._batch_avals
        )

    def _aot_supported(self, strategy) -> bool:
        # the pipeline step takes host arrays (different signature) and
        # the offload step's mixed host/device shardings defeat the
        # spec-keyed cache — both keep their lazy jit path
        return strategy.mesh.pp == 1 and not strategy.offload_opt

    def _record_batch_avals(self, x, y):
        """Shapes/dtypes of the live batch — speculative compiles for
        other meshes lower against these. Recorded at the REAL row
        count: a rebalanced strategy's zero-weight pad rows are its
        own physical artifact (``_batch_specs`` re-pads per target
        strategy)."""
        pad = int(getattr(self.accel.strategy, "batch_pad", 0) or 0)
        try:
            self._batch_avals = tuple(
                ((int(b.shape[0]) - pad,) + tuple(b.shape[1:]), str(b.dtype))
                for b in (x, y)
            )
        except (AttributeError, TypeError, IndexError):
            pass

    def _prime_step_cache(self, x, y):
        """First SAFE step on a mesh: route it through the AOT compile
        cache. This replaces (not adds to) the lazy jit compile that
        would happen at this exact moment, but the executable lands in
        a cache that outlives the wrapper a resize throws away — the
        entry is what makes resizing BACK to this mesh warm. Donating
        steps never prime: their twin is a different program, and a
        donation-only run pays no extra compile for a cache it may
        never need (the resize itself populates it then)."""
        self._aot_primed = True
        strategy = self.accel.strategy
        if not self._aot_supported(strategy):
            return
        step_fn, state = self._step_fn, self.state
        key = self._step_cache_key(strategy, self.mesh, state, (x, y))
        try:
            with span("compile_prime"):
                fn, _ = self._compile_cache.get_or_compile(
                    key, lambda: step_fn.lower(state, x, y).compile()
                )
            self._install_aot(fn, (x.shape, y.shape))
        except Exception as e:
            # AOT is an optimization: a lowering quirk must not take
            # down training — the lazy jit path still works
            logger.warning(f"AOT step-cache priming failed: {e!r}")

    def _install_aot(self, exec_fn, shapes):
        self._aot_exec = exec_fn
        self._aot_shapes = tuple(tuple(s) for s in shapes)

    def _safe_step_for(self, x, y):
        """The non-donating step for THIS batch: the AOT executable when
        the shapes match what it was lowered for, else the jit wrapper —
        a Compiled rejects differing avals where jit retraces, and both
        the dataloader's short final batch and a master-retuned batch
        size legitimately change the shape mid-run."""
        if self._aot_exec is not None and self._aot_shapes == (
            tuple(x.shape), tuple(y.shape)
        ):
            return self._aot_exec
        return self._step_fn

    def _run_step(self, x, y):
        """One optimizer step, donation-aware: donate the state and the
        batch whenever no checkpoint staging is reading the buffers."""
        if self._batch_avals is None:
            self._record_batch_avals(x, y)
        donate = (
            self._donating_step_fn is not None
            and self._stager is None
            and (
                self._ckptr is None
                or not self._ckptr.staging_in_flight()
            )
            and (
                self._best_ckptr is None
                or not self._best_ckptr.staging_in_flight()
            )
        )
        if not donate and not self._aot_primed:
            self._prime_step_cache(x, y)
        fn = (
            self._donating_step_fn
            if donate
            else self._safe_step_for(x, y)
        )
        stats = self.pipeline_stats
        if donate:
            stats.donated_steps += 1
            stats.donated_bytes += self._state_nbytes + sum(
                getattr(b, "nbytes", 0) for b in (x, y)
            )
        else:
            stats.safe_steps += 1
        self.state, metrics = fn(self.state, x, y)
        return metrics

    def _advance_stager(self):
        """Drain one budget's worth of checkpoint chunks off the step
        cadence; commit (cheap: metadata publish + agent notify) once
        the backlog is empty."""
        if self._stager is None:
            return
        self._stager.advance(
            budget_s=self.tcfg.stage_budget_ms / 1e3,
            stats=self.pipeline_stats,
        )
        if self._stager.done:
            self._stager.commit(stats=self.pipeline_stats)
            self._stager = None

    def _finish_stager(self):
        """The commit barrier: drain whatever is left and publish."""
        if self._stager is not None:
            self._stager.commit(stats=self.pipeline_stats)
            self._stager = None

    def _abort_stager(self):
        if self._stager is not None:
            self._stager.abort()
            self._stager = None

    def _maybe_save(self, step: int):
        if self._ckptr is None:
            return
        if step % self.tcfg.save_storage_interval == 0:
            # the disk save supersedes any half-staged older step:
            # abort it (nobody saw it — metadata is still invalid) so
            # the shard lock is free for the synchronous staging
            self._abort_stager()
            self.save(StorageType.DISK)
        elif step % self.tcfg.save_memory_interval == 0:
            if not self.tcfg.chunked_staging:
                self.save(StorageType.MEMORY)
            elif self._stager is None:
                # a previous stage still draining keeps draining — skip
                # this interval rather than stall on a forced commit
                # (same skip-never-block contract as save_to_memory)
                self._stager = self._ckptr.begin_chunked_save(
                    step,
                    self._ckpt_state(),
                    chunk_bytes=self.tcfg.stage_chunk_mb << 20,
                )

    # -- elastic resize (fast path) ------------------------------------
    def _strategy_for_exact(self, n_devices: int) -> Optional[Strategy]:
        """Strategy using EXACTLY ``n_devices``, or None. Model-
        parallel axes (tp/sp/ep/pp) are divisibility choices of the
        MODEL and keep their sizes; the data axes (dp, fsdp) absorb
        the device delta. When the current shape cannot scale, falls
        back to full candidate enumeration."""
        from dataclasses import replace as dc_replace

        s = self.accel.strategy
        m = s.mesh
        fixed = m.tp * m.sp * m.ep * m.pp
        if n_devices <= 0:
            return None
        if n_devices % fixed == 0:
            rem = n_devices // fixed
            if m.fsdp == 1:
                dp, fsdp = rem, 1
            elif m.dp == 1:
                dp, fsdp = 1, rem
            else:
                # mixed split: keep as much fsdp (the memory win) as
                # divides the remainder
                fsdp = min(m.fsdp, rem)
                while rem % fsdp:
                    fsdp -= 1
                dp = rem // fsdp
            unit = self.tcfg.batch_size // max(self.tcfg.grad_accum, 1)
            if unit % (dp * fsdp) == 0:
                return dc_replace(
                    s, mesh=dc_replace(m, dp=dp, fsdp=fsdp)
                )
        from dlrover_tpu.accel.candidates import candidate_strategies

        cands = [
            c
            for c in candidate_strategies(
                self._model_cfg,
                n_devices,
                self.tcfg.batch_size,
                self.tcfg.seq_len,
                grad_accum=self.tcfg.grad_accum,
            )
            if c.mesh.pp == 1
        ]
        if not cands:
            return None
        return dc_replace(
            cands[0],
            dtype=s.dtype,
            remat=s.remat,
            opts=s.opts,
            offload_opt=s.offload_opt,
            # field-carried grad-sync knobs survive the fallback too
            # (opts cover the trainer-knob path; an explicit Strategy
            # may carry them ONLY as fields)
            comm_overlap=s.comm_overlap,
            grad_compress=s.grad_compress,
            grad_bucket_mb=s.grad_bucket_mb,
            grad_topk_density=s.grad_topk_density,
        )

    def _rebalanced_strategy_for(
        self, n_devices: int
    ) -> Optional[Strategy]:
        """Micro-batch-rebalanced strategy using ALL ``n_devices`` on
        an indivisible count: the data axes absorb the delta and the
        batch is padded with ``batch_pad`` zero-weight rows so it
        divides (heavier ranks effectively take one extra micro-batch
        row; the pads land on the trailing ranks and carry loss
        weight 0, so gradients are those of the real batch). None
        when the count is exactly divisible (the exact path owns it),
        the model axes don't divide ``n_devices``, or the trainer
        runs grad_accum (pads would multiply across microbatches)."""
        from dataclasses import replace as dc_replace

        if not self.tcfg.mb_rebalance or self.tcfg.grad_accum > 1:
            return None
        if self._model_cfg.num_experts:
            # pad rows would contaminate the router's aux losses (see
            # build_train_step's batch_pad guard)
            return None
        s = self.accel.strategy
        m = s.mesh
        fixed = m.tp * m.sp * m.ep * m.pp
        if n_devices <= 0 or n_devices % fixed or m.pp > 1:
            return None
        rem = n_devices // fixed
        if m.fsdp == 1:
            dp, fsdp = rem, 1
        elif m.dp == 1:
            dp, fsdp = 1, rem
        else:
            fsdp = min(m.fsdp, rem)
            while rem % fsdp:
                fsdp -= 1
            dp = rem // fsdp
        shards = dp * fsdp
        pad = (-self.tcfg.batch_size) % shards
        if pad == 0:
            return None  # divisible: _strategy_for_exact handles it
        return dc_replace(
            s,
            mesh=dc_replace(m, dp=dp, fsdp=fsdp),
            batch_pad=pad,
        )

    def _strategy_for(self, n_devices: int) -> Strategy:
        """Strategy for a resized world, degrading gracefully: on a
        non-divisible count (e.g. 6 of 8 devices at batch 16) the
        trainer prices BOTH alternatives through the dry-runner —
        (a) the largest valid mesh <= ``n_devices`` with the surplus
        ranks idle, and (b) the micro-batch rebalance using every
        rank with a padded batch (``_rebalanced_strategy_for``) —
        and the cheaper wins. ``resize`` trims the device list, logs
        the choice and sets the ``dlrover_resize_idle_ranks`` /
        ``dlrover_resize_mb_pad`` gauges (NOT set here — this is also
        the speculative-compile path, and a hypothetical candidate
        must not corrupt the live metric). The descending scan is
        pure-Python candidate enumeration (no compiles), so even an
        exhaustive miss costs milliseconds. Raises a clear ValueError
        only when NO device count down to 1 admits a valid mesh
        (never a crash deep inside ``build_mesh``)."""
        from dataclasses import replace as dc_replace

        for n in range(n_devices, 0, -1):
            s = self._strategy_for_exact(n)
            if s is None:
                continue
            # the current strategy may carry a pad from a previous
            # rebalance; an exact fit needs none
            if s.batch_pad:
                s = dc_replace(s, batch_pad=0)
            if n < n_devices:
                reb = self._rebalanced_strategy_for(n_devices)
                if reb is not None:
                    from dlrover_tpu.accel.dry_runner import (
                        price_rebalance_options,
                    )

                    measured = (
                        self._step_time_sum / self._step_time_n
                        if self._step_time_n
                        else None
                    )
                    idle_s, reb_s = price_rebalance_options(
                        self._model_cfg,
                        self.tcfg.batch_size,
                        self.tcfg.seq_len,
                        s,
                        reb,
                        measured_step_s=measured,
                        current_strategy=self.accel.strategy,
                    )
                    if reb_s < idle_s:
                        logger.info(
                            f"micro-batch rebalance: padding the "
                            f"batch by {reb.batch_pad} rows to use "
                            f"all {n_devices} devices "
                            f"({reb.mesh.axis_sizes()}, est "
                            f"{reb_s * 1e3:.2f} ms/step) instead of "
                            f"idling {n_devices - n} rank(s) "
                            f"(est {idle_s * 1e3:.2f} ms/step)"
                        )
                        return reb
                    logger.info(
                        f"micro-batch rebalance priced out (pad "
                        f"{reb.batch_pad} rows, est "
                        f"{reb_s * 1e3:.2f} ms/step vs idle "
                        f"{idle_s * 1e3:.2f}); degrading instead"
                    )
                logger.info(
                    f"no valid mesh factorization uses all "
                    f"{n_devices} devices at batch="
                    f"{self.tcfg.batch_size}; degrading to "
                    f"{s.mesh.axis_sizes()} on {n} devices"
                )
            return s
        raise ValueError(
            f"no valid mesh factorization for any count <= {n_devices} "
            f"devices at batch={self.tcfg.batch_size}, "
            f"seq={self.tcfg.seq_len}: the resize target must let "
            f"dp*fsdp divide the batch or satisfy the model's "
            f"axis-divisibility rules"
        )

    def resize(
        self, n_devices: Optional[int] = None, devices=None,
        strategy: Optional[Strategy] = None,
    ) -> Dict[str, Any]:
        """Live reconfiguration to a new device world WITHOUT a restart.

        The fast path: (1) the prefetcher is closed FIRST — its
        buffered device copies pin old-mesh arrays and its producer
        thread could keep placing onto the dying mesh mid-reshard —
        and the live sampler is rewound by the dropped lookahead so no
        sample is skipped; (2) any in-flight chunked checkpoint stage
        is committed (its barrier) so nothing reads old buffers; (3)
        the accel artifacts are rebuilt for the new mesh (explicit
        strategy — no search) and the safe step comes out of the AOT
        compile cache, which a speculative pre-lower or an earlier
        visit to this mesh makes a HIT (no XLA compile in the downtime
        window); (4) live state is remapped shard-by-shard on device
        (``ckpt/reshard.py``) — only leaves with no surviving local
        source fall back to the shm/storage restore.

        Single-process scope: the sampler's replica split is
        per-process and unchanged here; multi-process resizes
        re-rendezvous through the agent and land in ``__init__``'s
        restore path instead. Returns a dict of timings/counters (the
        bench's ``resize_downtime_*`` keys)."""
        import jax

        t0 = time.perf_counter()
        if devices is None:
            devices = (
                list(jax.devices())[:n_devices]
                if n_devices
                else list(jax.devices())
            )
        devices = list(devices)
        if self.accel.strategy.mesh.pp > 1:
            raise ValueError(
                "resize fast path requires a pp=1 current strategy "
                "(pipeline state has its own layout); restart instead"
            )
        idle_ranks = 0
        if strategy is None:
            strategy = self._strategy_for(len(devices))
            if strategy.mesh.num_devices < len(devices):
                # graceful degradation: the largest valid mesh won;
                # the surplus ranks sit idle this incarnation
                idle_ranks = len(devices) - strategy.mesh.num_devices
                devices = devices[: strategy.mesh.num_devices]
        if strategy.mesh.num_devices != len(devices):
            raise ValueError(
                f"strategy mesh needs {strategy.mesh.num_devices} "
                f"devices, resize got {len(devices)}"
            )
        if not self._aot_supported(strategy):
            raise ValueError(
                "resize fast path supports pp=1, non-offload "
                "strategies; restart for pipeline/offload changes"
            )
        # stat/gauge writes only after every validation that can still
        # abort this resize — a raise above must not leave dashboards
        # claiming idle ranks for a world that was never built
        if idle_ranks:
            logger.warning(
                f"resize: degrading to {strategy.mesh.num_devices} "
                f"of {strategy.mesh.num_devices + idle_ranks} devices "
                f"({strategy.mesh.axis_sizes()}), leaving "
                f"{idle_ranks} rank(s) idle"
            )
        if strategy.batch_pad:
            logger.info(
                f"resize: micro-batch rebalance active — batch padded "
                f"by {strategy.batch_pad} zero-weight rows so "
                f"{strategy.mesh.axis_sizes()} uses every rank "
                f"(resize_idle_ranks=0)"
            )
        self.pipeline_stats.resize_idle_ranks = idle_ranks
        self.pipeline_stats.resize_mb_pad = strategy.batch_pad
        self._registry.gauge(
            "dlrover_resize_idle_ranks",
            "devices left idle by resize degradation",
        ).set(float(idle_ranks))
        self._registry.gauge(
            "dlrover_resize_mb_pad",
            "zero-weight pad rows/step of the micro-batch rebalance",
        ).set(float(strategy.batch_pad))
        # a resize is a DELIBERATE stall: the hang watchdog must not
        # dump forensics of a cold compile that is working as designed
        # (cleared on success below; a raise lets the window lapse — a
        # resize that died mid-world-change masks real hangs for at
        # most this long)
        self._flight.suppress_watchdog(600.0)
        # stale scale predictions are worthless now — and the resize
        # owns the compile budget
        if self._spec_compiler is not None:
            self._spec_compiler.submit(())
        # the whole resize window is device-idle: refresh the arbiter's
        # out-of-compute mark so the co-located serving plane's idle-gap
        # gate opens NOW instead of waiting out the mark TTL
        transfer_sched.note_compute(False)
        # (1) prefetcher down BEFORE any reshard: see docstring
        with span("resize_drain"):
            buffered = (
                self._prefetcher.buffered_batches()
                if self._prefetcher is not None
                else 0
            )
            self._close_prefetcher()
            if buffered:
                self.sampler.load_state_dict(
                    self._rewound_sampler_state(
                        self.sampler.state_dict(), buffered
                    )
                )
            # (2) a half-staged checkpoint reads old-mesh buffers
            self._finish_stager()
        # (3) new-world artifacts; explicit strategy skips the search
        with span("resize_build"):
            accel = auto_accelerate(
                self._model_cfg,
                self._tx,
                batch=self.tcfg.batch_size,
                seq=self.tcfg.seq_len,
                devices=devices,
                strategy=strategy,
                donate=False,
                grad_accum=self.tcfg.grad_accum,
            )
        from dlrover_tpu.ckpt import reshard as reshard_mod
        from dlrover_tpu.models.train import state_spec

        from dlrover_tpu.parallel.grad_sync import strip_residual

        spec = state_spec(accel.cfg, accel.mesh, self._tx)
        # (4) on-device remap; host restore only for uncovered leaves.
        # The error-feedback residual is stripped first: reshard trees
        # must match the spec (which never carries it), its shapes are
        # tied to the OLD world's bucket plan anyway, and
        # _setup_grad_sync re-attaches a fresh one for the new plan
        # the with-block (not a manual handle) guarantees the span
        # closes on the raise paths below — a leaked open span would
        # poison hang attribution for the rest of the process
        with span("resize_reshard") as reshard_sp:
            try:
                new_state, report = reshard_mod.reshard_state(
                    strip_residual(self.state), spec,
                    stats=self.pipeline_stats,
                )
            except (OSError, RuntimeError) as e:
                # a failed on-device gather must not abort the resize
                # mid-world-change: degrade every leaf to the host
                # fallback below and restore the whole state from the
                # checkpoint instead. RuntimeError covers the real
                # failure mode (XLA surfaces interconnect/device errors
                # as XlaRuntimeError), OSError the injected
                # reshard.gather fault; ValueError (shape/struct
                # mismatch = model change) still raises
                import jax as _jax

                logger.error(
                    f"resize: on-device reshard failed ({e!r}); "
                    f"falling back to a full checkpoint restore"
                )
                _leaves, _ = _jax.tree_util.tree_flatten_with_path(spec)
                report = reshard_mod.ReshardReport(
                    fallback_paths=[
                        reshard_mod._keystr(kp) for kp, _ in _leaves
                    ]
                )
                new_state = spec
            reshard_sp.set(
                fallback_leaves=len(report.fallback_paths),
                device_bytes=report.device_bytes,
            )
            if report.fallback_paths:
                if self._ckptr is None:
                    raise RuntimeError(
                        f"resize: {len(report.fallback_paths)} leaves "
                        f"have no surviving on-device source and no "
                        f"ckpt_dir is configured for the host fallback "
                        f"(first: {report.fallback_paths[:3]})"
                    )
                step0, restored = self._ckptr.load_checkpoint(
                    {"train": spec, "sampler": self.sampler.state_dict()}
                )
                if restored is None or step0 < 0:
                    raise RuntimeError(
                        "resize: host fallback restore found no usable "
                        "checkpoint"
                    )
                live_step = int(self.state.step)
                if step0 == live_step:
                    # same step: fill only the holes, keep the
                    # on-device arrays for everything that survived
                    new_state = reshard_mod.merge_fallback(
                        new_state, restored["train"],
                        report.fallback_paths,
                    )
                else:
                    # mixing leaves from different optimizer steps
                    # would be silently inconsistent state — roll the
                    # WHOLE state back to the checkpoint (every leaf
                    # from one step)
                    logger.warning(
                        f"resize: fallback checkpoint is step {step0} "
                        f"but live state is step {live_step}; "
                        f"restoring the full checkpoint instead of "
                        f"mixing steps ({live_step - step0} steps of "
                        f"progress replayed)"
                    )
                    new_state = restored["train"]
                    self.sampler.load_state_dict(restored["sampler"])
        # swap the world
        self.accel = accel
        self.cfg = accel.cfg
        self.mesh = accel.mesh
        self.state = new_state
        self._donating_step_fn = (
            accel.donating_step_fn if self.tcfg.donation_aware else None
        )
        self._step_fn = accel.step_fn
        self._eval_step_fn = None  # per-mesh memo re-resolves lazily
        # link model: re-probe ONLY when the device fingerprint changed
        # (docs/elastic-resize.md) — a resize back onto the same
        # hardware reuses the cached probe and costs nothing here
        self._setup_link_model()
        # buckets are re-planned for the new dp degree and a fresh
        # error-feedback residual attached (shapes changed with dp);
        # the timing probe is skipped — downtime window
        self._setup_grad_sync(measure=False)
        # the SDC lane axis is per-world: rebuild the detector and
        # probe for the new device total (history from the old world
        # describes different lanes)
        self._setup_sdc()
        # spans straddling the rebuild belong to neither world's
        # budget: drop everything buffered so far, then re-price the
        # per-component budget for the new mesh (tests/test_audit.py
        # guards the no-double-count property)
        self._auditor.skip_to_now()
        self._setup_audit_budget()
        new_state = self.state
        # candidates already seen were filtered against the OLD world;
        # the next poll must re-evaluate them for this one
        self._last_candidates = None
        cache_hit = None
        self._aot_exec = self._aot_shapes = None
        if self._batch_avals is not None:
            with span("resize_compile") as compile_sp:
                xy = self._batch_specs(accel.mesh, strategy)
                key = self._step_cache_key(
                    strategy, accel.mesh, new_state, xy
                )
                if (
                    self._spec_compiler is not None
                    and self._spec_compiler.in_flight_key == key
                ):
                    # this exact executable is mid-compile on the
                    # background thread: waiting converts a duplicate
                    # multi-minute compile into a cache hit
                    self._spec_compiler.wait_idle(600.0)
                step_fn, state = accel.step_fn, new_state
                fn, cache_hit = self._compile_cache.get_or_compile(
                    key, lambda: step_fn.lower(state, *xy).compile()
                )
                compile_sp.set(cache_hit=bool(cache_hit))
                self._install_aot(
                    fn, tuple(shape for shape, _ in self._batch_avals)
                )
                self._aot_primed = True
        else:
            self._aot_primed = False
        self._flight.clear_suppression()
        downtime_ms = (time.perf_counter() - t0) * 1e3
        self.pipeline_stats.resize_count += 1
        self.pipeline_stats.resize_downtime_ms = downtime_ms
        logger.info(
            f"resized to {strategy.describe()} on {len(devices)} "
            f"devices in {downtime_ms:.0f} ms (compile cache "
            f"{'hit' if cache_hit else 'miss' if cache_hit is not None else 'n/a'}, "
            f"{report.moved_leaves} leaves resharded on device, "
            f"{len(report.fallback_paths)} via host)"
        )
        return {
            "downtime_ms": downtime_ms,
            "compile_cache_hit": cache_hit,
            "reshard_bytes_device": report.device_bytes,
            "reshard_bytes_host": report.host_bytes,
            "fallback_paths": list(report.fallback_paths),
            "mesh": strategy.mesh.axis_sizes(),
        }

    # -- speculative compilation ---------------------------------------
    def _staging_active(self) -> bool:
        return (
            self._stager is not None
            or (
                self._ckptr is not None
                and self._ckptr.staging_in_flight()
            )
            or (
                self._best_ckptr is not None
                and self._best_ckptr.staging_in_flight()
            )
        )

    def update_scale_candidates(self, device_counts) -> int:
        """Pre-lower the train step for likely next world sizes on a
        background thread (the speculative leg of the resize fast
        path). Candidates that cannot form a valid mesh are skipped
        with a log — a bad prediction must never hurt the current
        world. Returns the number of candidates submitted."""
        if not self.tcfg.speculative_compile:
            return 0
        if self._batch_avals is None or not self._aot_supported(
            self.accel.strategy
        ):
            return 0
        import jax

        all_devices = list(jax.devices())
        tasks, seen = [], set()
        for n in device_counts:
            n = int(n)
            if (
                n <= 0
                or n in seen
                or n == self.accel.strategy.mesh.num_devices
                or n > len(all_devices)
            ):
                continue
            seen.add(n)
            try:
                cand = self._strategy_for(n)
            except ValueError as e:
                logger.info(
                    f"speculative compile: skipping {n}-device "
                    f"candidate ({e})"
                )
                continue
            # a degraded candidate uses fewer devices than predicted —
            # lower for the mesh it will actually build
            task = self._speculative_task(
                cand, all_devices[: cand.mesh.num_devices]
            )
            if task is not None:
                tasks.append(task)
        if not tasks:
            return 0
        if self._spec_compiler is None:
            from dlrover_tpu.accel.compile_cache import (
                SpeculativeCompiler,
            )

            self._spec_compiler = SpeculativeCompiler(
                self._compile_cache,
                pause_fn=self._staging_active,
                budget_s=self.tcfg.spec_compile_budget_s,
            )
        self._spec_compiler.submit(tasks)
        logger.info(
            f"speculative compile: {len(tasks)} candidate meshes "
            f"queued ({sorted(seen)})"
        )
        return len(tasks)

    def _speculative_task(self, cand: Strategy, devices):
        """One pre-lower unit: key computed now (cheap eval_shape
        traces), the expensive lower+compile deferred to the
        background thread."""
        from dlrover_tpu.accel.dry_runner import _build
        from dlrover_tpu.models.train import state_spec
        from dlrover_tpu.accel.compile_cache import CompileTask
        from dlrover_tpu.parallel.mesh import build_mesh

        model_cfg, tx = self._model_cfg, self._tx
        try:
            mesh = build_mesh(cand.mesh, devices=devices)
        except ValueError as e:
            logger.info(f"speculative compile: {e}")
            return None
        # specs must match what the resize will lower against, so the
        # cfg/mesh derivation mirrors auto_accelerate's _build
        from dlrover_tpu.accel.opt_lib import apply_optimizations
        from dataclasses import replace as dc_replace

        cfg2, cand2 = apply_optimizations(model_cfg, cand, cand.opts)
        cfg2 = dc_replace(cfg2, dtype=cand2.dtype, remat=cand2.remat)
        spec = state_spec(cfg2, mesh, tx)
        from dlrover_tpu.parallel.grad_sync import (
            residual_spec,
            resolve_plan,
        )

        plan = resolve_plan(cfg2, cand2)
        if plan is not None and plan.compress == "int8":
            # a compressed run steps with the residual in its state
            # tree — the pre-lowered executable (and its cache key)
            # must see the same tree or the resize can never hit it
            spec = dc_replace(spec, grad_residual=residual_spec(plan, mesh))
        xy = self._batch_specs(mesh, cand)
        key = self._step_cache_key(cand, mesh, spec, xy)

        def build():
            _, mesh2, step_fn, _, _, _ = _build(
                cand, model_cfg, tx, devices, donate=False
            )
            return step_fn.lower(spec, *xy).compile()

        return CompileTask(
            label=f"mesh{cand.mesh.axis_sizes()}", key=key, build=build
        )

    def _poll_scale_candidates(self):
        """Pick up the master's predicted next worker counts from the
        paral-config file (the agent's ParalConfigTuner mirrors the
        master's ``candidate_worker_counts`` there) and queue
        speculative compiles for them."""
        if not self.tcfg.speculative_compile:
            return
        from dlrover_tpu.trainer.elastic.dataloader import (
            read_paral_config,
        )

        counts = read_paral_config().get("candidate_worker_counts") or []
        counts = [
            int(c) for c in counts if isinstance(c, (int, float)) and c > 0
        ]
        if not counts or counts == self._last_candidates:
            return
        if self._batch_avals is None:
            # too early: the first step hasn't recorded the batch avals
            # the pre-lower needs — leave the candidates unconsumed so
            # the next poll picks them up
            return
        self._last_candidates = counts
        import jax

        from dlrover_tpu.common.constants import NodeEnv

        num_procs = max(
            1, int(os.getenv(NodeEnv.NUM_PROCESSES, "1") or "1")
        )
        # worker counts → device counts at this job's density
        per_worker = max(1, len(jax.devices()) // num_procs)
        self.update_scale_candidates([c * per_worker for c in counts])

    def train(self, num_steps: int) -> Any:
        """Run up to ``num_steps`` optimizer steps (across epochs)."""
        import jax

        t0 = time.time()
        start_step = self.global_step
        # hang attribution reads THIS thread's open spans (the prefetch
        # producer parks in a read by design and must not masquerade as
        # the stuck frame)
        self._train_tid = threading.get_ident()
        self._last_eval: Dict[str, float] = {}
        # run-local best for the patience counter; the PERSISTED best
        # (_best_eval_loss, sidecar-loaded) deliberately survives so a
        # restarted run's first (worse) eval can't supersede it on disk
        self._run_best_eval_loss = float("inf")
        self._evals_since_best = 0
        try:
            return self._train_loop(num_steps, t0, start_step)
        except BaseException as e:
            # crash flight recorder: the black box dumps BEFORE the
            # exception unwinds past the trainer (stacks, last-N spans,
            # metrics, recent events) — by the time a human reads the
            # worker log, the process is long gone
            if not isinstance(e, (KeyboardInterrupt, SystemExit)):
                # force past the rate limiter: the process is about to
                # die and the exception is evidence no earlier dump
                # (hang watchdog, degraded episode) captured
                self._flight.dump("crash", exc=e, force=True)
            raise
        finally:
            self._close_prefetcher()
            try:
                # a half-staged checkpoint must not die with the loop:
                # the barrier drains and publishes it
                self._finish_stager()
            except Exception as e:
                # never mask the loop's own exception with a commit
                # failure; the stage is already aborted (lock released)
                logger.error(f"final stage commit failed: {e!r}")
            logger.info(f"pipeline: {self.pipeline_stats.summary()}")

    def _observe_step_time(self, dt_s: float):
        self._step_time_hist.observe(dt_s)
        self._step_time_sum += dt_s
        self._step_time_n += 1

    def _report_metrics(self, step: int, scalars: Dict[str, float]):
        """Publish at log cadence: training scalars + the whole metrics
        registry through ONE file (the agent's TrainingMonitor forwards
        every float in it to the master's collector). PipelineStats
        folds into the registry here so its counters ride the same
        export path as everything else."""
        if self._step_time_n:
            scalars["step_time_ms"] = round(
                1e3 * self._step_time_sum / self._step_time_n, 3
            )
            self._step_time_sum = 0.0
            self._step_time_n = 0
        for k, v in scalars.items():
            self._registry.gauge(
                f"dlrover_train_{k}", "training scalar"
            ).set(v)
        fold_pipeline_stats(self.pipeline_stats, self._registry)
        # goodput accounting rides the same export: collect the window
        # since the last report and publish the dlrover_goodput_*
        # gauges (the aggregator re-assembles the fleet number from
        # these scalars)
        self._goodput.export(self._registry)
        # budget reconciliation rides the same cadence: audit every
        # step completed since the last report, publish the
        # dlrover_audit_* series (residual/drift/alarm per component)
        # and rate-limited-persist the drift snapshot beside the rail
        # cache so a warm restart starts repriced
        self._auditor.export(self._registry)
        if self._link_fp:
            self._auditor.persist(fingerprint=self._link_fp)
        self._poll_worker_commands()
        if self.tcfg.report_metrics:
            report_runtime_metrics(
                step, **{**scalars, **self._registry.scalars()}
            )
        return scalars

    def _poll_worker_commands(self):
        """Execute master->worker commands relayed by the agent
        (flight dumps, profiler captures). Log-cadence polling of one
        small JSON file; ids are master-monotonic so a command runs
        exactly once per process."""
        from dlrover_tpu.agent.monitor import read_worker_commands

        try:
            cmds = read_worker_commands()
        except Exception:
            return
        for c in cmds:
            try:
                cid = int(c.get("id", 0))
            except (TypeError, ValueError):
                continue
            if cid <= self._last_command_id:
                continue
            self._last_command_id = cid
            kind = c.get("kind", "")
            reason = str(c.get("reason", "") or "master_request")
            if kind == "evict":
                # the master-side notice channel (platform preemption
                # watchers, operators, the auto-scaler): arg carries
                # the grace window, 0 = the trainer's default
                self.request_eviction(
                    float(c.get("arg", 0) or 0) or None,
                    reason=f"master_{reason}",
                )
            elif kind == "flight_dump":
                logger.info(
                    f"master requested flight dump (#{cid}, {reason})"
                )
                self._flight.dump(f"request_{reason}")
            elif kind == "profile":
                steps = int(c.get("arg", 0) or 3)
                if self._profiler_capture.request(steps, reason=reason):
                    logger.info(
                        f"master requested profiler capture (#{cid}, "
                        f"{steps} steps, {reason})"
                    )
                else:
                    # refusal is the artifact-volume bound working
                    # (live capture / cooldown), but it must be
                    # visible — the master believes evidence is coming
                    logger.warning(
                        f"profiler capture request #{cid} ({reason}) "
                        f"refused: capture active or cooling down"
                    )
            else:
                logger.warning(
                    f"unknown worker command kind {kind!r} (#{cid})"
                )

    def _train_loop(self, num_steps: int, t0, start_step) -> Any:
        import jax

        while self.global_step < num_steps and not self.eviction_pending:
            self.dataloader.load_config()  # master-retuned batch size
            self._apply_lr_scale(self.dataloader.lr_scale)
            # master-predicted next world sizes → background pre-lower
            self._poll_scale_candidates()
            # epoch rollover and mid-epoch position both live in the
            # sampler (its iterator advances completed_num and bumps the
            # epoch on exhaustion) — the trainer never touches them, so a
            # num_steps stop mid-epoch checkpoints the exact position
            # (modulo the prefetch rewind in _ckpt_state)
            batches = self._epoch_batches(num_steps)
            while True:
                # step boundary = the preemption arrival point: the
                # in-flight step is finished, nothing is half-donated.
                # node.preempt `kill` is the scripted hard death the
                # chaos harness replays; a pending eviction notice
                # (SIGTERM / env deadline / `evict` command) enters the
                # graceful drain instead
                faults.fire("node.preempt")
                if self.eviction_pending:
                    break
                # on-demand jax.profiler capture (no-op unless a master
                # `profile` command armed it)
                self._profiler_capture.on_step_begin()
                # the step span + its phase children are the trace's
                # spine: a dump shows where each step's wall time went
                # (docs/observability.md span taxonomy). An exception
                # escaping the body must CANCEL the span — a leaked
                # open frame would poison hang attribution for the
                # rest of the process (cancel after end is a no-op)
                step_sp = span("step")
                step_t0 = time.perf_counter()
                try:
                    try:
                        with span("data_wait"):
                            x, y = next(batches)
                    except StopIteration:
                        step_sp.cancel()
                        break
                    with span("compute"):
                        # compute-window mark for the host-link
                        # arbiter: background transfers (spill drain,
                        # staging D2H) are scheduled INTO this window,
                        # off the inter-step host section
                        transfer_sched.note_compute(True)
                        try:
                            metrics = self._run_step(x, y)
                            # materializing the step count forces the
                            # dispatched update on synchronous backends
                            # — that wall time is compute, so it must
                            # land inside this span
                            step = self.global_step
                        finally:
                            transfer_sched.note_compute(False)
                    # interleave checkpoint chunks while the step
                    # computes (the engine emits its own ckpt_stage
                    # span)
                    self._advance_stager()
                    # the per-lane norm vector is detector input, not
                    # a reporting scalar — pop it before any consumer
                    # that reports scalars sees it (same contract as
                    # moe_expert_load)
                    dev_norms = metrics.pop("sdc_device_norms", None)
                    if self._sdc is not None:
                        self._sdc_step(step, metrics, dev_norms)
                    if self._metrics_hook is not None:
                        self._metrics_hook(step, metrics)
                    if (
                        self._moe_rebalancer is not None
                        and step % self.tcfg.moe_rebalance_interval
                        == 0
                        and "moe_expert_load" in metrics
                    ):
                        self._maybe_rebalance_experts(
                            metrics["moe_expert_load"]
                        )
                    if step % self.tcfg.log_interval == 0:
                        # the only host sync in the loop: loss is
                        # materialized at log cadence, not every step
                        # (async dispatch stays ahead of the host
                        # otherwise)
                        with span("host_sync"):
                            loss = float(metrics["loss"])
                        with span("report"):
                            scalars = {"loss": loss}
                            lr = self.current_lr()
                            if lr is not None:
                                scalars["lr"] = lr
                            if self._last_eval:
                                scalars.update(self._last_eval)
                            # the agent's TrainingMonitor forwards
                            # these to the master's collector
                            # (TrainMetricsReport)
                            self._report_metrics(step, scalars)
                            rate = (step - start_step) / max(
                                time.time() - t0, 1e-9
                            )
                            lr_s = (
                                f" lr={lr:.2e}" if lr is not None else ""
                            )
                            logger.info(
                                f"step {step}: loss={loss:.4f}{lr_s} "
                                f"({rate:.2f} it/s)"
                            )
                    if (
                        self._eval_dataset is not None
                        and self.tcfg.eval_interval
                        and step % self.tcfg.eval_interval == 0
                    ):
                        with span("eval"):
                            self._last_eval = self.evaluate()
                        logger.info(
                            f"step {step}: "
                            f"eval_loss={self._last_eval['eval_loss']:.4f} "
                            f"ppl={self._last_eval['eval_ppl']:.2f}"
                        )
                        if self._metrics_hook is not None:
                            self._metrics_hook(
                                step, dict(self._last_eval)
                            )
                        if self._after_eval(step):
                            logger.info(
                                f"early stopping at step {step}: no "
                                f"eval improvement in "
                                f"{self.tcfg.early_stopping_patience} "
                                f"evals (best {self._best_eval_loss:.4f})"
                            )
                            step_sp.end()
                            jax.block_until_ready(self.state.params)
                            return self.state
                    if self._sdc_halt:
                        # tier-3 conviction already rolled the state
                        # back — saving at THIS step would commit a
                        # checkpoint claiming progress the rollback
                        # discarded. End the step cleanly and halt
                        # (the quarantine-drain: the master excludes
                        # the convicted chip; the next incarnation
                        # resumes from the verified step)
                        step_sp.end()
                        break
                    with span("ckpt_save"):
                        self._maybe_save(step)
                    step_sp.end()
                    self._profiler_capture.on_step_end()
                    self._observe_step_time(
                        time.perf_counter() - step_t0
                    )
                    if (
                        self._replay_until_step is not None
                        and step >= self._replay_until_step
                    ):
                        # caught back up to the pre-restart frontier:
                        # wall time is productive again
                        self._goodput.replay_end()
                        self._replay_until_step = None
                except BaseException:
                    step_sp.cancel()
                    raise
                if step >= num_steps:
                    break
            if self._sdc_halt:
                break
            if self.eviction_pending:
                # the prefetcher stays up: the emergency checkpoint's
                # sampler snapshot rewinds by its buffered lookahead
                # (_ckpt_state), exactly like a normal save; the drain
                # closes it afterwards
                break
            self._close_prefetcher()  # fresh buffer per epoch
        if self.eviction_pending:
            jax.block_until_ready(self.state.params)
            self._drain_for_eviction()
        jax.block_until_ready(self.state.params)
        return self.state

    def _apply_lr_scale(self, scale: float):
        """Linear-scaling rule: when the master retunes the batch size it
        also publishes optimizer.batch_size_factor. Optimizers from
        ``build_optimizer`` carry a dedicated ``retune_scale`` hyperparam
        that COMPOSES with the LR schedule (the schedule rewrites
        ``learning_rate`` every step, so multiplying that would be
        overwritten); plain ``optax.inject_hyperparams`` optimizers fall
        back to rescaling ``learning_rate`` in place."""
        if scale == getattr(self, "_applied_lr_scale", 1.0):
            return
        hp = getattr(self.state.opt_state, "hyperparams", None)
        # a SCHEDULE-driven learning_rate is recomputed from the step
        # count on every update, so multiplying it in place would be
        # silently discarded — only retune_scale can compose with it
        lr_is_scheduled = bool(
            getattr(self.state.opt_state, "hyperparams_states", {}).get(
                "learning_rate"
            )
        )
        can_apply = hp is not None and (
            "retune_scale" in hp
            or ("learning_rate" in hp and not lr_is_scheduled)
        )
        if not can_apply:
            if not getattr(self, "_warned_lr_scale", False):
                logger.warning(
                    f"master suggests lr scale {scale} but the optimizer "
                    "cannot accept it (no injected hyperparams, or a "
                    "schedule without a retune_scale knob); build tx "
                    "with build_optimizer to enable retuning"
                )
                self._warned_lr_scale = True
            return
        prev = getattr(self, "_applied_lr_scale", 1.0)
        if "retune_scale" in hp:
            hp["retune_scale"] = hp["retune_scale"] * (scale / prev)
        else:
            hp["learning_rate"] = hp["learning_rate"] * (scale / prev)
        self._applied_lr_scale = scale
        logger.info(f"learning rate rescaled x{scale} (linear scaling)")

    def close(self):
        if self._span_heartbeat is not None:
            self._span_heartbeat.stop()
            self._span_heartbeat = None
        # final drift snapshot, bypassing the rate limit — short jobs
        # still leave a calibration for the next run on this hardware
        if self._link_fp:
            self._auditor.persist(fingerprint=self._link_fp, force=True)
        self._flight.stop_watchdog()
        self._profiler_capture.abort()
        self._close_prefetcher()
        self._abort_stager()
        if self._spec_compiler is not None:
            self._spec_compiler.close()
            self._spec_compiler = None
        if self._ckptr is not None:
            self._ckptr.engine.close()
        if self._best_ckptr is not None:
            self._best_ckptr.engine.close()
