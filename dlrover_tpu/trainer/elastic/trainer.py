"""ElasticTrainer: the user-facing training loop.

Parity: dlrover/trainer/torch/elastic/trainer.py:48 (ElasticTrainer
wrapping model/optimizer/dataloader for elasticity) and ATorch's
HF-style ``AtorchTrainer`` (atorch/trainer/atorch_trainer.py:127). One
facade owns the full elastic story so a user train script collapses to
~30 lines:

- strategy: an explicit ``Strategy`` or the auto_accelerate search picks
  the mesh/remat/microbatching (donation off — flash staging reads the
  state after the step);
- data: ``ElasticDataLoader`` + ``ElasticDistributedSampler`` (resumes
  mid-epoch across world-size changes, honors master-retuned batch size);
- checkpoint: flash save every ``save_memory_interval`` steps (ms-scale,
  shm), persisted every ``save_storage_interval`` steps; sampler state
  rides the train state so restore is exactly-once over the data;
- monitoring: every step publishes the global step for the agent's
  TrainingMonitor (feeds master hang detection / auto-scaling).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import numpy as np

from dlrover_tpu.accel.accelerate import AccelerateResult, auto_accelerate
from dlrover_tpu.accel.strategy import Strategy
from dlrover_tpu.agent.monitor import report_runtime_metrics
from dlrover_tpu.ckpt.checkpointer import FlashCheckpointer, StorageType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.models.config import TransformerConfig
from dlrover_tpu.models.train import shard_batch
from dlrover_tpu.trainer.elastic.dataloader import ElasticDataLoader
from dlrover_tpu.trainer.elastic.sampler import ElasticDistributedSampler


@dataclass
class TrainerConfig:
    batch_size: int = 8
    seq_len: int = 128
    ckpt_dir: str = ""
    save_memory_interval: int = 50
    save_storage_interval: int = 500
    report_metrics: bool = True
    log_interval: int = 10


class ElasticTrainer:
    def __init__(
        self,
        model_cfg: TransformerConfig,
        tx,
        dataset,
        trainer_cfg: Optional[TrainerConfig] = None,
        strategy: Optional[Strategy] = None,
        devices=None,
        collate_fn: Optional[Callable] = None,
        metrics_hook: Optional[Callable[[int, Dict], None]] = None,
    ):
        import jax

        self.tcfg = trainer_cfg or TrainerConfig()
        self._metrics_hook = metrics_hook
        # async flash staging reads state buffers after the step returns,
        # so the production step must NOT donate them
        self.accel: AccelerateResult = auto_accelerate(
            model_cfg,
            tx,
            batch=self.tcfg.batch_size,
            seq=self.tcfg.seq_len,
            devices=devices,
            strategy=strategy,
            donate=False,
        )
        self.cfg = self.accel.cfg
        self.mesh = self.accel.mesh
        self._step_fn = self.accel.step_fn
        self.state = self.accel.init_fn(jax.random.PRNGKey(0))

        self.sampler = ElasticDistributedSampler(
            len(dataset), shuffle=True
        )
        self.dataloader = ElasticDataLoader(
            dataset,
            batch_size=self.tcfg.batch_size,
            sampler=self.sampler,
            collate_fn=collate_fn,
        )
        self._ckptr: Optional[FlashCheckpointer] = None
        if self.tcfg.ckpt_dir:
            self._ckptr = FlashCheckpointer(self.tcfg.ckpt_dir)
            self._maybe_restore()

    # -- checkpoint ----------------------------------------------------
    def _ckpt_state(self):
        return {"train": self.state, "sampler": self.sampler.state_dict()}

    def _maybe_restore(self):
        step, restored = self._ckptr.load_checkpoint(self._ckpt_state())
        if restored is not None and step >= 0:
            self.state = restored["train"]
            self.sampler.load_state_dict(restored["sampler"])
            logger.info(f"resumed from flash checkpoint step {step}")

    def save(self, storage: StorageType = StorageType.MEMORY) -> bool:
        if self._ckptr is None:
            return False
        return self._ckptr.save_checkpoint(
            self.global_step, self._ckpt_state(), storage
        )

    # -- loop ----------------------------------------------------------
    @property
    def global_step(self) -> int:
        return int(self.state.step)

    def _device_batch(self, batch):
        if isinstance(batch, dict):
            bx, by = batch["x"], batch["y"]
        else:  # tuple/list samples from the default collate
            bx, by = batch[0], batch[1]
        if self.accel.strategy.mesh.pp > 1:
            return bx, by  # pipeline step takes host arrays
        sharded = shard_batch({"x": bx, "y": by}, self.mesh)
        return sharded["x"], sharded["y"]

    def train(self, num_steps: int) -> Any:
        """Run up to ``num_steps`` optimizer steps (across epochs)."""
        import jax

        t0 = time.time()
        start_step = self.global_step
        while self.global_step < num_steps:
            self.dataloader.load_config()  # master-retuned batch size
            self._apply_lr_scale(self.dataloader.lr_scale)
            # epoch rollover and mid-epoch position both live in the
            # sampler (its iterator advances completed_num and bumps the
            # epoch on exhaustion) — the trainer never touches them, so a
            # num_steps stop mid-epoch checkpoints the exact position
            for batch in self.dataloader:
                x, y = self._device_batch(batch)
                self.state, metrics = self._step_fn(self.state, x, y)
                step = self.global_step
                if self._metrics_hook is not None:
                    self._metrics_hook(step, metrics)
                if step % self.tcfg.log_interval == 0:
                    # the only host sync in the loop: loss is materialized
                    # at log cadence, not every step (async dispatch stays
                    # ahead of the host otherwise)
                    loss = float(metrics["loss"])
                    if self.tcfg.report_metrics:
                        report_runtime_metrics(step, loss=loss)
                    rate = (step - start_step) / max(
                        time.time() - t0, 1e-9
                    )
                    logger.info(
                        f"step {step}: loss={loss:.4f} ({rate:.2f} it/s)"
                    )
                if self._ckptr is not None:
                    if step % self.tcfg.save_storage_interval == 0:
                        self.save(StorageType.DISK)
                    elif step % self.tcfg.save_memory_interval == 0:
                        self.save(StorageType.MEMORY)
                if step >= num_steps:
                    break
        jax.block_until_ready(self.state.params)
        return self.state

    def _apply_lr_scale(self, scale: float):
        """Linear-scaling rule: when the master retunes the batch size it
        also publishes optimizer.batch_size_factor; if the optimizer was
        built with ``optax.inject_hyperparams`` the learning rate is
        rescaled in place (otherwise a one-time warning is logged)."""
        if scale == getattr(self, "_applied_lr_scale", 1.0):
            return
        hp = getattr(self.state.opt_state, "hyperparams", None)
        if hp is None or "learning_rate" not in hp:
            if not getattr(self, "_warned_lr_scale", False):
                logger.warning(
                    f"master suggests lr scale {scale} but the optimizer "
                    "has no injected hyperparams; build tx with "
                    "optax.inject_hyperparams to enable retuning"
                )
                self._warned_lr_scale = True
            return
        prev = getattr(self, "_applied_lr_scale", 1.0)
        hp["learning_rate"] = hp["learning_rate"] * (scale / prev)
        self._applied_lr_scale = scale
        logger.info(f"learning rate rescaled x{scale} (linear scaling)")

    def close(self):
        if self._ckptr is not None:
            self._ckptr.engine.close()
