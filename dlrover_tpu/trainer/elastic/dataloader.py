"""Elastic data loader: batch size retunable at runtime via the
master-driven paral-config file.

Parity: dlrover/trainer/torch/elastic/dataloader.py:26 (ElasticDataLoader
``:97-143`` re-reads batch size from the config file the agent's
ParalConfigTuner writes). Framework-free: yields stacked numpy batches
ready for ``jax.device_put``/``make_array_from_process_local_data``.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterator, Optional

import numpy as np

from dlrover_tpu.common.constants import ConfigPath
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.trainer.elastic.sampler import ElasticDistributedSampler


def read_paral_config(path: str = "") -> dict:
    path = path or os.getenv(
        ConfigPath.ENV_PARAL_CONFIG, ConfigPath.PARAL_CONFIG
    )
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


class ElasticDataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        sampler: Optional[ElasticDistributedSampler] = None,
        collate_fn: Optional[Callable] = None,
        config_file: str = "",
    ):
        self.dataset = dataset
        self._batch_size = batch_size
        self.sampler = sampler or ElasticDistributedSampler(
            len(dataset), shuffle=False
        )
        self._collate_fn = collate_fn or _default_collate
        self._config_file = config_file
        # linear-scaling LR multiplier the master retunes alongside the
        # batch size (optimizer.batch_size_factor); trainers with
        # injected hyperparams apply it (ElasticTrainer does)
        self.lr_scale = 1.0
        self.load_config()

    @property
    def batch_size(self) -> int:
        return self._batch_size

    def set_batch_size(self, batch_size: int):
        if batch_size > 0 and batch_size != self._batch_size:
            logger.info(
                f"dataloader batch size {self._batch_size} -> {batch_size}"
            )
            self._batch_size = batch_size

    def load_config(self):
        """Pick up a master-tuned batch size / LR scale if present."""
        config = read_paral_config(self._config_file)
        dl = config.get("dataloader", {})
        if dl.get("batch_size"):
            self.set_batch_size(int(dl["batch_size"]))
        factor = config.get("optimizer", {}).get("batch_size_factor")
        if factor and factor > 0:
            self.lr_scale = float(factor)

    def __iter__(self) -> Iterator:
        batch = []
        for idx in self.sampler:
            batch.append(self.dataset[idx])
            if len(batch) >= self._batch_size:
                yield self._collate_fn(batch)
                batch = []
        if batch:
            yield self._collate_fn(batch)

    def __len__(self) -> int:
        return -(-len(self.sampler) // self._batch_size)

    # -- checkpoint ----------------------------------------------------
    def state_dict(self) -> dict:
        return {"sampler": self.sampler.state_dict()}

    def load_state_dict(self, state: dict):
        self.sampler.load_state_dict(state.get("sampler", {}))


def _default_collate(batch):
    first = batch[0]
    if isinstance(first, tuple):
        return tuple(
            np.stack([b[i] for b in batch]) for i in range(len(first))
        )
    if isinstance(first, dict):
        return {k: np.stack([b[k] for b in batch]) for k in first}
    return np.stack(batch)
