"""Checkpointable data sampler that survives world-size changes.

Parity: dlrover/trainer/torch/elastic/sampler.py:25
(ElasticDistributedSampler: ``state_dict:118`` / ``load_state_dict:130``) —
the sampler records global progress (``completed_num``) so training resumes
mid-epoch after a restart even when the number of data-parallel replicas
changed; no torch dependency, indices feed any indexable dataset or a
tf.data/grain pipeline equally.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np


class ElasticDistributedSampler:
    def __init__(
        self,
        dataset_size: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas:
            raise ValueError(
                f"rank {rank} >= num_replicas {num_replicas}"
            )
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # samples (global, across all replicas) consumed in this epoch
        self.completed_num = 0
        # heterogeneous throughput weights (parallel/topology.slice_
        # throughput_weights): None = equal round-robin shards (the
        # historical path, byte-identical); else one positive weight
        # per replica and samples are dealt proportionally
        self._weights: Optional[np.ndarray] = None
        self._deal: Optional[np.ndarray] = None  # memoized pattern

    def _epoch_total(self) -> int:
        """Samples per epoch after drop/pad, without materializing indices."""
        if self.drop_last:
            return (
                self.dataset_size // self.num_replicas
            ) * self.num_replicas
        return -(-self.dataset_size // self.num_replicas) * self.num_replicas

    def _epoch_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        total = self._epoch_total()
        if total <= len(indices):
            indices = indices[:total]
        else:
            # wrap as many times as needed (num_replicas can exceed the
            # dataset size); a short epoch would give replicas different
            # step counts and hang the next collective
            reps = -(-total // len(indices))
            indices = np.tile(indices, reps)[:total]
        return indices

    # -- heterogeneous throughput weighting ----------------------------
    def set_throughput_weights(self, weights) -> None:
        """Unequal data shards for unequal replicas (arXiv 2602.18007
        via ``topology.slice_throughput_weights``): ``weights`` is one
        positive share per replica (normalized here) and samples are
        dealt proportionally by a deterministic smooth weighted
        round-robin — every replica computes the identical deal
        pattern from the same weights, so no coordination is needed.
        ``None`` restores equal round-robin dealing."""
        if weights is None:
            self._weights = self._deal = None
            return
        w = np.asarray(list(weights), dtype=np.float64)
        if len(w) != self.num_replicas or (w <= 0).any():
            raise ValueError(
                f"need {self.num_replicas} positive weights, got "
                f"{list(weights)!r}"
            )
        self._weights = w / w.sum()
        self._deal = None

    def _deal_pattern(self) -> np.ndarray:
        """Replica id per global sample position over one window of
        ``16 * num_replicas`` positions (smooth weighted round-robin:
        each position goes to the replica with the largest accumulated
        deficit, so shares interleave instead of clumping). Purely a
        function of the weights — identical on every replica."""
        if self._deal is not None:
            return self._deal
        W = 16 * self.num_replicas
        credit = np.zeros(self.num_replicas)
        out = np.empty(W, dtype=np.int64)
        for p in range(W):
            credit += self._weights
            r = int(np.argmax(credit))
            out[p] = r
            credit[r] -= 1.0
        self._deal = out
        return out

    def __iter__(self) -> Iterator[int]:
        indices = self._epoch_indices()
        if self._weights is None:
            # skip what the job already consumed (any previous world
            # size): completed_num is global, so the remaining samples
            # are simply re-dealt round-robin to the current replicas
            remaining = indices[self.completed_num:]
            for i, idx in enumerate(remaining):
                if i % self.num_replicas == self.rank:
                    self.completed_num += self.num_replicas
                    yield int(idx)
        else:
            # weighted dealing walks GLOBAL positions one at a time
            # (completed_num stays the global cursor, so checkpoints
            # and world-size changes keep their exactly-once story)
            pattern = self._deal_pattern()
            W = len(pattern)
            total = len(indices)
            while self.completed_num < total:
                p = self.completed_num
                self.completed_num += 1
                if pattern[p % W] == self.rank:
                    yield int(indices[p])
        # epoch exhausted: roll over so a plain
        # ``for epoch in range(n): for batch in loader`` loop works even
        # without an explicit set_epoch (which still overrides shuffling)
        self.epoch += 1
        self.completed_num = 0

    def rewound_completed(self, completed: int, owned: int) -> int:
        """Global cursor after rewinding ``owned`` of THIS rank's
        samples from ``completed`` — the prefetch-rewind arithmetic
        (trainer ``_rewound_sampler_state``) must match the dealing
        mode. Equal dealing: every owned sample spans ``num_replicas``
        global positions. Weighted dealing: walk the deal pattern
        backwards, releasing a unit of ``owned`` per owned position.
        May return a NEGATIVE value: that many global positions borrow
        from the previous epoch (the caller rolls the epoch back); for
        the weighted walk the remainder past position 0 is converted
        at the equal-dealing rate — exact for ``num_replicas == 1``
        and an approximation that errs on the replay-not-skip side
        only across an epoch rollover."""
        if self._weights is None:
            return completed - owned * self.num_replicas
        pattern = self._deal_pattern()
        W = len(pattern)
        c = completed
        while owned > 0 and c > 0:
            c -= 1
            if pattern[c % W] == self.rank:
                owned -= 1
        return c - owned * self.num_replicas

    def __len__(self) -> int:
        indices_left = max(0, self._epoch_total() - self.completed_num)
        if self._weights is not None:
            # owned positions among the remaining global ones
            pattern = self._deal_pattern()
            W = len(pattern)
            start = self.completed_num
            pos = (np.arange(indices_left) + start) % W
            return int((pattern[pos] == self.rank).sum())
        return indices_left // self.num_replicas

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.completed_num = 0

    # -- checkpoint ----------------------------------------------------
    def state_dict(self) -> Dict:
        return {
            "epoch": self.epoch,
            "completed_num": self.completed_num,
        }

    def load_state_dict(self, state: Dict):
        self.epoch = state.get("epoch", 0)
        self.completed_num = state.get("completed_num", 0)
        # clamp: a smaller dataset or changed padding must not overflow
        total = self._epoch_total()
        if self.completed_num >= total:
            self.completed_num = 0
            self.epoch += 1
