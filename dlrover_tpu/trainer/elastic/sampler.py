"""Checkpointable data sampler that survives world-size changes.

Parity: dlrover/trainer/torch/elastic/sampler.py:25
(ElasticDistributedSampler: ``state_dict:118`` / ``load_state_dict:130``) —
the sampler records global progress (``completed_num``) so training resumes
mid-epoch after a restart even when the number of data-parallel replicas
changed; no torch dependency, indices feed any indexable dataset or a
tf.data/grain pipeline equally.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np


class ElasticDistributedSampler:
    def __init__(
        self,
        dataset_size: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas:
            raise ValueError(
                f"rank {rank} >= num_replicas {num_replicas}"
            )
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # samples (global, across all replicas) consumed in this epoch
        self.completed_num = 0

    def _epoch_total(self) -> int:
        """Samples per epoch after drop/pad, without materializing indices."""
        if self.drop_last:
            return (
                self.dataset_size // self.num_replicas
            ) * self.num_replicas
        return -(-self.dataset_size // self.num_replicas) * self.num_replicas

    def _epoch_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        total = self._epoch_total()
        if total <= len(indices):
            indices = indices[:total]
        else:
            # wrap as many times as needed (num_replicas can exceed the
            # dataset size); a short epoch would give replicas different
            # step counts and hang the next collective
            reps = -(-total // len(indices))
            indices = np.tile(indices, reps)[:total]
        return indices

    def __iter__(self) -> Iterator[int]:
        indices = self._epoch_indices()
        # skip what the job already consumed (any previous world size):
        # completed_num is global, so the remaining samples are simply
        # re-dealt round-robin to the current replicas
        remaining = indices[self.completed_num:]
        for i, idx in enumerate(remaining):
            if i % self.num_replicas == self.rank:
                self.completed_num += self.num_replicas
                yield int(idx)
        # epoch exhausted: roll over so a plain
        # ``for epoch in range(n): for batch in loader`` loop works even
        # without an explicit set_epoch (which still overrides shuffling)
        self.epoch += 1
        self.completed_num = 0

    def __len__(self) -> int:
        indices_left = max(0, self._epoch_total() - self.completed_num)
        return indices_left // self.num_replicas

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.completed_num = 0

    # -- checkpoint ----------------------------------------------------
    def state_dict(self) -> Dict:
        return {
            "epoch": self.epoch,
            "completed_num": self.completed_num,
        }

    def load_state_dict(self, state: Dict):
        self.epoch = state.get("epoch", 0)
        self.completed_num = state.get("completed_num", 0)
        # clamp: a smaller dataset or changed padding must not overflow
        total = self._epoch_total()
        if self.completed_num >= total:
            self.completed_num = 0
            self.epoch += 1
