"""Goodput ledger: attribute EVERY second of job wall time to one bucket.

Before this module "goodput" existed only as ad-hoc arithmetic inside
bench legs (``goodput_pct_preempt_flashckpt_gpt2`` and friends) — a
number you could quote but not decompose, and nothing continuous a
resource optimizer could plan against. The ledger turns the PR-4 span
stream into a closed accounting: wall time since the ledger started is
partitioned into the taxonomy below, the categories sum back to wall
time (the **closure invariant**, gated at ±1% by ``bench.py --smoke``),
and the resulting goodput fraction is exported as ``dlrover_goodput_*``
Prometheus gauges, aggregated per-worker/fleet by the master's
``TelemetryAggregator``, and ingested by the Brain as the
goodput-per-chip objective its allocation decisions plan against.

Taxonomy (priority order — an instant claimed by a higher row is
subtracted from every lower row, so the partition is disjoint):

| category            | claimed by                                     |
|---------------------|------------------------------------------------|
| eviction            | ``eviction_begin()``..``end()`` episodes: the  |
|                     | grace-window drain after a preemption notice   |
|                     | (claims the emergency-checkpoint spans inside) |
| resize_downtime     | ``resize_drain/build/reshard/compile`` spans   |
| restart_replay      | ``replay_begin()``..``replay_end()`` episodes: |
|                     | re-earning steps lost to a restart             |
| ckpt_block          | ``ckpt_save/stage/commit/persist`` spans       |
| data_stall          | ``data_wait`` spans                            |
| comm_exposed        | ``grad_sync_ici/dcn/probe`` spans (exposed on  |
|                     | the train thread, not overlapped)              |
| productive_compute  | ``compute`` spans                              |
| degraded            | ``degraded_enter()``..``exit()`` episode time  |
|                     | not already claimed above (PR-5 shm-only mode) |
| serving_soak        | ``serving_begin()``..``end()`` episodes: the   |
|                     | co-located inference plane decoding in idle    |
|                     | step gaps / resize drains (PR-17); ranked      |
|                     | BELOW every training row so serving can only   |
|                     | claim time training left on the table — any    |
|                     | overlap with ``compute`` is priced as training |
| other               | the remainder (bring-up, eval, logging, ...)   |

Only spans on the train thread count (``tid_fn``, same convention as
``SpanHeartbeat``): the prefetcher's ``h2d`` overlaps ``compute`` by
design and must not double-claim wall time.

The ledger consumes the tracer incrementally (``SpanTracer.drain``
cursors), so a multi-day job can ``collect()`` at log cadence without
ever re-reading the ring; spans still open at collect time (a wedged
``ckpt_commit``) are attributed up to "now" and the completed record is
clipped against the already-counted window, so a hang shows up in the
ledger *while it is happening*.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.obs.trace import SpanTracer, get_tracer

# the closed taxonomy, in priority order (highest claim first);
# "other" is the remainder and always closes the partition.
# "eviction" outranks everything: the drain window deliberately runs
# checkpoint/report work inside it, and that time is the price of the
# preemption, not of checkpointing policy
CATEGORIES = (
    "eviction",
    "resize_downtime",
    "restart_replay",
    "ckpt_block",
    "data_stall",
    "comm_exposed",
    "productive_compute",
    "degraded",
    "serving_soak",
    "other",
)

# span name -> category (docs/observability.md span taxonomy)
SPAN_CATEGORY = {
    "resize_drain": "resize_downtime",
    "resize_build": "resize_downtime",
    "resize_reshard": "resize_downtime",
    "resize_compile": "resize_downtime",
    "ckpt_save": "ckpt_block",
    "ckpt_stage": "ckpt_block",
    "ckpt_commit": "ckpt_block",
    "ckpt_persist": "ckpt_block",
    "data_wait": "data_stall",
    "grad_sync_ici": "comm_exposed",
    "grad_sync_dcn": "comm_exposed",
    "grad_sync_probe": "comm_exposed",
    "grad_sync_overlap_probe": "comm_exposed",
    "compute": "productive_compute",
}

# the closure gate: |sum(categories) - wall| / wall must stay under
# this (bench --smoke exits nonzero past it)
CLOSURE_GATE_PCT = 1.0

METRIC_PREFIX = "dlrover_goodput_"


def _merge(ivs: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Sorted, overlap-merged copy of ``ivs``."""
    out: List[Tuple[int, int]] = []
    for lo, hi in sorted(ivs):
        if hi <= lo:
            continue
        if out and lo <= out[-1][1]:
            if hi > out[-1][1]:
                out[-1] = (out[-1][0], hi)
        else:
            out.append((lo, hi))
    return out


def _subtract(
    ivs: List[Tuple[int, int]], cover: List[Tuple[int, int]]
) -> List[Tuple[int, int]]:
    """``ivs`` minus ``cover`` (both merged/sorted)."""
    out: List[Tuple[int, int]] = []
    for lo, hi in ivs:
        cur = lo
        for clo, chi in cover:
            if chi <= cur:
                continue
            if clo >= hi:
                break
            if clo > cur:
                out.append((cur, clo))
            cur = max(cur, chi)
            if cur >= hi:
                break
        if cur < hi:
            out.append((cur, hi))
    return out


def _total_s(ivs: List[Tuple[int, int]]) -> float:
    return sum(hi - lo for lo, hi in ivs) / 1e9


def compute_goodput_pct(productive_s: float, wall_s: float) -> float:
    """The one shared goodput formula (bench legs that measure across
    processes — where no single tracer sees the whole window — still
    divide through here, so the definition cannot drift)."""
    if wall_s <= 0:
        return 0.0
    return 100.0 * max(0.0, productive_s) / wall_s


@dataclass
class GoodputReport:
    """One closed accounting of a wall-time window."""

    wall_s: float = 0.0
    seconds: Dict[str, float] = field(default_factory=dict)

    @property
    def goodput_pct(self) -> float:
        return compute_goodput_pct(
            self.seconds.get("productive_compute", 0.0), self.wall_s
        )

    @property
    def closure_error_pct(self) -> float:
        """|sum(categories) - wall| as a % of wall — the invariant the
        smoke gate holds at ≤ ``CLOSURE_GATE_PCT``. Nonzero means the
        interval arithmetic double- or under-claimed time."""
        if self.wall_s <= 0:
            return 0.0
        total = sum(self.seconds.values())
        return 100.0 * abs(total - self.wall_s) / self.wall_s

    def as_dict(self) -> dict:
        return {
            "wall_s": round(self.wall_s, 6),
            "goodput_pct": round(self.goodput_pct, 3),
            "closure_error_pct": round(self.closure_error_pct, 4),
            **{k: round(v, 6) for k, v in self.seconds.items()},
        }


class GoodputLedger:
    """Incremental wall-time accountant over a ``SpanTracer``.

    Thread-safe; ``collect()`` is meant for log cadence (it drains only
    records appended since the previous call). ``snapshot()`` collects
    and returns the cumulative :class:`GoodputReport` since the ledger
    started.
    """

    def __init__(
        self,
        tracer: Optional[SpanTracer] = None,
        tid_fn: Optional[Callable[[], Optional[int]]] = None,
    ):
        # `is None`, not truthiness — SpanTracer defines __len__ (same
        # footgun SpanHeartbeat documents)
        self._tracer = tracer if tracer is not None else get_tracer()
        self._tid_fn = tid_fn
        self._lock = threading.Lock()
        now = time.monotonic_ns()
        self._t0_ns = now
        self._last_ns = now  # end of the last collected window
        self._cursor = 0
        self._dropped = 0  # records lost to ring lapping
        self._seconds: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        # live episodes (None = not active) + closed-but-uncollected
        self._degraded_since: Optional[int] = None
        self._degraded_closed: List[Tuple[int, int]] = []
        self._replay_since: Optional[int] = None
        self._replay_closed: List[Tuple[int, int]] = []
        self._eviction_since: Optional[int] = None
        self._eviction_closed: List[Tuple[int, int]] = []
        self._serving_since: Optional[int] = None
        self._serving_closed: List[Tuple[int, int]] = []

    # -- event-derived categories (PR-5 node events) -------------------
    def degraded_enter(self):
        """Storage persists failing; checkpoints are shm-only (the
        saver's ``ckpt_degraded`` node event)."""
        with self._lock:
            if self._degraded_since is None:
                self._degraded_since = time.monotonic_ns()

    def degraded_exit(self):
        with self._lock:
            if self._degraded_since is not None:
                self._degraded_closed.append(
                    (self._degraded_since, time.monotonic_ns())
                )
                self._degraded_since = None

    def replay_begin(self):
        """Entering the lost-progress window after a restore: steps run
        until ``replay_end()`` re-earn work a previous incarnation had
        already done."""
        with self._lock:
            if self._replay_since is None:
                self._replay_since = time.monotonic_ns()

    def replay_end(self):
        with self._lock:
            if self._replay_since is not None:
                self._replay_closed.append(
                    (self._replay_since, time.monotonic_ns())
                )
                self._replay_since = None

    def eviction_begin(self):
        """Entering the eviction grace-window drain (a preemption
        notice arrived): every second until ``eviction_end()`` — the
        finishing step, the emergency checkpoint, the forensics flush —
        is the preemption's cost, booked above every span category."""
        with self._lock:
            if self._eviction_since is None:
                self._eviction_since = time.monotonic_ns()

    def eviction_end(self):
        with self._lock:
            if self._eviction_since is not None:
                self._eviction_closed.append(
                    (self._eviction_since, time.monotonic_ns())
                )
                self._eviction_since = None

    def serving_begin(self):
        """The co-located serving plane started decoding a batch.
        Ranked below every training category, so serving only claims
        wall time training left unclaimed — the idle gaps it is meant
        to soak; a batch that overlaps a ``compute`` span costs the
        serving row nothing (training already owns that second)."""
        with self._lock:
            if self._serving_since is None:
                self._serving_since = time.monotonic_ns()

    def serving_end(self):
        with self._lock:
            if self._serving_since is not None:
                self._serving_closed.append(
                    (self._serving_since, time.monotonic_ns())
                )
                self._serving_since = None

    def mark_interval(self, category: str, start_ns: int, end_ns: int):
        """Attribute an explicit monotonic-ns interval (bench probes
        that measure a restore with ``time.perf_counter`` bracket it
        here instead of re-inventing the categories; a serving plane
        running in another process reports its busy windows the same
        way)."""
        buckets = {
            "restart_replay": self._replay_closed,
            "degraded": self._degraded_closed,
            "eviction": self._eviction_closed,
            "serving_soak": self._serving_closed,
        }
        if category not in buckets:
            raise ValueError(
                f"mark_interval supports the event-derived categories "
                f"({', '.join(buckets)}), got {category!r}"
            )
        with self._lock:
            buckets[category].append((int(start_ns), int(end_ns)))

    # -- collection ----------------------------------------------------
    def _episode_intervals(
        self, closed: List[Tuple[int, int]], since: Optional[int],
        a: int, b: int,
    ) -> List[Tuple[int, int]]:
        """Window-clipped intervals for one episode kind; consumes the
        closed list (portions beyond ``b`` are put back)."""
        ivs = []
        keep = []
        for lo, hi in closed:
            if hi > b:
                keep.append((max(lo, b), hi))
                hi = b
            lo, hi = max(lo, a), min(hi, b)
            if hi > lo:
                ivs.append((lo, hi))
        closed[:] = keep
        if since is not None:
            lo = max(since, a)
            if b > lo:
                ivs.append((lo, b))
        return ivs

    def collect(self, now_ns: Optional[int] = None):
        """Attribute the window since the last collect. Records are
        clipped to the window, so a span that was partially counted
        while still open (or that straddles two collects) never
        double-claims."""
        with self._lock:
            b = int(now_ns) if now_ns is not None else time.monotonic_ns()
            a = self._last_ns
            if b <= a:
                return
            self._last_ns = b
            tid = self._tid_fn() if self._tid_fn is not None else None
            # open spans are snapshotted BEFORE the drain: a span that
            # completes in between is then claimed by BOTH views of the
            # same window, and the per-category merge coalesces the
            # overlap — the reverse order would let it slip past both
            # (gone from the open list, clipped to emptiness when its
            # record arrives next window) and lose its entire duration
            open_records = self._tracer.open_span_records(tid=tid)
            records, self._cursor, dropped = self._tracer.drain(
                self._cursor
            )
            self._dropped += dropped

            per_cat: Dict[str, List[Tuple[int, int]]] = {
                c: [] for c in CATEGORIES
            }
            for name, rtid, start, dur, _depth, _attrs, _seq in records:
                cat = SPAN_CATEGORY.get(name)
                if cat is None or (tid is not None and rtid != tid):
                    continue
                lo, hi = max(start, a), min(start + dur, b)
                if hi > lo:
                    per_cat[cat].append((lo, hi))
            # spans open at snapshot time (a wedged ckpt_commit, a long
            # data_wait): claim their elapsed part up to b; the
            # completed record is later clipped to the next window
            for name, rtid, start, _depth in open_records:
                cat = SPAN_CATEGORY.get(name)
                if cat is None:
                    continue
                lo = max(start, a)
                if b > lo:
                    per_cat[cat].append((lo, b))
            per_cat["restart_replay"].extend(
                self._episode_intervals(
                    self._replay_closed, self._replay_since, a, b
                )
            )
            per_cat["degraded"].extend(
                self._episode_intervals(
                    self._degraded_closed, self._degraded_since, a, b
                )
            )
            per_cat["eviction"].extend(
                self._episode_intervals(
                    self._eviction_closed, self._eviction_since, a, b
                )
            )
            per_cat["serving_soak"].extend(
                self._episode_intervals(
                    self._serving_closed, self._serving_since, a, b
                )
            )

            covered: List[Tuple[int, int]] = []
            for cat in CATEGORIES:
                if cat == "other":
                    continue
                claimed = _subtract(_merge(per_cat[cat]), covered)
                self._seconds[cat] += _total_s(claimed)
                covered = _merge(covered + claimed)

    # -- reporting -----------------------------------------------------
    def snapshot(self, now_ns: Optional[int] = None) -> GoodputReport:
        self.collect(now_ns=now_ns)
        with self._lock:
            wall = (self._last_ns - self._t0_ns) / 1e9
            seconds = dict(self._seconds)
            attributed = sum(seconds.values())
            # "other" closes the partition; interval bugs surface as a
            # negative remainder => closure_error_pct > 0, which the
            # smoke gate catches instead of silently clamping
            seconds["other"] = wall - attributed
            return GoodputReport(wall_s=wall, seconds=seconds)

    @property
    def dropped_records(self) -> int:
        """Spans lost to ring-buffer lapping between collects (their
        time lands in "other" — collect more often if nonzero)."""
        with self._lock:
            return self._dropped

    def export(self, registry) -> GoodputReport:
        """Snapshot + publish the ``dlrover_goodput_*`` gauges. The
        trainer calls this at log cadence, so the scalars ride the
        runtime-metrics file to the master like every other registry
        number."""
        report = self.snapshot()
        g = registry.gauge(
            METRIC_PREFIX + "seconds_total",
            "wall seconds attributed per goodput category",
            labelnames=("category",),
        )
        for cat, secs in report.seconds.items():
            g.labels(cat).set(secs)
        registry.gauge(
            METRIC_PREFIX + "wall_seconds",
            "wall seconds accounted by the goodput ledger",
        ).set(report.wall_s)
        registry.gauge(
            METRIC_PREFIX + "pct",
            "productive_compute share of wall time, percent",
        ).set(report.goodput_pct)
        return report


# -- process-default ledger (the saver's degraded hooks and the trainer
# both reach it without holding a reference to each other) ------------

_default: Optional[GoodputLedger] = None
_default_lock = threading.Lock()


def install_default_ledger(ledger: GoodputLedger) -> GoodputLedger:
    global _default
    with _default_lock:
        _default = ledger
    return ledger


def default_ledger() -> Optional[GoodputLedger]:
    return _default


def note_degraded(entered: bool):
    """PR-5 degraded-mode seam: the checkpoint saver flips this on
    episode entry/exit; a no-op until a trainer installs a ledger."""
    ledger = _default
    if ledger is None:
        return
    if entered:
        ledger.degraded_enter()
    else:
        ledger.degraded_exit()


def note_serving(active: bool):
    """Serving-plane seam: the co-located inference engine flips this
    around each decode batch so the trainer's ledger prices exactly
    what co-location costs; a no-op until a trainer installs a
    ledger."""
    ledger = _default
    if ledger is None:
        return
    if active:
        ledger.serving_begin()
    else:
        ledger.serving_end()
