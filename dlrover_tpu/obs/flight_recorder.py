"""Crash flight recorder: an always-on black box for training forensics.

When a trainer dies, hangs, or drops into degraded mode, the evidence
usually dies with it — the span ring lives in process memory, the
metrics registry was last exported a log-interval ago, and the thread
that knows why is the one that is wedged. The flight recorder keeps a
bounded event log while everything is healthy and, on trigger, dumps a
self-contained **bundle** to a quarantine-style directory
(``DLROVER_TPU_FLIGHT_DIR``, default ``/tmp/dlrover_tpu/flight``):

```
<flight_dir>/<utc-stamp>_<reason>_pid<pid>/
  manifest.json   trigger reason, wall/monotonic stamps, node identity,
                  config/mesh fingerprint, open spans, goodput snapshot,
                  exception (crash dumps)
  trace.json      last-N spans as a valid Chrome trace (Perfetto-loadable,
                  mergeable across workers by tools/merge_timeline.py)
  metrics.prom    Prometheus text exposition of the whole registry
  stacks.txt      every thread's current Python stack
  events.json     recent node events (degraded entry/exit, injected
                  faults, restarts — whatever note_event saw)
```

Triggers:

- **crash** — ``ElasticTrainer.train`` dumps on any escaping exception;
- **hang** — the built-in watchdog thread dumps when the train thread's
  innermost span stays open past ``hang_dump_after_s`` (once per
  episode; the loop being wedged is exactly when only a daemon thread
  can still write);
- **degraded entry** — the PR-5 checkpoint saver's episode hook;
- **master request** — the master queues a ``flight_dump`` worker
  command (RPC → agent relay file → trainer poll) to pull a bundle
  from one specific worker while it is still alive.

``ProfilerCapture`` is the companion evidence channel: a master
``profile`` command (auto-queued at most once per straggler episode)
arms a K-step ``jax.profiler`` trace whose artifact lands in the same
bundle directory tree, so a flagged straggler ships device-level
evidence with its attribution.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.log import default_logger as logger

ENV_FLIGHT_DIR = "DLROVER_TPU_FLIGHT_DIR"
DEFAULT_FLIGHT_DIR = "/tmp/dlrover_tpu/flight"

# two dumps closer than this are one incident — the second trigger
# (e.g. crash right after the hang watchdog fired) is folded into the
# first bundle's story instead of doubling the artifacts
MIN_DUMP_INTERVAL_S = 5.0

_EVENT_LOG_CAP = 256


def flight_dir() -> str:
    return os.getenv(ENV_FLIGHT_DIR, DEFAULT_FLIGHT_DIR)


def _thread_stacks() -> str:
    """Every thread's current Python stack, hang-safe (no locks the
    train loop could hold)."""
    lines: List[str] = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(tid, '?')} (tid {tid}) ---")
        lines.extend(
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        )
        lines.append("")
    return "\n".join(lines)


class FlightRecorder:
    """Bounded event log + bundle dumper. One per process is the
    intended shape (``default_recorder``); construct directly in tests.
    """

    def __init__(
        self,
        base_dir: str = "",
        tracer=None,
        registry=None,
        identity: Optional[Dict] = None,
    ):
        from dlrover_tpu.obs.metrics import default_registry
        from dlrover_tpu.obs.trace import get_tracer

        # "" = resolve flight_dir() per dump, so redirecting the env
        # var works even after the process-default recorder exists
        # (bench legs and tests point it at a scratch dir)
        self._base_dir = base_dir
        self._tracer = tracer if tracer is not None else get_tracer()
        self._registry = (
            registry if registry is not None else default_registry()
        )
        # node identity + config/mesh fingerprint, set by the trainer
        self._identity: Dict = dict(identity or {})
        self._events: deque = deque(maxlen=_EVENT_LOG_CAP)
        self._lock = threading.Lock()
        self._last_dump_ts = 0.0
        self._dumps: List[str] = []
        # hang watchdog state
        self._watchdog: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()
        self._hang_dumped_for: Optional[float] = None
        # deliberate-maintenance window (eviction drain, resize): the
        # train thread is SUPPOSED to sit in one long span, and a hang
        # dump of a healthy drain is forged evidence
        self._suppress_until = 0.0

    # -- deliberate-maintenance suppression ----------------------------
    def suppress_watchdog(self, duration_s: float):
        """Declare the next ``duration_s`` a deliberate maintenance
        window (graceful drain, resize): the hang watchdog must not
        dump a bundle for a stall the trainer chose. Windows extend,
        never shrink; ``clear_suppression()`` ends one early."""
        with self._lock:
            self._suppress_until = max(
                self._suppress_until, time.monotonic() + duration_s
            )

    def clear_suppression(self):
        with self._lock:
            self._suppress_until = 0.0

    def watchdog_suppressed(self) -> bool:
        with self._lock:
            return time.monotonic() < self._suppress_until

    # -- identity / events ---------------------------------------------
    def set_identity(self, **fields):
        """Stamp node/job/mesh identity into every future manifest
        (e.g. ``node_id``, ``job_name``, ``mesh``, ``config_digest``)."""
        with self._lock:
            self._identity.update(fields)

    def note_event(self, kind: str, detail: str = ""):
        """Append to the bounded black-box event log (degraded entry,
        fault injections, restarts...)."""
        self._events.append(
            {"ts": time.time(), "kind": str(kind), "detail": str(detail)}
        )

    def events(self) -> List[dict]:
        return list(self._events)

    @property
    def dumps(self) -> List[str]:
        """Bundle directories written by this recorder."""
        with self._lock:
            return list(self._dumps)

    # -- the dump ------------------------------------------------------
    def dump(
        self,
        reason: str,
        exc: Optional[BaseException] = None,
        extra: Optional[Dict] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Write one bundle; returns its directory (None when rate-
        limited or when the dump itself failed — forensics must never
        take the job down with it)."""
        now = time.time()
        with self._lock:
            if not force and now - self._last_dump_ts < MIN_DUMP_INTERVAL_S:
                return None
            self._last_dump_ts = now
        try:
            return self._dump_locked(reason, exc, extra, now)
        except Exception as e:  # pragma: no cover - defensive
            logger.error(f"flight-recorder dump failed: {e!r}")
            return None

    def _dump_locked(self, reason, exc, extra, now) -> str:
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason
        )
        bundle = os.path.join(
            self._base_dir or flight_dir(),
            f"{stamp}_{safe_reason}_pid{os.getpid()}",
        )
        n = 1
        while os.path.exists(bundle):
            bundle = f"{bundle}.{n}"
            n += 1
        os.makedirs(bundle, exist_ok=True)

        # stacks first: the most perishable evidence, and the cheapest
        with open(os.path.join(bundle, "stacks.txt"), "w") as f:
            f.write(_thread_stacks())
        with open(os.path.join(bundle, "trace.json"), "w") as f:
            json.dump(self._tracer.chrome_trace(), f)
        with open(os.path.join(bundle, "metrics.prom"), "w") as f:
            f.write(self._registry.prometheus_text())
        with open(os.path.join(bundle, "events.json"), "w") as f:
            json.dump(self.events(), f, indent=1)

        manifest = {
            "reason": reason,
            "wall_ts": now,
            "monotonic_ns": time.monotonic_ns(),
            "pid": os.getpid(),
            "identity": dict(self._identity),
            "open_spans": self._tracer.open_spans(),
            "span_records_buffered": len(self._tracer),
        }
        try:
            from dlrover_tpu.obs.goodput import default_ledger

            ledger = default_ledger()
            if ledger is not None:
                manifest["goodput"] = ledger.snapshot().as_dict()
        except Exception:
            pass
        if exc is not None:
            manifest["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__
                ),
            }
        if extra:
            manifest["extra"] = dict(extra)
        with open(os.path.join(bundle, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        with self._lock:
            self._dumps.append(bundle)
        logger.warning(f"flight recorder: bundle dumped to {bundle}")
        return bundle

    # -- hang watchdog -------------------------------------------------
    def start_watchdog(
        self,
        hang_dump_after_s: float = 120.0,
        tid_fn: Optional[Callable[[], Optional[int]]] = None,
        interval_s: float = 5.0,
    ):
        """Daemon thread: dump once per hang episode when the watched
        thread's innermost open span exceeds ``hang_dump_after_s``.
        This is the only trigger that works while the train loop is
        wedged — the whole reason the recorder is a separate thread."""
        if self._watchdog is not None:
            return

        def _run():
            while not self._watchdog_stop.wait(interval_s):
                try:
                    if self.watchdog_suppressed():
                        # deliberate drain/resize window: a long open
                        # span here is the PLAN, not a hang. A span
                        # still open past the threshold AFTER the
                        # window expires dumps then — a wedged resize
                        # is a real hang
                        self._hang_dumped_for = None
                        continue
                    tid = tid_fn() if tid_fn is not None else None
                    hit = self._tracer.last_open_span(tid=tid)
                    if hit is None or hit[1] < hang_dump_after_s:
                        self._hang_dumped_for = None
                        continue
                    # one dump per episode: the span's start identifies
                    # the episode (elapsed keeps growing while stuck)
                    episode = time.monotonic() - hit[1]
                    prev = self._hang_dumped_for
                    if prev is not None and abs(prev - episode) < 1.0:
                        continue
                    self._hang_dumped_for = episode
                    self.note_event(
                        "hang",
                        f"stuck in {hit[0]} for {hit[1]:.0f}s",
                    )
                    self.dump(
                        "hang",
                        extra={"span": hit[0], "elapsed_s": hit[1]},
                    )
                except Exception:
                    pass  # the watchdog must never hurt training

        self._watchdog_stop.clear()
        self._watchdog = threading.Thread(
            target=_run, name="flight-watchdog", daemon=True
        )
        self._watchdog.start()

    def stop_watchdog(self):
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
            self._watchdog = None


class ProfilerCapture:
    """On-demand K-step ``jax.profiler`` capture, armed by a master
    ``profile`` worker command and driven by the train loop's
    ``on_step_begin``/``on_step_end`` hooks (both no-ops while idle).

    At most one capture runs at a time; re-requests during a live or
    cooling-down capture are dropped, which combined with the master's
    once-per-straggler-episode queueing bounds artifact volume."""

    def __init__(self, out_root: str = "", cooldown_s: float = 300.0):
        self._out_root = out_root  # "" = <flight_dir()>/profiles per use
        self._cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._pending_steps = 0
        self._reason = ""
        self._active_dir: Optional[str] = None
        self._last_done_ts = 0.0
        self.artifacts: List[str] = []

    def request(self, steps: int = 3, reason: str = "manual") -> bool:
        """Arm a capture of ``steps`` train steps; False when refused
        (already active / cooling down / bad arg)."""
        steps = int(steps)
        if steps <= 0:
            return False
        with self._lock:
            if self._active_dir is not None or self._pending_steps:
                return False
            if time.time() - self._last_done_ts < self._cooldown_s:
                return False
            self._pending_steps = steps
            self._reason = reason
            return True

    @property
    def active(self) -> bool:
        return self._active_dir is not None

    def on_step_begin(self):
        with self._lock:
            if self._pending_steps <= 0 or self._active_dir is not None:
                return
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            out = os.path.join(
                self._out_root or os.path.join(flight_dir(), "profiles"),
                f"{stamp}_{self._reason}",
            )
            os.makedirs(out, exist_ok=True)
            try:
                import jax

                jax.profiler.start_trace(out)
            except Exception as e:
                logger.warning(f"profiler capture failed to start: {e!r}")
                self._pending_steps = 0
                return
            self._active_dir = out
            logger.info(
                f"profiler capture started ({self._pending_steps} "
                f"steps -> {out}, reason={self._reason})"
            )

    def on_step_end(self):
        with self._lock:
            if self._active_dir is None:
                return
            self._pending_steps -= 1
            if self._pending_steps > 0:
                return
            out = self._active_dir
            self._active_dir = None
            self._last_done_ts = time.time()
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                logger.warning(f"profiler capture failed to stop: {e!r}")
                return
            self.artifacts.append(out)
            logger.info(f"profiler capture finished: {out}")

    def abort(self):
        """Stop a live capture (trainer close/resize)."""
        with self._lock:
            self._pending_steps = 0
            if self._active_dir is None:
                return
            self._active_dir = None
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass


# -- process-default recorder ------------------------------------------------

_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def default_recorder() -> FlightRecorder:
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder()
        return _default


def note_event(kind: str, detail: str = ""):
    """Event-log seam for subsystems that must not hold a recorder
    reference (ckpt saver, fault injector): always records; only the
    degraded-mode entry also triggers a dump (once per episode via the
    rate limiter)."""
    rec = default_recorder()
    rec.note_event(kind, detail)
    if kind == "ckpt_degraded":
        rec.dump("degraded", extra={"detail": detail})
