"""Metrics registry: counters / gauges / histograms with one export path.

Before this module every fast-path subsystem invented its own counter
surface (``PipelineStats`` fields, bench result keys, ad-hoc scalars in
the runtime-metrics file). The registry gives them one home with two
read sides:

- ``prometheus_text()`` — the Prometheus text exposition format, for
  scraping / file drops (names and label conventions in
  docs/observability.md);
- ``scalars()`` — a flat ``{name: float}`` dict the trainer merges into
  ``report_runtime_metrics`` so the agent's TrainingMonitor forwards
  every registry scalar to the master's collector unchanged.

``fold_pipeline_stats`` is the adapter that makes ``PipelineStats`` a
*view* into the registry instead of a second export path: it walks
``as_dict()`` generically, so a PipelineStats field added tomorrow
shows up in both exports without touching this file (the drift-tripwire
test in tests/test_obs.py enforces the ``as_dict`` side).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# seconds-scale latency buckets (prometheus client defaults)
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# every PipelineStats-derived gauge is exported under this prefix
PIPELINE_PREFIX = "dlrover_pipeline_"

# cardinality guard: a label value drawn from an unbounded set (step
# numbers, pod names of a churning fleet) would grow the exposition —
# and every scalars() forward to the master — without bound. Past the
# cap a metric warns ONCE and refuses growth: unseen label sets share
# one detached overflow child that never enters the exposition, so
# writes stay cheap no-ops instead of raising on the hot path.
# (Departed-WORKER pruning is the aggregator's job; this protects the
# registry itself from any mislabeled series.)
ENV_MAX_LABEL_SETS = "DLROVER_TPU_METRIC_MAX_LABEL_SETS"
DEFAULT_MAX_LABEL_SETS = 256


def _default_max_label_sets() -> int:
    try:
        return int(
            os.getenv(ENV_MAX_LABEL_SETS, str(DEFAULT_MAX_LABEL_SETS))
        )
    except ValueError:
        return DEFAULT_MAX_LABEL_SETS


def _label_key(
    labelnames: Sequence[str], labelvalues: Sequence[str]
) -> Tuple[str, ...]:
    if len(labelvalues) != len(labelnames):
        raise ValueError(
            f"expected labels {tuple(labelnames)}, got {tuple(labelvalues)}"
        )
    return tuple(str(v) for v in labelvalues)


def _fmt_labels(labelnames, key) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{n}="{v}"' for n, v in zip(labelnames, key)
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str],
        max_label_sets: Optional[int] = None,
    ):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_label_sets = (
            int(max_label_sets)
            if max_label_sets is not None
            else _default_max_label_sets()
        )
        self._children: Dict[Tuple[str, ...], object] = {}
        self._overflow = None  # shared sink past the cardinality cap
        self._overflow_warned = False
        self._lock = threading.Lock()

    def labels(self, *labelvalues, **labelkw):
        if labelkw:
            labelvalues = tuple(
                labelkw[n] for n in self.labelnames
            )
        key = _label_key(self.labelnames, labelvalues)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    if len(self._children) >= self.max_label_sets:
                        return self._overflow_child()
                    child = self._children.setdefault(
                        key, self._new_child()
                    )
        return child

    def _overflow_child(self):
        """Detached child for label sets past the cap (lock held):
        callers keep working, but the series never reaches the
        exposition — bounded memory beats a hot-path exception."""
        if not self._overflow_warned:
            self._overflow_warned = True
            from dlrover_tpu.common.log import default_logger as logger

            logger.warning(
                f"metric {self.name} hit its label-set cap "
                f"({self.max_label_sets}); new label sets are dropped "
                f"from the exposition — an unbounded label value "
                f"(step? pod name?) is leaking into "
                f"{self.labelnames} (cap: {ENV_MAX_LABEL_SETS})"
            )
        if self._overflow is None:
            self._overflow = self._new_child()
        return self._overflow

    def label_set_count(self) -> int:
        """Distinct label sets currently live (the guard's read side)."""
        with self._lock:
            return len(self._children)

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; call "
                f".labels(...) first"
            )
        return self.labels()

    def _new_child(self):
        raise NotImplementedError


class _Value:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    @property
    def value(self) -> float:
        return self._v


class _CounterChild(_Value):
    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._v += amount


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0):
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _GaugeChild(_Value):
    def set(self, v: float):
        with self._lock:
            self._v = float(v)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._v += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float):
        self._default_child().set(v)

    def inc(self, amount: float = 1.0):
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _HistogramChild:
    __slots__ = ("_buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets: Sequence[float]):
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, le in enumerate(self._buckets):
                if v <= le:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count)] including +Inf — the exposition
        shape."""
        out = []
        running = 0
        for le, c in zip(self._buckets, self._counts):
            running += c
            out.append((le, running))
        out.append((math.inf, running + self._counts[-1]))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-resolution quantile (upper bound of the bucket the
        q-th observation lands in) — good enough for straggler ratios,
        not for SLO math."""
        if not self._count:
            return None
        target = q * self._count
        for le, cum in self.cumulative():
            if cum >= target:
                return le if le != math.inf else self._buckets[-1]
        return self._buckets[-1]


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self, name, help, labelnames=(), buckets=DEFAULT_BUCKETS,
        max_label_sets=None,
    ):
        super().__init__(
            name, help, labelnames, max_label_sets=max_label_sets
        )
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float):
        self._default_child().observe(v)

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum

    def quantile(self, q: float) -> Optional[float]:
        return self._default_child().quantile(q)


class MetricsRegistry:
    """Get-or-create metric catalog. Re-requesting a name returns the
    existing metric (so call sites don't coordinate creation), but a
    kind mismatch is a hard error — two subsystems disagreeing about
    what a name *is* must fail loudly, not silently shadow."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"{name} already registered as {m.kind}, "
                        f"requested {cls.kind}"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(
        self, name: str, help: str = "", labelnames=(),
        max_label_sets=None,
    ) -> Counter:
        return self._get_or_create(
            Counter, name, help, labelnames,
            max_label_sets=max_label_sets,
        )

    def gauge(
        self, name: str, help: str = "", labelnames=(),
        max_label_sets=None,
    ) -> Gauge:
        return self._get_or_create(
            Gauge, name, help, labelnames,
            max_label_sets=max_label_sets,
        )

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames=(),
        buckets=DEFAULT_BUCKETS,
        max_label_sets=None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets,
            max_label_sets=max_label_sets,
        )

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- export --------------------------------------------------------
    def prometheus_text(self) -> str:
        """Prometheus text exposition (the format a /metrics endpoint
        or node-exporter textfile drop serves)."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            children = list(m._children.items()) or (
                [] if m.labelnames else [((), m._default_child())]
            )
            for key, child in children:
                labels = _fmt_labels(m.labelnames, key)
                if isinstance(m, Histogram):
                    for le, cum in child.cumulative():
                        le_lbl = (
                            _fmt_labels(
                                m.labelnames + ("le",),
                                key + (_fmt_value(le),),
                            )
                        )
                        lines.append(
                            f"{m.name}_bucket{le_lbl} {cum}"
                        )
                    lines.append(
                        f"{m.name}_sum{labels} {_fmt_value(child.sum)}"
                    )
                    lines.append(f"{m.name}_count{labels} {child.count}")
                else:
                    lines.append(
                        f"{m.name}{labels} {_fmt_value(child.value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def scalars(self) -> Dict[str, float]:
        """Flat ``{name[{labels}]: value}`` — the shape the trainer
        merges into the runtime-metrics file for master forwarding.
        Histograms export ``_sum``/``_count`` (the master re-derives
        rates; raw buckets stay scrape-side)."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            children = list(m._children.items()) or (
                [] if m.labelnames else [((), m._default_child())]
            )
            for key, child in children:
                labels = _fmt_labels(m.labelnames, key)
                if isinstance(m, Histogram):
                    out[f"{m.name}_sum{labels}"] = float(child.sum)
                    out[f"{m.name}_count{labels}"] = float(child.count)
                else:
                    out[f"{m.name}{labels}"] = float(child.value)
        return out


def fold_pipeline_stats(stats, registry: "MetricsRegistry") -> int:
    """Fold a ``PipelineStats`` record into the registry as gauges —
    ONE export path for the pipeline counters. Walks ``as_dict()``
    generically: numeric entries become ``dlrover_pipeline_<field>``
    gauges, ``None`` entries export as NaN-free 0-gauges so the name
    still exists (dashboards key on presence), list-valued ratio pairs
    are skipped (their scalar components are separate fields already).
    Returns the number of gauges written."""
    n = 0
    for key, value in stats.as_dict().items():
        if isinstance(value, (list, tuple, dict, str)):
            continue  # composite view; components are separate fields
        g = registry.gauge(
            PIPELINE_PREFIX + key,
            "pipeline stat (accel/profiler.PipelineStats)",
        )
        g.set(0.0 if value is None else float(value))
        n += 1
    return n


# -- process-wide default registry ------------------------------------------

_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default
