"""Unified telemetry for dlrover-tpu: span tracing, metrics, attribution.

Three layers, one spine (docs/observability.md):

- ``obs.trace`` — a low-overhead, thread-safe span tracer the trainer,
  prefetcher, checkpoint engine and grad-sync paths write the real step
  timeline into; exports Chrome trace-event JSON (Perfetto-loadable)
  and answers "what is this process doing RIGHT NOW" (hang
  attribution);
- ``obs.metrics`` — a counters/gauges/histograms registry with
  Prometheus text exposition; the existing ``PipelineStats`` record
  folds into it so there is exactly one export path for every number
  the fast-path subsystems produce;
- ``obs.aggregate`` — the master's side: per-worker step-time
  aggregation, straggler detection against the fleet median, hang
  reports enriched with each worker's last open span, and the fleet
  goodput rollup;
- ``obs.goodput`` — the accounting layer: a ``GoodputLedger`` that
  attributes every second of trainer wall time to a closed taxonomy
  derived from the span stream, with a closure invariant gated by
  ``bench.py --smoke``;
- ``obs.flight_recorder`` — the forensics layer: an always-on black
  box that dumps a self-contained bundle (trace, metrics, stacks,
  events, manifest) on crash/hang/degraded-entry or master request,
  plus on-demand K-step ``jax.profiler`` captures.
"""
