"""Unified telemetry for dlrover-tpu: span tracing, metrics, attribution.

Three layers, one spine (docs/observability.md):

- ``obs.trace`` — a low-overhead, thread-safe span tracer the trainer,
  prefetcher, checkpoint engine and grad-sync paths write the real step
  timeline into; exports Chrome trace-event JSON (Perfetto-loadable)
  and answers "what is this process doing RIGHT NOW" (hang
  attribution);
- ``obs.metrics`` — a counters/gauges/histograms registry with
  Prometheus text exposition; the existing ``PipelineStats`` record
  folds into it so there is exactly one export path for every number
  the fast-path subsystems produce;
- ``obs.aggregate`` — the master's side: per-worker step-time
  aggregation, straggler detection against the fleet median, and hang
  reports enriched with each worker's last open span.
"""
