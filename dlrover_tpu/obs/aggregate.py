"""Master-side telemetry aggregation: per-worker step times, straggler
detection, hang attribution.

The master already knows the FLEET's speed (SpeedMonitor's sliding
window over the max global step) — what it cannot answer is *which
worker* is slow or *what* a stuck worker is doing. This module holds
the per-worker view:

- **step-time histograms** — one bounded window per worker, fed two
  ways: an explicit ``step_time_ms`` scalar when the worker reports it
  (the ElasticTrainer does, at log cadence), else derived from
  consecutive ``GlobalStepReport`` (Δtimestamp / Δstep). Explicit wins:
  once a worker has sent a real measurement the coarse derivation for
  that worker is ignored.
- **straggler detection** — a worker whose p50 step time exceeds
  ``ratio`` × the fleet median p50 (``ratio`` defaults to the
  ``straggler_time_ratio`` context knob) is flagged; newly-flagged
  workers are pushed to the Brain datastore through ``brain_reporter``
  (event ``"straggler"``, see brain/ingestion.straggler_sink) so the
  evidence survives this master, and the auto-scaler reads the flags
  off ``stragglers``.
- **hang attribution** — each worker's last reported open span (the
  SpanHeartbeat channel through the runtime-metrics file →
  TrainingMonitor → ``TrainMetricsReport``) is kept with its receipt
  time, so a hang report can say "worker 3 stuck in ckpt_commit for
  42s" instead of "no step progress".
- **step-budget attribution** — the per-component audit scalars
  (``dlrover_audit_*``, obs/audit.py) ride the same metrics channel;
  a straggler flag is upgraded from "worker 3 is slow" to "worker 3's
  dcn_sync is 2.4× its budget while its compute is on-price", and that
  *why* travels to the Brain in the straggler row's ``detail``.
"""

from __future__ import annotations

import re
import statistics
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from dlrover_tpu.common.global_context import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.obs.goodput import (
    CATEGORIES as GOODPUT_CATEGORIES,
    compute_goodput_pct,
)

_ctx = Context.singleton_instance()

# a (derived or explicit) step-time sample longer than this is a stall
# artifact (restart, resize, rendezvous), not a speed signal
_MAX_SAMPLE_S = 3600.0

# the ledger scalars as they arrive through the runtime-metrics file ->
# TrainingMonitor -> TrainMetricsReport flattening, e.g.
# 'dlrover_goodput_seconds_total{category="ckpt_block"}'
_GOODPUT_SECONDS_RE = re.compile(
    r'^dlrover_goodput_seconds_total\{category="([a-z_]+)"\}$'
)
_GOODPUT_WALL_KEY = "dlrover_goodput_wall_seconds"

# the step-budget auditor's per-component scalars (obs/audit.py) ride
# the same flattened channel: the observed/budget ratio and the alarm
# latch per priced component, e.g.
# 'dlrover_audit_budget_ratio{component="dcn_sync"}'
_AUDIT_RATIO_RE = re.compile(
    r'^dlrover_audit_budget_ratio\{component="([a-z_]+)"\}$'
)
_AUDIT_ALARM_RE = re.compile(
    r'^dlrover_audit_alarm\{component="([a-z_]+)"\}$'
)
# a component within this band of its (drift-corrected) budget reads
# as "on-price" in the straggler attribution line
_AUDIT_ON_PRICE_BAND = (0.75, 1.25)


class TelemetryAggregator:
    def __init__(
        self,
        straggler_ratio: Optional[float] = None,
        window: int = 64,
        min_samples: int = 4,
        brain_reporter: Optional[Callable[[int, float, float], None]] = None,
    ):
        # > 1.0 multiple of the fleet median p50; the context knob is
        # the job-wide default, per-master override via the ctor
        self.straggler_ratio = float(
            straggler_ratio
            if straggler_ratio is not None
            else _ctx.straggler_time_ratio
        )
        self._window = max(int(window), 4)
        self._min_samples = max(int(min_samples), 1)
        self._brain_reporter = brain_reporter
        self._lock = threading.Lock()
        self._samples: Dict[int, Deque[float]] = {}
        self._explicit: set = set()  # workers with real step_time_ms
        self._last_report: Dict[int, Tuple[int, float]] = {}
        # worker -> (span name, elapsed_s at receipt, monotonic receipt)
        self._open_spans: Dict[int, Tuple[str, float, float]] = {}
        self._last_metrics: Dict[int, dict] = {}
        self._flagged: set = set()
        # worker -> {"wall_s": float, "seconds": {category: s}} — the
        # latest goodput-ledger snapshot each worker reported
        self._goodput: Dict[int, dict] = {}
        # worker -> {"ratio": {component: x}, "alarm": {component: 0/1}}
        # — the latest step-budget audit snapshot (obs/audit.py)
        self._audit: Dict[int, dict] = {}
        # straggler auto-profile: called once per newly-flagged worker
        # (the master wires this to queue a `profile` worker command)
        self._profile_requester: Optional[Callable[[int], None]] = None
        # deliberate-maintenance window (eviction drain, resize): new
        # straggler flags and hang forensics are suppressed while the
        # fleet is DESIGNED to be stalled
        self._maintenance_until = 0.0

    # -- maintenance window --------------------------------------------
    def note_maintenance(self, duration_s: float):
        """Declare the next ``duration_s`` a deliberate maintenance
        window (a resize or an eviction drain is in flight): straggler
        attribution must not flag workers for pausing on purpose, and
        the master's hang path must not aim ``flight_dump`` commands at
        healthy workers. Windows extend, never shrink."""
        with self._lock:
            self._maintenance_until = max(
                self._maintenance_until,
                time.monotonic() + float(duration_s),
            )

    def in_maintenance(self) -> bool:
        with self._lock:
            return time.monotonic() < self._maintenance_until

    # -- ingestion (servicer / speed-monitor hooks) --------------------
    def observe_step_report(
        self, worker_id: int, step: int, timestamp: float
    ):
        """Per-worker step-time derivation from the global-step channel
        (every worker reports; no trainer changes needed)."""
        if worker_id < 0:
            return
        with self._lock:
            prev = self._last_report.get(worker_id)
            self._last_report[worker_id] = (step, timestamp)
            if (
                prev is None
                or step <= prev[0]
                or timestamp <= prev[1]
                or worker_id in self._explicit
            ):
                return
            per_step = (timestamp - prev[1]) / (step - prev[0])
            if 0.0 < per_step <= _MAX_SAMPLE_S:
                self._bucket(worker_id).append(per_step)

    def observe_metrics(
        self,
        worker_id: int,
        step: int,
        metrics: Optional[dict] = None,
        open_span: str = "",
        open_span_elapsed_s: float = 0.0,
    ):
        """The TrainMetricsReport hook: explicit step-time samples plus
        the hang-attribution span snapshot."""
        if worker_id < 0:
            return
        metrics = metrics or {}
        with self._lock:
            if metrics:
                self._last_metrics[worker_id] = dict(metrics)
                self._ingest_goodput(worker_id, metrics)
                self._ingest_audit(worker_id, metrics)
            st_ms = metrics.get("step_time_ms")
            if st_ms is not None and st_ms > 0:
                if worker_id not in self._explicit:
                    # switch sources: coarse derived samples would skew
                    # the percentile the explicit channel now owns
                    self._explicit.add(worker_id)
                    self._samples.pop(worker_id, None)
                s = float(st_ms) / 1e3
                if s <= _MAX_SAMPLE_S:
                    self._bucket(worker_id).append(s)
            if open_span:
                self._open_spans[worker_id] = (
                    str(open_span),
                    float(open_span_elapsed_s),
                    time.monotonic(),
                )
            elif worker_id in self._open_spans:
                # the worker reported "nothing open": clear stale frames
                self._open_spans.pop(worker_id, None)

    def _ingest_goodput(self, worker_id: int, metrics: dict):
        """Pick the goodput-ledger scalars out of a metrics report
        (lock held by caller). Workers export absolute category seconds
        since their ledger started; the fleet view re-derives fractions
        so restarts (which reset a worker's ledger) stay consistent."""
        seconds: Dict[str, float] = {}
        wall = None
        for key, value in metrics.items():
            if key == _GOODPUT_WALL_KEY:
                wall = float(value)
                continue
            m = _GOODPUT_SECONDS_RE.match(key)
            if m and m.group(1) in GOODPUT_CATEGORIES:
                seconds[m.group(1)] = float(value)
        if wall is not None and wall > 0 and seconds:
            self._goodput[worker_id] = {
                "wall_s": wall, "seconds": seconds,
            }

    def _ingest_audit(self, worker_id: int, metrics: dict):
        """Pick the step-budget audit scalars out of a metrics report
        (lock held by caller): per-component observed/budget ratio plus
        the alarm latch. This is what upgrades a straggler flag from
        "worker 3 is slow" to "worker 3's dcn_sync is 2.4x its budget
        while its compute is on-price"."""
        ratio: Dict[str, float] = {}
        alarm: Dict[str, float] = {}
        for key, value in metrics.items():
            m = _AUDIT_RATIO_RE.match(key)
            if m:
                ratio[m.group(1)] = float(value)
                continue
            m = _AUDIT_ALARM_RE.match(key)
            if m:
                alarm[m.group(1)] = float(value)
        if ratio or alarm:
            self._audit[worker_id] = {"ratio": ratio, "alarm": alarm}

    def set_profile_requester(self, fn: Optional[Callable[[int], None]]):
        """``fn(worker_id)`` fires once per newly-flagged straggler —
        the master wires it to queue a ``profile`` worker command so a
        flagged worker ships jax.profiler evidence with its
        attribution (at most once per episode: recovery clears the
        flag, a relapse re-triggers)."""
        self._profile_requester = fn

    # -- goodput (fleet accounting) ------------------------------------
    def worker_goodput(self, worker_id: int) -> Optional[dict]:
        """Latest reported ledger snapshot for one worker:
        ``{"wall_s", "seconds": {category: s}, "goodput_pct"}``."""
        with self._lock:
            rec = self._goodput.get(worker_id)
        if rec is None:
            return None
        productive = rec["seconds"].get("productive_compute", 0.0)
        return {
            **rec,
            "goodput_pct": compute_goodput_pct(productive, rec["wall_s"]),
        }

    def fleet_goodput(self) -> Optional[dict]:
        """Wall-time-weighted fleet rollup — THE number ROADMAP item 1
        plans against: ``goodput_pct`` plus summed per-category
        seconds. None until any worker has reported its ledger."""
        with self._lock:
            recs = list(self._goodput.values())
        if not recs:
            return None
        wall = sum(r["wall_s"] for r in recs)
        seconds = {c: 0.0 for c in GOODPUT_CATEGORIES}
        for r in recs:
            for cat, s in r["seconds"].items():
                seconds[cat] = seconds.get(cat, 0.0) + s
        return {
            "wall_s": wall,
            "seconds": seconds,
            "goodput_pct": compute_goodput_pct(
                seconds.get("productive_compute", 0.0), wall
            ),
            "workers": len(recs),
        }

    # -- step-budget audit (fleet attribution) -------------------------
    def worker_audit(self, worker_id: int) -> Optional[dict]:
        """Latest audit snapshot for one worker:
        ``{"ratio": {component: x}, "alarm": {component: 0/1}}``."""
        with self._lock:
            rec = self._audit.get(worker_id)
        if rec is None:
            return None
        return {
            "ratio": dict(rec["ratio"]),
            "alarm": dict(rec["alarm"]),
        }

    def audit_alarms(self) -> Dict[int, List[str]]:
        """worker -> components with an active regression alarm — the
        fleet view of the auditor's CUSUM latches."""
        with self._lock:
            items = [
                (w, rec["alarm"]) for w, rec in self._audit.items()
            ]
        return {
            w: sorted(c for c, v in alarm.items() if v >= 1.0)
            for w, alarm in items
            if any(v >= 1.0 for v in alarm.values())
        }

    def audit_attribution(self, worker_id: int) -> str:
        """The per-component *why* behind a slow worker, from its last
        audit snapshot: names the components over budget (worst first,
        alarmed components always included) and contrasts with the
        on-price ones. Empty string when the worker never reported
        audit scalars — attribution then stays the bare time flag."""
        rec = self.worker_audit(worker_id)
        if rec is None:
            return ""
        lo, hi = _AUDIT_ON_PRICE_BAND
        over = sorted(
            (
                (c, r)
                for c, r in rec["ratio"].items()
                if r > hi or rec["alarm"].get(c, 0.0) >= 1.0
            ),
            key=lambda cr: -cr[1],
        )
        if not over:
            return ""
        on_price = sorted(
            c
            for c, r in rec["ratio"].items()
            if lo <= r <= hi and c not in {c for c, _ in over}
        )
        parts = [
            f"{c} is {r:.1f}x its budget"
            + (" [alarm]" if rec["alarm"].get(c, 0.0) >= 1.0 else "")
            for c, r in over
        ]
        line = ", ".join(parts)
        if on_price:
            line += f" while {', '.join(on_price)} " + (
                "are" if len(on_price) > 1 else "is"
            ) + " on-price"
        return line

    def remove_worker(self, worker_id: int):
        """A departed worker's history must not haunt the fleet median."""
        with self._lock:
            self._samples.pop(worker_id, None)
            self._explicit.discard(worker_id)
            self._last_report.pop(worker_id, None)
            self._open_spans.pop(worker_id, None)
            self._last_metrics.pop(worker_id, None)
            self._flagged.discard(worker_id)
            self._goodput.pop(worker_id, None)
            self._audit.pop(worker_id, None)

    def _bucket(self, worker_id: int) -> Deque[float]:
        b = self._samples.get(worker_id)
        if b is None:
            b = self._samples[worker_id] = deque(maxlen=self._window)
        return b

    # -- queries -------------------------------------------------------
    def worker_p50(self, worker_id: int) -> Optional[float]:
        with self._lock:
            samples = list(self._samples.get(worker_id, ()))
        if len(samples) < self._min_samples:
            return None
        return float(statistics.median(samples))

    def worker_step_times(self, worker_id: int) -> List[float]:
        with self._lock:
            return list(self._samples.get(worker_id, ()))

    def fleet_median(self) -> Optional[float]:
        """Median of the per-worker p50s (each worker one vote — a
        straggler's own slow samples must not drag the baseline up the
        way a pooled median would on small fleets)."""
        p50s = [
            p
            for p in (
                self.worker_p50(w) for w in self.workers()
            )
            if p is not None
        ]
        if not p50s:
            return None
        return float(statistics.median(p50s))

    def workers(self) -> List[int]:
        with self._lock:
            return sorted(self._samples.keys())

    # -- straggler detection -------------------------------------------
    def detect_stragglers(self) -> List[int]:
        """Workers whose p50 step time exceeds ``straggler_ratio`` × the
        fleet median p50. Newly flagged workers are reported to the
        Brain once per flagging episode (recovery clears the flag, so a
        relapse reports again). During a maintenance window (resize /
        eviction drain) the pass is a no-op: a deliberate fleet pause
        must not mint straggler verdicts or auto-profile commands."""
        if self.in_maintenance():
            return self.stragglers
        med = self.fleet_median()
        flagged: List[int] = []
        details: Dict[int, float] = {}
        if med is not None and med > 0 and len(self.workers()) >= 2:
            for w in self.workers():
                p50 = self.worker_p50(w)
                if p50 is not None and p50 > self.straggler_ratio * med:
                    flagged.append(w)
                    details[w] = p50
        with self._lock:
            new = [w for w in flagged if w not in self._flagged]
            self._flagged = set(flagged)
        for w in new:
            # the audit upgrade: when the worker ships step-budget
            # scalars the flag carries the component-level *why*
            why = self.audit_attribution(w)
            logger.warning(
                f"straggler: worker {w} p50 step time "
                f"{details[w] * 1e3:.0f} ms > {self.straggler_ratio}x "
                f"fleet median {med * 1e3:.0f} ms"
                + (f" — {why}" if why else "")
            )
            if self._brain_reporter is not None:
                try:
                    try:
                        self._brain_reporter(w, details[w], med, why)
                    except TypeError:
                        # pre-audit reporter contract (3-arg sinks)
                        self._brain_reporter(w, details[w], med)
                except Exception as e:
                    logger.warning(
                        f"straggler brain report failed: {e!r}"
                    )
            if self._profile_requester is not None:
                # once per episode (only NEW flags reach here): the
                # flagged worker ships profiler evidence with its
                # attribution
                try:
                    self._profile_requester(w)
                except Exception as e:
                    logger.warning(
                        f"straggler profile request failed: {e!r}"
                    )
        return sorted(flagged)

    @property
    def stragglers(self) -> List[int]:
        """Last detection pass's verdict (the auto-scaler's read side —
        call ``detect_stragglers`` to recompute)."""
        with self._lock:
            return sorted(self._flagged)

    # -- hang attribution ----------------------------------------------
    def last_open_span(
        self, worker_id: int
    ) -> Optional[Tuple[str, float]]:
        """(span name, elapsed_s advanced to NOW) of the worker's last
        reported open span."""
        with self._lock:
            rec = self._open_spans.get(worker_id)
        if rec is None:
            return None
        name, elapsed, received = rec
        return name, elapsed + (time.monotonic() - received)

    def hang_attribution(self) -> Dict[int, str]:
        """Per-worker one-liners for the hang report."""
        out: Dict[int, str] = {}
        with self._lock:
            workers = set(self._last_report) | set(self._open_spans)
        for w in sorted(workers):
            span = self.last_open_span(w)
            if span is not None:
                out[w] = f"stuck in {span[0]} for {span[1]:.0f}s"
            else:
                out[w] = "no open span reported"
        return out

    def describe_hang(self) -> str:
        """The enrichment line for 'job hanged' logs: every worker's
        last open span, stragglers called out."""
        attribution = self.hang_attribution()
        if not attribution:
            return "no per-worker telemetry"
        parts = [
            f"worker {w} {desc}" for w, desc in attribution.items()
        ]
        if self.stragglers:
            parts.append(f"stragglers={self.stragglers}")
        return "; ".join(parts)

    # -- registry export ------------------------------------------------
    def export(self, registry) -> None:
        """Per-worker p50s + fleet median into a MetricsRegistry (the
        master's Prometheus surface)."""
        g = registry.gauge(
            "dlrover_worker_step_time_p50_seconds",
            "per-worker median step time",
            labelnames=("worker",),
        )
        live = set()
        for w in self.workers():
            p50 = self.worker_p50(w)
            if p50 is not None:
                g.labels(str(w)).set(p50)
                live.add((str(w),))
        # prune departed workers' label children: a scaled-away worker
        # must not keep exposing its last p50 as a frozen ghost series
        with g._lock:
            for key in [k for k in g._children if k not in live]:
                del g._children[key]
        med = self.fleet_median()
        if med is not None:
            registry.gauge(
                "dlrover_fleet_step_time_median_seconds",
                "median of per-worker p50 step times",
            ).set(med)
        registry.gauge(
            "dlrover_straggler_count", "currently flagged stragglers"
        ).set(len(self.stragglers))
        # fleet view of the step-budget auditor's regression latches:
        # how many workers currently hold at least one component alarm
        registry.gauge(
            "dlrover_audit_alarm_workers",
            "workers with an active step-budget regression alarm",
        ).set(float(len(self.audit_alarms())))
        # fleet goodput accounting (the Brain objective + dashboards)
        fleet = self.fleet_goodput()
        gw = registry.gauge(
            "dlrover_goodput_worker_pct",
            "per-worker productive share of wall time, percent",
            labelnames=("worker",),
        )
        live_g = set()
        with self._lock:
            goodput_workers = sorted(self._goodput)
        for w in goodput_workers:
            rec = self.worker_goodput(w)
            if rec is not None:
                gw.labels(str(w)).set(rec["goodput_pct"])
                live_g.add((str(w),))
        with gw._lock:
            for key in [k for k in gw._children if k not in live_g]:
                del gw._children[key]
        if fleet is not None:
            registry.gauge(
                "dlrover_goodput_fleet_pct",
                "fleet productive share of wall time, percent",
            ).set(fleet["goodput_pct"])
            gc = registry.gauge(
                "dlrover_goodput_fleet_seconds_total",
                "fleet wall seconds attributed per goodput category",
                labelnames=("category",),
            )
            for cat, secs in fleet["seconds"].items():
                gc.labels(cat).set(secs)
