"""Step-budget reconciliation: priced-vs-observed, per component.

The repo prices every step component (``dry_runner`` rooflines,
``comm_time_per_device_s`` sync legs, ``aggregate_host_exposed_s`` host
exposure) and traces every step (the PR-4 span spine) — but until this
module the two planes never met: a slow step was "slow", not "dcn_sync
is 2.4× its budget while compute is on-price". This module closes the
loop:

- :class:`StepBudget` — the pricing side's per-component *predicted*
  seconds for one train step (``compute`` / ``ici_sync`` / ``dcn_sync``
  / ``host_xfer`` / ``data_wait``), assembled from whatever pricing
  source is available (dry-run roofline, grad-sync leg pricing, the
  transfer arbiter) or — for components the plan does not price, like
  ``data_wait`` — seeded from a warmup observation window.
- :class:`StepAuditor` — harvests the matching *observed* seconds from
  the span tracer each step (incremental ``drain`` cursor, same
  contract as ``GoodputLedger``), computes signed per-component
  residuals, and feeds them to two consumers:

  1. a per-component EWMA **drift estimator** (:class:`ComponentDrift`)
     that replaces the single scalar ``calib`` the dry-runner used to
     collapse all mispricing into — rebalance/Brain plans are repriced
     by the component that actually drifted, and the factors persist
     beside the observed rail-rate cache (``auditcal-<fp>.json``);
  2. a CUSUM-style **regression detector** (:class:`CusumDetector`)
     whose sustained alarms *name* the offending component, trigger a
     flight-recorder bundle, and ride the runtime-metrics file → agent
     → master ``TelemetryAggregator`` → Brain.

Drift vs regression — the decision rule (docs/observability.md):
an observation within ``DRIFT_GATE``× of the drift-corrected budget is
treated as price drift and folded into the component's EWMA (no alarm);
an observation beyond the gate is withheld from the EWMA and feeds the
CUSUM on the drift-corrected normalized residual instead — sustained
excess raises the alarm. Mispricing heals silently; regressions alarm.

Observed-side mapping: components with per-step spans (``data_wait``,
``compute``, ``host_sync``) are clipped to each ``step`` window exactly
like the goodput ledger clips categories. The sync legs run *inside*
the jitted ``compute`` span and have no per-step spans — the trainer
installs the standalone probe's measured leg times via
:meth:`StepAuditor.set_measured`, and the auditor deducts that share
from observed compute so the partition stays disjoint. ``OBSERVED`` is
the component→span-name registry graftlint's ``audit-budget-coverage``
pass checks against ``StepBudget``'s fields: a newly priced component
cannot silently go unmeasured.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.obs.trace import SpanTracer, get_tracer

# the priced/audited components, in export order. StepBudget carries
# one ``<component>_s`` field per entry; OBSERVED maps each to the span
# names that realize it (graftlint: audit-budget-coverage keeps the
# three views aligned).
COMPONENTS = ("compute", "ici_sync", "dcn_sync", "host_xfer", "data_wait")

# component -> span names whose step-window-clipped time observes it.
# ici/dcn sync: the per-step sync runs inside the jitted ``compute``
# span; these names only appear around the standalone measure probes,
# so per-step observation comes from ``set_measured`` and the listed
# spans matter when a probe lands inside a step window (rare) and for
# the coverage lint.
OBSERVED: Dict[str, Tuple[str, ...]] = {
    "compute": ("compute",),
    "ici_sync": ("grad_sync_ici",),
    "dcn_sync": ("grad_sync_dcn",),
    "host_xfer": ("host_sync",),
    "data_wait": ("data_wait",),
}

# EWMA weight for drift folding (matches the observed rail-rate cache's
# convergence character: ~4 samples to mostly adopt a new price)
DRIFT_EWMA_WEIGHT = 0.25

# an observation within this factor (either side) of the drift-
# corrected budget is price drift — folded, never alarmed. Beyond it,
# the EWMA is left alone and the CUSUM sees the full residual.
DRIFT_GATE = 2.0

# two-sided CUSUM parameters on the normalized residual
# r = (obs - pred*drift) / denom: per-step slack K is forgiven, the
# accumulated excess must cross H to alarm. With these values a
# sustained 2.5x regression alarms in ~3 steps; a 1.6x mispricing
# decays through the EWMA without ever crossing H.
CUSUM_K = 0.25
CUSUM_H = 3.0

# components where both prediction and observation sit under this are
# noise (an unpriced, unexercised leg) — skipped entirely
MIN_COMPONENT_S = 1e-3
# floor of the residual-normalization denominator, as a fraction of the
# whole step budget: gives unpriced components (data_wait's budget is
# legitimately ~0) a meaningful scale instead of an infinite ratio
DENOM_FLOOR_FRACTION = 0.05

# observed-seeded budgets average this many audited steps
WARMUP_STEPS = 5

# drift-cache persistence cadence (same best-effort durability contract
# as railrates-<fp>.json)
PERSIST_MIN_INTERVAL_S = 30.0

METRIC_PREFIX = "dlrover_audit_"


# ---------------------------------------------------------------------------
# budget


@dataclass
class StepBudget:
    """Predicted seconds per component for one train step. ``source``
    records provenance per component (``priced`` / ``measured`` /
    ``observed``) so an alarm report can say what the budget was
    anchored to."""

    compute_s: float = 0.0
    ici_sync_s: float = 0.0
    dcn_sync_s: float = 0.0
    host_xfer_s: float = 0.0
    data_wait_s: float = 0.0
    source: Dict[str, str] = field(default_factory=dict)

    def component(self, name: str) -> float:
        return float(getattr(self, name + "_s"))

    def set_component(self, name: str, seconds: float, source: str = ""):
        setattr(self, name + "_s", float(max(0.0, seconds)))
        if source:
            self.source[name] = source

    def total_s(self) -> float:
        return sum(self.component(c) for c in COMPONENTS)

    def as_dict(self) -> dict:
        d = {c + "_s": round(self.component(c), 6) for c in COMPONENTS}
        d["source"] = dict(self.source)
        return d


# ---------------------------------------------------------------------------
# drift estimator + persistence


@dataclass
class ComponentDrift:
    """Multiplicative price-drift EWMA for one component: the factor
    the priced seconds must be scaled by to match observation.
    ``seed()`` installs a first estimate from a single measurement (the
    dry-runner's one timed row) without EWMA damping, so the very first
    resize is already repriced."""

    factor: float = 1.0
    samples: int = 0

    def seed(self, ratio: float):
        if not ratio > 0.0:
            return
        if self.samples == 0:
            self.factor = float(ratio)
            self.samples = 1

    def fold(self, ratio: float, weight: float = DRIFT_EWMA_WEIGHT):
        if not ratio > 0.0:
            return
        if self.samples == 0:
            self.factor = float(ratio)
        else:
            self.factor = (1.0 - weight) * self.factor + weight * float(
                ratio
            )
        self.samples += 1


@dataclass
class AuditCalibration:
    """Persisted per-component drift snapshot, fingerprint-keyed like
    the probed LinkModel / observed rail-rate caches: a restart (or the
    next dry-run pricing pass) starts from the prices the last
    incarnation converged to, not from raw rooflines."""

    fingerprint: str = ""
    factors: Dict[str, float] = field(default_factory=dict)
    samples: Dict[str, int] = field(default_factory=dict)
    updated_at: float = 0.0

    def to_payload(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "factors": {k: float(v) for k, v in self.factors.items()},
            "samples": {k: int(v) for k, v in self.samples.items()},
            "updated_at": float(self.updated_at),
        }

    @staticmethod
    def from_payload(d: dict) -> "AuditCalibration":
        return AuditCalibration(
            fingerprint=str(d["fingerprint"]),
            factors={
                str(k): float(v) for k, v in dict(d["factors"]).items()
            },
            samples={
                str(k): int(v)
                for k, v in dict(d.get("samples", {})).items()
            },
            updated_at=float(d.get("updated_at", 0.0)),
        )


def audit_cal_path(
    fingerprint: str, dir_override: Optional[str] = None
) -> str:
    from dlrover_tpu.parallel.topology import cache_dir

    import os

    return os.path.join(
        cache_dir(dir_override), f"auditcal-{fingerprint}.json"
    )


def load_audit_calibration(
    fingerprint: Optional[str] = None,
    dir_override: Optional[str] = None,
) -> Optional[AuditCalibration]:
    import json

    if fingerprint is None:
        try:
            from dlrover_tpu.parallel.topology import device_fingerprint

            fingerprint = device_fingerprint()
        except Exception:  # no backend yet (early import paths)
            return None
    try:
        with open(audit_cal_path(fingerprint, dir_override)) as f:
            cal = AuditCalibration.from_payload(json.load(f))
    except (OSError, ValueError, TypeError, KeyError):
        return None
    if cal.fingerprint != fingerprint:
        return None  # stale file copied across worlds
    return cal


def save_audit_calibration(
    cal: AuditCalibration, dir_override: Optional[str] = None
) -> Optional[str]:
    """Durable best-effort persist (fsync-before-rename); a read-only
    cache dir degrades to process-local drift, never to a failure."""
    path = audit_cal_path(cal.fingerprint, dir_override)
    try:
        from dlrover_tpu.agent.monitor import atomic_write_json

        atomic_write_json(path, cal.to_payload(), durable=True)
        return path
    except OSError as e:
        logger.warning(f"audit calibration cache write failed: {e!r}")
        return None


# ---------------------------------------------------------------------------
# regression detector


class CusumDetector:
    """Two-sided CUSUM on the drift-corrected normalized residual.
    Only the positive (slower-than-budget) side raises the regression
    alarm — a component running persistently *faster* than its
    corrected price is mispricing, which the drift EWMA owns. The
    negative accumulator is still tracked so ``state()`` can report
    how far off-price the fast side is."""

    def __init__(self, k: float = CUSUM_K, h: float = CUSUM_H):
        self.k = float(k)
        self.h = float(h)
        self.pos = 0.0
        self.neg = 0.0

    def update(self, r: float) -> bool:
        """Fold one residual; True when the slow-side alarm fires
        (the accumulator resets so a persisting regression re-alarms
        only after re-accumulating — a built-in refire hysteresis)."""
        self.pos = max(0.0, self.pos + r - self.k)
        self.neg = max(0.0, self.neg - r - self.k)
        if self.pos > self.h:
            self.pos = 0.0
            return True
        return False

    def reset(self):
        self.pos = 0.0
        self.neg = 0.0

    def state(self) -> Tuple[float, float]:
        return self.pos, self.neg


# ---------------------------------------------------------------------------
# the auditor


@dataclass
class AuditStepResult:
    """One audited step: observed/predicted/residual seconds per
    component plus any alarms raised."""

    step_index: int = 0
    observed: Dict[str, float] = field(default_factory=dict)
    predicted: Dict[str, float] = field(default_factory=dict)
    residual: Dict[str, float] = field(default_factory=dict)
    ratio: Dict[str, float] = field(default_factory=dict)
    alarms: List[str] = field(default_factory=list)


class StepAuditor:
    """Incremental priced-vs-observed reconciler over a ``SpanTracer``.

    ``collect()`` is meant for log cadence (it drains only records
    appended since the previous call, grouping completed ``step`` spans
    on the train thread and window-clipping their children into
    component buckets). Thread-safe.

    ``on_alarm(component, ratio, detail)`` fires on each regression
    alarm — the trainer hangs a flight-recorder dump off it.
    """

    def __init__(
        self,
        tracer: Optional[SpanTracer] = None,
        tid_fn: Optional[Callable[[], Optional[int]]] = None,
        budget: Optional[StepBudget] = None,
        on_alarm: Optional[Callable[[str, float, str], None]] = None,
        drift_weight: float = DRIFT_EWMA_WEIGHT,
        cusum_k: float = CUSUM_K,
        cusum_h: float = CUSUM_H,
    ):
        # `is None`, not truthiness — SpanTracer defines __len__ (the
        # footgun SpanHeartbeat/GoodputLedger both document)
        self._tracer = tracer if tracer is not None else get_tracer()
        self._tid_fn = tid_fn
        self._on_alarm = on_alarm
        self._drift_weight = float(drift_weight)
        self._lock = threading.Lock()
        self._cursor = 0
        self._dropped = 0
        # completed records not yet claimed by a completed ``step``
        # span (children of an in-flight step drain before their parent
        # does; they are held here until the step record arrives)
        self._held: List[tuple] = []
        self._held_cap = 8192
        self._budget = budget if budget is not None else StepBudget()
        self.drift: Dict[str, ComponentDrift] = {
            c: ComponentDrift() for c in COMPONENTS
        }
        self._cusum: Dict[str, CusumDetector] = {
            c: CusumDetector(cusum_k, cusum_h) for c in COMPONENTS
        }
        # probe-measured per-step seconds for span-less components
        # (the sync legs); deducted from observed compute
        self._measured: Dict[str, float] = {}
        self._steps_audited = 0
        self._last: Optional[AuditStepResult] = None
        self._alarm_active: Dict[str, bool] = {c: False for c in COMPONENTS}
        self._alarm_clear: Dict[str, int] = {c: 0 for c in COMPONENTS}
        self._alarms_total: Dict[str, int] = {c: 0 for c in COMPONENTS}
        # warmup accumulation for observed-seeded budgets
        self._warmup_sum: Dict[str, float] = {c: 0.0 for c in COMPONENTS}
        self._warmup_n = 0
        self._persisted_samples = -1
        self._persisted_ts = 0.0

    # -- configuration -------------------------------------------------
    def set_budget(self, budget: StepBudget, reset_detectors: bool = True):
        """Install a new budget (setup / after resize). Detectors reset
        by default: the old accumulation was against the old prices."""
        with self._lock:
            self._budget = budget
            if reset_detectors:
                for det in self._cusum.values():
                    det.reset()
                self._alarm_active = {c: False for c in COMPONENTS}
                self._alarm_clear = {c: 0 for c in COMPONENTS}
            self._warmup_sum = {c: 0.0 for c in COMPONENTS}
            self._warmup_n = 0

    def budget(self) -> StepBudget:
        with self._lock:
            return replace(
                self._budget, source=dict(self._budget.source)
            )

    def set_measured(self, component: str, seconds: float):
        """Install a probe-measured per-step observation for a
        component without per-step spans (``ici_sync``/``dcn_sync``
        from ``measure_sync_legs_ms``)."""
        if component not in COMPONENTS:
            raise ValueError(f"unknown component {component!r}")
        with self._lock:
            self._measured[component] = float(max(0.0, seconds))

    def skip_to_now(self):
        """Drop every already-recorded span from audit consideration
        (called across a resize: spans from the old incarnation must
        not be reconciled against the new budget)."""
        with self._lock:
            _records, self._cursor, dropped = self._tracer.drain(
                self._cursor
            )
            self._dropped += dropped
            self._held = []

    # -- drift calibration seams --------------------------------------
    def drift_factors(self) -> Dict[str, float]:
        with self._lock:
            return {c: self.drift[c].factor for c in COMPONENTS}

    def seed_drift(self, component: str, ratio: float):
        """Seed one component's drift from a single out-of-band
        measurement (the dry-runner's timed row); a no-op once real
        observations exist."""
        if component not in COMPONENTS:
            raise ValueError(f"unknown component {component!r}")
        with self._lock:
            self.drift[component].seed(ratio)

    def apply_calibration(self, cal: AuditCalibration):
        """Adopt a persisted drift snapshot — only for components this
        process has not observed yet (live EWMAs outrank disk)."""
        with self._lock:
            for c in COMPONENTS:
                f = cal.factors.get(c)
                if f is not None and self.drift[c].samples == 0:
                    self.drift[c].factor = float(f)
                    self.drift[c].samples = int(
                        cal.samples.get(c, 1)
                    ) or 1

    def calibration(self, fingerprint: str = "") -> AuditCalibration:
        with self._lock:
            return AuditCalibration(
                fingerprint=fingerprint,
                factors={
                    c: self.drift[c].factor
                    for c in COMPONENTS
                    if self.drift[c].samples > 0
                },
                samples={
                    c: self.drift[c].samples
                    for c in COMPONENTS
                    if self.drift[c].samples > 0
                },
                updated_at=time.time(),
            )

    def persist(
        self,
        fingerprint: Optional[str] = None,
        dir_override: Optional[str] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Rate-limited best-effort persist of the drift snapshot
        beside ``railrates-<fp>.json`` (only when new samples arrived
        since the last write)."""
        if fingerprint is None:
            try:
                from dlrover_tpu.parallel.topology import (
                    device_fingerprint,
                )

                fingerprint = device_fingerprint()
            except Exception:
                return None
        with self._lock:
            total = sum(d.samples for d in self.drift.values())
            now = time.time()
            if not force and (
                total == self._persisted_samples
                or now - self._persisted_ts < PERSIST_MIN_INTERVAL_S
            ):
                return None
            self._persisted_samples = total
            self._persisted_ts = now
        return save_audit_calibration(
            self.calibration(fingerprint), dir_override
        )

    # -- introspection -------------------------------------------------
    @property
    def steps_audited(self) -> int:
        with self._lock:
            return self._steps_audited

    @property
    def dropped_records(self) -> int:
        with self._lock:
            return self._dropped

    def last_result(self) -> Optional[AuditStepResult]:
        with self._lock:
            return self._last

    def alarm_components(self) -> List[str]:
        with self._lock:
            return [c for c in COMPONENTS if self._alarm_active[c]]

    def alarms_total(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._alarms_total)

    # -- collection ----------------------------------------------------
    def collect(self) -> List[AuditStepResult]:
        """Drain new records, audit every newly completed ``step``
        span, return the per-step results (empty when no step
        finished since the last call)."""
        alarm_cbs: List[Tuple[str, float, str]] = []
        with self._lock:
            records, self._cursor, dropped = self._tracer.drain(
                self._cursor
            )
            self._dropped += dropped
            tid = self._tid_fn() if self._tid_fn is not None else None
            held = self._held
            held.extend(records)
            results: List[AuditStepResult] = []
            last_step_seq = -1
            for rec in held:
                name, rtid, start, dur, depth, _attrs, seq = rec
                if name != "step" or (tid is not None and rtid != tid):
                    continue
                obs = self._observe_window(
                    held, rtid, start, start + dur, depth
                )
                res = self._audit_step(obs, alarm_cbs)
                results.append(res)
                last_step_seq = seq
            if last_step_seq >= 0:
                # children of completed steps are claimed; anything
                # newer may belong to an in-flight step — hold it
                held[:] = [r for r in held if r[6] > last_step_seq]
            if len(held) > self._held_cap:
                # bound memory when no step spans flow (a non-trainer
                # process sharing the tracer): keep the fresh tail
                del held[: len(held) - self._held_cap]
            if results:
                self._last = results[-1]
        for component, ratio, detail in alarm_cbs:
            # callbacks run outside the lock: a flight dump inside it
            # could deadlock against another thread's collect/export
            if self._on_alarm is not None:
                try:
                    self._on_alarm(component, ratio, detail)
                except Exception:
                    pass  # forensics must never hurt training
        return results

    def _observe_window(
        self,
        held: List[tuple],
        tid: int,
        lo: int,
        hi: int,
        parent_depth: int,
    ) -> Dict[str, float]:
        """Component seconds observed inside one step window: direct
        children (same tid, depth parent+1) clipped to the window and
        overlap-merged per component — a span straddling the window
        edge (e.g. across a mesh rebuild) contributes only its inside
        portion, never double-counts into a neighbor step."""
        span_comp: Dict[str, str] = {}
        for comp, names in OBSERVED.items():
            for n in names:
                span_comp[n] = comp
        per: Dict[str, List[Tuple[int, int]]] = {}
        for name, rtid, start, dur, depth, _attrs, _seq in held:
            comp = span_comp.get(name)
            if comp is None or rtid != tid:
                continue
            if depth != parent_depth + 1:
                continue
            a, b = max(start, lo), min(start + dur, hi)
            if b > a:
                per.setdefault(comp, []).append((a, b))
        obs: Dict[str, float] = {}
        for comp, ivs in per.items():
            total = 0.0
            end = float("-inf")
            for s, e in sorted(ivs):
                if e <= end:
                    continue
                total += e - max(s, end)
                end = e
            obs[comp] = total / 1e9
        return obs

    def _audit_step(
        self,
        obs: Dict[str, float],
        alarm_cbs: List[Tuple[str, float, str]],
    ) -> AuditStepResult:
        """Reconcile one step's observation against the budget (caller
        holds the lock)."""
        # sync legs observe via the standalone probe unless probe spans
        # landed inside this very window; the measured share is then
        # deducted from the compute span it runs inside of
        deduct = 0.0
        for leg in ("ici_sync", "dcn_sync"):
            if leg not in obs and leg in self._measured:
                obs[leg] = self._measured[leg]
                deduct += self._measured[leg]
        if deduct and "compute" in obs:
            obs["compute"] = max(0.0, obs["compute"] - deduct)

        self._steps_audited += 1
        res = AuditStepResult(step_index=self._steps_audited)
        budget = self._budget
        self._warmup_n += 1
        # the first steps after a (re)budget are the baseline window:
        # observed-seeded components have no budget yet and priced ones
        # are still settling post-compile — drift may fold, but the
        # regression detector stays quiet until the baseline exists
        in_warmup = self._warmup_n <= WARMUP_STEPS
        corr_total = sum(
            budget.component(c) * self.drift[c].factor
            for c in COMPONENTS
        )
        denom_floor = max(
            MIN_COMPONENT_S, DENOM_FLOOR_FRACTION * corr_total
        )
        for c in COMPONENTS:
            o = float(obs.get(c, 0.0))
            pred = budget.component(c)
            self._warmup_sum[c] += o
            # observed-seeded budget: a component the plan did not
            # price adopts its warmup-window mean as the budget — the
            # baseline later regressions are judged against
            if pred <= 0.0 and self._warmup_n == WARMUP_STEPS:
                mean = self._warmup_sum[c] / WARMUP_STEPS
                if mean >= MIN_COMPONENT_S:
                    budget.set_component(c, mean, source="observed")
            dr = self.drift[c]
            pred_corr = pred * dr.factor
            res.observed[c] = o
            res.predicted[c] = pred_corr
            res.residual[c] = o - pred_corr
            if max(o, pred_corr) < MIN_COMPONENT_S:
                res.ratio[c] = 1.0
                continue  # unexercised leg: noise, not evidence
            denom = max(pred_corr, denom_floor)
            res.ratio[c] = o / denom if denom > 0 else 0.0
            ratio_corr = o / pred_corr if pred_corr > 0 else float(
                "inf"
            )
            if (
                pred >= MIN_COMPONENT_S
                and 1.0 / DRIFT_GATE <= ratio_corr <= DRIFT_GATE
            ):
                # plausibly mispriced, not broken: heal the price
                dr.fold(o / pred, weight=self._drift_weight)
                pred_corr = pred * dr.factor
                denom = max(pred_corr, denom_floor)
            if in_warmup:
                continue
            r = (o - pred_corr) / denom
            fired = self._cusum[c].update(r)
            if fired:
                self._alarms_total[c] += 1
                self._alarm_clear[c] = 0
                ratio = o / pred_corr if pred_corr > 0 else res.ratio[c]
                detail = (
                    f"{c} observed {o * 1e3:.1f}ms vs budget "
                    f"{pred_corr * 1e3:.1f}ms ({ratio:.2f}x, "
                    f"source={budget.source.get(c, 'priced')})"
                )
                res.alarms.append(c)
                if not self._alarm_active[c]:
                    self._alarm_active[c] = True
                    alarm_cbs.append((c, ratio, detail))
                logger.warning(f"audit regression alarm: {detail}")
            elif self._alarm_active[c]:
                if r <= self._cusum[c].k:
                    self._alarm_clear[c] += 1
                    if self._alarm_clear[c] >= 3:
                        self._alarm_active[c] = False
                        self._alarm_clear[c] = 0
                else:
                    self._alarm_clear[c] = 0
        return res

    # -- export --------------------------------------------------------
    def export(self, registry) -> Optional[AuditStepResult]:
        """Collect + publish the ``dlrover_audit_*`` series. The
        trainer calls this at log cadence so the scalars ride the
        runtime-metrics file to the master like every other registry
        number."""
        results = self.collect()
        with self._lock:
            last = self._last
            if last is None:
                return None
            g_res = registry.gauge(
                METRIC_PREFIX + "residual_seconds",
                "last-step observed minus drift-corrected budget, "
                "seconds (signed)",
                labelnames=("component",),
            )
            g_obs = registry.gauge(
                METRIC_PREFIX + "observed_seconds",
                "last-step observed seconds per audited component",
                labelnames=("component",),
            )
            g_bud = registry.gauge(
                METRIC_PREFIX + "budget_seconds",
                "drift-corrected per-step budget seconds per component",
                labelnames=("component",),
            )
            g_drift = registry.gauge(
                METRIC_PREFIX + "drift_factor",
                "per-component price-drift EWMA factor "
                "(observed/priced)",
                labelnames=("component",),
            )
            g_ratio = registry.gauge(
                METRIC_PREFIX + "budget_ratio",
                "last-step observed over drift-corrected budget "
                "(floored denominator)",
                labelnames=("component",),
            )
            g_alarm = registry.gauge(
                METRIC_PREFIX + "alarm",
                "1 while a sustained regression alarm is active for "
                "the component",
                labelnames=("component",),
            )
            h_ratio = registry.histogram(
                METRIC_PREFIX + "step_ratio",
                "distribution of per-step observed/budget ratios "
                "across audited components",
            )
            for c in COMPONENTS:
                g_res.labels(c).set(last.residual.get(c, 0.0))
                g_obs.labels(c).set(last.observed.get(c, 0.0))
                g_bud.labels(c).set(last.predicted.get(c, 0.0))
                g_drift.labels(c).set(self.drift[c].factor)
                g_ratio.labels(c).set(last.ratio.get(c, 0.0))
                g_alarm.labels(c).set(
                    1.0 if self._alarm_active[c] else 0.0
                )
            for res in results:
                for c in COMPONENTS:
                    if res.ratio.get(c):
                        h_ratio.observe(res.ratio[c])
            registry.gauge(
                METRIC_PREFIX + "steps_total",
                "train steps reconciled by the step auditor",
            ).set(float(self._steps_audited))
            return last


# ---------------------------------------------------------------------------
# process-default auditor (the dry-runner's repricing reaches the live
# drift estimate without holding a trainer reference)

_default: Optional[StepAuditor] = None
_default_lock = threading.Lock()
# dry-run seeded factors used before any trainer installs an auditor
_seeded_factors: Dict[str, float] = {}


def install_default_auditor(auditor: StepAuditor) -> StepAuditor:
    global _default
    with _default_lock:
        _default = auditor
        for c, f in _seeded_factors.items():
            auditor.seed_drift(c, f)
    return auditor


def default_auditor() -> Optional[StepAuditor]:
    return _default


def seed_default_drift(component: str, ratio: float):
    """Dry-runner seam: record a single-measurement drift seed so the
    factor survives until (and into) the trainer's auditor."""
    if component not in COMPONENTS or not ratio > 0.0:
        return
    with _default_lock:
        aud = _default
        if aud is not None:
            aud.seed_drift(component, ratio)
        elif component not in _seeded_factors:
            _seeded_factors[component] = float(ratio)


def current_drift_factors() -> Dict[str, float]:
    """The best per-component drift estimate this process has: the
    live auditor's EWMAs, overlaid on the persisted calibration,
    overlaid on any dry-run seeds. Missing components price at 1.0."""
    factors: Dict[str, float] = {c: 1.0 for c in COMPONENTS}
    cal = load_audit_calibration()
    if cal is not None:
        factors.update(cal.factors)
    with _default_lock:
        factors.update(_seeded_factors)
        aud = _default
    if aud is not None:
        for c, d in aud.drift.items():
            if d.samples > 0:
                factors[c] = d.factor
    return factors


def reset_default_auditor():
    """Test seam: forget the installed auditor and dry-run seeds."""
    global _default
    with _default_lock:
        _default = None
        _seeded_factors.clear()
