"""Step-phase span tracer: where did each step's wall time go?

A process-wide, thread-safe tracer built for the train loop's cadence:

- **low overhead** — an enabled span costs two ``time.monotonic_ns``
  calls, one small object and one GIL-atomic deque append (no lock on
  the hot path); a disabled tracer hands back a shared no-op context
  manager. The bench gates the measured overhead (``bench.py --smoke``,
  docs/observability.md) at ≤ ``TRACER_OVERHEAD_GATE_PCT`` of step
  time.
- **bounded memory** — spans land in a ring buffer (``capacity``
  events, oldest dropped); a multi-day job can leave tracing on.
- **hang attribution** — every thread's currently-open span stack is
  observable from any other thread (``open_spans`` /
  ``last_open_span``), so a wedged step can be described as "stuck in
  ckpt_commit for 42s" instead of "no progress". ``SpanHeartbeat``
  publishes that snapshot into the runtime-metrics file the agent's
  TrainingMonitor forwards to the master — the one channel that keeps
  working while the train loop itself is stuck inside a span.
- **Chrome trace-event export** — ``chrome_trace()`` / ``dump()`` emit
  the JSON object format (``{"traceEvents": [...]}``) chrome://tracing
  and Perfetto load directly; span depth rides in ``args.depth`` so
  ``step_coverage`` can be recomputed from a dumped artifact.

Span taxonomy (docs/observability.md): the trainer emits ``step`` with
children ``data_wait`` / ``compute`` / ``host_sync`` / ``eval`` /
``ckpt_save``; the prefetcher's producer thread emits ``prefetch_pull``
/ ``h2d``; the checkpoint engine emits ``ckpt_stage`` / ``ckpt_commit``
/ ``ckpt_persist``; resize emits ``resize`` with ``resize_drain`` /
``resize_reshard`` / ``resize_compile`` (cache_hit attr); grad-sync
emits ``grad_sync_probe``.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

_TRACE_ENV = "DLROVER_TPU_TRACE"  # "0"/"false" disables at import

# record layout: (name, tid, start_ns, dur_ns, depth, attrs-or-None, seq)
# seq is a process-lifetime monotonic id (``drain`` cursors key on it)
_Record = Tuple[str, int, int, int, int, Optional[dict], int]


class _NoopSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def end(self):
        pass

    def cancel(self):
        pass

    def set(self, **attrs):
        pass


_NOOP = _NoopSpan()


class _OpenSpan:
    """A live span: ``end()`` records it, ``cancel()`` discards it.
    Also a context manager (``with tracer.span(...)``)."""

    __slots__ = (
        "_tracer", "name", "start_ns", "depth", "attrs", "_tid", "_done",
    )

    def __init__(self, tracer, name, start_ns, depth, attrs, tid):
        self._tracer = tracer
        self.name = name
        self.start_ns = start_ns
        self.depth = depth
        self.attrs = attrs
        self._tid = tid
        self._done = False

    def set(self, **attrs):
        """Attach/override attributes before the span ends (e.g. the
        resize compile leg stamping cache_hit once known)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)

    def end(self):
        self._tracer._end(self)

    def cancel(self):
        self._tracer._cancel(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class SpanTracer:
    """Ring-buffer span tracer; see module docstring."""

    def __init__(self, capacity: int = 65536, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.getenv(_TRACE_ENV, "1").lower() not in (
                "0", "false", "off",
            )
        self.enabled = bool(enabled)
        self._buf: deque = deque(maxlen=max(int(capacity), 16))
        self._appended = 0  # total ever; dropped = appended - len(buf)
        # process-lifetime record ids. Seq draw + append happen under
        # one tiny lock so buffer order == seq order — without it, a
        # thread preempted between next(seq) and append would let a
        # HIGHER seq land first, and a drain cursor advancing past it
        # would silently drop the straggler record forever (~100ns
        # acquire vs the ~µs span cost the bench gate bounds)
        self._seq = itertools.count()
        self._end_lock = threading.Lock()
        # tid -> stack of live _OpenSpan (each thread mutates only its
        # own list; snapshots copy, so no lock is needed around them)
        self._stacks: Dict[int, list] = {}
        self._thread_names: Dict[int, str] = {}
        self._t0_ns = time.monotonic_ns()
        # wall-clock anchor of the monotonic epoch: lets an offline
        # tool (tools/merge_timeline.py) align traces from different
        # processes/hosts onto one master-timestamp axis
        self._wall_t0 = time.time()
        self._pid = os.getpid()

    # -- hot path ------------------------------------------------------
    def span(self, name: str, **attrs):
        """Context manager / handle for one span. Usage::

            with tracer.span("data_wait"):
                batch = next(it)

        or manually: ``s = tracer.span("step"); ...; s.end()``."""
        if not self.enabled:
            return _NOOP
        tid = threading.get_ident()
        stack = self._stacks.get(tid)
        if stack is None:
            stack = self._stacks[tid] = []
            self._thread_names[tid] = threading.current_thread().name
        sp = _OpenSpan(
            self, name, time.monotonic_ns(), len(stack),
            attrs or None, tid,
        )
        stack.append(sp)
        return sp

    def _end(self, sp: _OpenSpan):
        if sp._done:
            return  # idempotent: a double end must not duplicate records
        sp._done = True
        dur_ns = time.monotonic_ns() - sp.start_ns
        stack = self._stacks.get(sp._tid)
        if stack and sp in stack:
            # tolerate out-of-order ends (an inner span leaked open):
            # drop everything above sp — their records are lost, which
            # is the observable symptom of the caller's bug
            while stack and stack.pop() is not sp:
                pass
        with self._end_lock:
            self._buf.append(
                (
                    sp.name, sp._tid, sp.start_ns, dur_ns, sp.depth,
                    sp.attrs, next(self._seq),
                )
            )
            self._appended += 1

    def _cancel(self, sp: _OpenSpan):
        if sp._done:
            return
        sp._done = True
        stack = self._stacks.get(sp._tid)
        if stack and sp in stack:
            while stack and stack.pop() is not sp:
                pass

    def traced(self, name: Optional[str] = None) -> Callable:
        """Decorator form: ``@tracer.traced("load_config")``."""

        def wrap(fn):
            import functools

            label = name or fn.__name__

            @functools.wraps(fn)
            def inner(*args, **kwargs):
                with self.span(label):
                    return fn(*args, **kwargs)

            return inner

        return wrap

    # -- introspection -------------------------------------------------
    def __len__(self) -> int:
        return len(self._buf)

    @property
    def dropped(self) -> int:
        return self._appended - len(self._buf)

    def reset(self):
        """Drop recorded spans (open stacks stay live — their ends land
        in the fresh buffer)."""
        self._buf.clear()
        self._appended = 0

    def drain(self, cursor: int = 0) -> Tuple[List[_Record], int, int]:
        """``(records, new_cursor, dropped)`` — every completed span
        with ``seq >= cursor`` still in the ring, in append order.

        The incremental-consumer API (GoodputLedger): each record is
        delivered exactly once per cursor chain, concurrent appends are
        safe (records are immutable tuples, ``list(deque)`` snapshots
        under the GIL), and a consumer lapped by the hot path learns
        how many records it lost (``dropped``) instead of silently
        double-counting or tearing."""
        snap = list(self._buf)
        fresh = [r for r in snap if r[6] >= cursor]
        if not fresh:
            return [], cursor, 0
        dropped = max(0, fresh[0][6] - cursor) if cursor else 0
        return fresh, fresh[-1][6] + 1, dropped

    def open_span_records(
        self, tid: Optional[int] = None
    ) -> List[Tuple[str, int, int, int]]:
        """``(name, tid, start_ns, depth)`` of every live span —
        the raw-timestamp twin of :meth:`open_spans` (the ledger
        attributes the elapsed part of still-open spans from this)."""
        out = []
        for t, stack in list(self._stacks.items()):
            if tid is not None and t != tid:
                continue
            for sp in list(stack):
                out.append((sp.name, t, sp.start_ns, sp.depth))
        return out

    def open_spans(self, tid: Optional[int] = None) -> List[dict]:
        """Snapshot of every live span, outermost first per thread."""
        now = time.monotonic_ns()
        out = []
        for t, stack in list(self._stacks.items()):
            if tid is not None and t != tid:
                continue
            for sp in list(stack):
                out.append(
                    {
                        "name": sp.name,
                        "tid": t,
                        "thread": self._thread_names.get(t, ""),
                        "elapsed_s": (now - sp.start_ns) / 1e9,
                        "depth": sp.depth,
                    }
                )
        return out

    def last_open_span(
        self, tid: Optional[int] = None
    ) -> Optional[Tuple[str, float]]:
        """(name, elapsed_s) of the most specific stuck frame: the
        INNERMOST open span of the thread whose innermost span has been
        open longest (restricted to ``tid`` when given). None when
        nothing is open. This is the string a hang report attaches:
        'worker 3 stuck in ckpt_commit for 42s'."""
        now = time.monotonic_ns()
        best: Optional[Tuple[str, float]] = None
        for t, stack in list(self._stacks.items()):
            if tid is not None and t != tid:
                continue
            frames = list(stack)
            if not frames:
                continue
            inner = frames[-1]
            elapsed = (now - inner.start_ns) / 1e9
            if best is None or elapsed > best[1]:
                best = (inner.name, elapsed)
        return best

    # -- export --------------------------------------------------------
    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (Perfetto/chrome://tracing).
        ``ts``/``dur`` are microseconds from the tracer's epoch; span
        depth is exported under ``args.depth`` so coverage can be
        recomputed from the artifact alone."""
        events: List[dict] = []
        for tid, tname in list(self._thread_names.items()):
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self._pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        for name, tid, start_ns, dur_ns, depth, attrs, _seq in list(
            self._buf
        ):
            args: Dict[str, Any] = {"depth": depth}
            if attrs:
                args.update(attrs)
            events.append(
                {
                    "name": name,
                    "ph": "X",
                    "pid": self._pid,
                    "tid": tid,
                    "ts": (start_ns - self._t0_ns) / 1e3,
                    "dur": dur_ns / 1e3,
                    "args": args,
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            # extra top-level keys are legal in the JSON object format;
            # merge_timeline.py uses wall_t0_s for cross-worker clock
            # alignment (ts 0 of this trace == this wall-clock second)
            "otherData": {"wall_t0_s": self._wall_t0, "pid": self._pid},
        }

    def dump(self, path: str) -> str:
        """Atomically write the Chrome-trace JSON to ``path``."""
        from dlrover_tpu.agent.monitor import atomic_write_json

        atomic_write_json(path, self.chrome_trace())
        return path


# -- artifact validation / analysis ----------------------------------------


def validate_chrome_trace(obj: Any) -> Tuple[bool, str]:
    """(ok, reason) for a loaded trace artifact: the JSON object format
    with a non-empty ``traceEvents`` list of well-formed events."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return False, "not a Chrome trace JSON object (no traceEvents)"
    events = obj["traceEvents"]
    if not isinstance(events, list) or not events:
        return False, "traceEvents empty or not a list"
    for e in events:
        if not isinstance(e, dict) or "ph" not in e or "name" not in e:
            return False, f"malformed event: {e!r}"
        if e["ph"] == "X" and ("ts" not in e or "dur" not in e):
            return False, f"complete event without ts/dur: {e!r}"
    if not any(e.get("ph") == "X" for e in events):
        return False, "no complete (ph=X) span events"
    return True, "ok"


def _merged_total(intervals: List[Tuple[float, float]]) -> float:
    total = 0.0
    end = float("-inf")
    for lo, hi in sorted(intervals):
        if hi <= end:
            continue
        total += hi - max(lo, end)
        end = hi
    return total


def step_coverage(trace: Any, parent: str = "step") -> Optional[float]:
    """Fraction of ``parent`` span wall time covered by its direct
    children (same tid, depth parent+1, overlap-merged) — the
    "spans explain the step" acceptance number. Accepts a tracer, a
    Chrome-trace dict, or a raw event list; None when no parent spans
    exist."""
    if isinstance(trace, SpanTracer):
        trace = trace.chrome_trace()
    events = trace.get("traceEvents", []) if isinstance(trace, dict) else trace
    xs = [e for e in events if e.get("ph") == "X"]
    by_tid: Dict[Any, List[dict]] = {}
    for e in xs:
        by_tid.setdefault(e.get("tid"), []).append(e)
    total = covered = 0.0
    for evs in by_tid.values():
        for p in evs:
            if p["name"] != parent:
                continue
            pdepth = (p.get("args") or {}).get("depth", 0)
            lo, hi = p["ts"], p["ts"] + p["dur"]
            if hi <= lo:
                continue
            kids = [
                (max(lo, e["ts"]), min(hi, e["ts"] + e["dur"]))
                for e in evs
                if e is not p
                and (e.get("args") or {}).get("depth", -1) == pdepth + 1
                and e["ts"] < hi
                and e["ts"] + e["dur"] > lo
            ]
            total += hi - lo
            covered += _merged_total(kids)
    if total <= 0:
        return None
    return covered / total


# -- process-wide default tracer --------------------------------------------

_default = SpanTracer()


def get_tracer() -> SpanTracer:
    return _default


def span(name: str, **attrs):
    """Span on the process default tracer (the instrumentation points
    in trainer/prefetch/ckpt/grad_sync all use this)."""
    return _default.span(name, **attrs)


def traced(name: Optional[str] = None) -> Callable:
    return _default.traced(name)


def enable(on: bool = True):
    _default.enabled = bool(on)


def last_open_span(tid: Optional[int] = None) -> Optional[Tuple[str, float]]:
    return _default.last_open_span(tid=tid)


# -- hang-attribution heartbeat ---------------------------------------------


class SpanHeartbeat:
    """Background publisher of the current open span into the
    runtime-metrics file (``agent.monitor`` path conventions).

    The train loop writes that file itself at log cadence — but a loop
    wedged inside a span by definition stops writing, which is exactly
    when attribution matters. This daemon thread keeps the file's
    ``open_span`` / ``open_span_elapsed_s`` / ``span_heartbeat_ts``
    fields fresh so the agent's TrainingMonitor can forward "stuck in
    ckpt_commit for 42s" to the master while the step is stuck.

    ``tid_fn`` (optional) narrows attribution to one thread — the
    trainer passes its loop thread so a by-design-parked prefetch
    producer can't masquerade as the stuck frame.
    """

    def __init__(
        self,
        tracer: Optional[SpanTracer] = None,
        path: str = "",
        interval: float = 5.0,
        tid_fn: Optional[Callable[[], Optional[int]]] = None,
    ):
        # `is None`, not truthiness: SpanTracer defines __len__, so an
        # EMPTY tracer is falsy and `tracer or _default` would silently
        # publish someone else's spans
        self._tracer = tracer if tracer is not None else _default
        self._path = path
        self._interval = interval
        self._tid_fn = tid_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self):
        """One read-modify-write of the metrics file (benign last-write
        race with the trainer's own reports: the next write of either
        side repairs the file)."""
        from dlrover_tpu.agent.monitor import (
            _metrics_path,
            atomic_write_json,
            read_runtime_metrics,
        )

        path = self._path or _metrics_path()
        payload = read_runtime_metrics(path)
        tid = self._tid_fn() if self._tid_fn is not None else None
        open_span = self._tracer.last_open_span(tid=tid)
        payload["open_span"] = open_span[0] if open_span else ""
        payload["open_span_elapsed_s"] = (
            round(open_span[1], 3) if open_span else 0.0
        )
        payload["span_heartbeat_ts"] = time.time()
        atomic_write_json(path, payload)

    def _run(self):
        while not self._stop.wait(self._interval):
            try:
                self.publish_once()
            except Exception:
                pass  # a telemetry hiccup must never hurt training

    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="span-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
