"""RLHF engine (parity: atorch/atorch/rl/ — model engine, PPO trainer,
replay buffer, generation backend).

TPU-native re-design: the reference juggles four torch models across a
DeepSpeed hybrid engine (train ↔ inference mode switches,
ds_hybrid_engine/hybrid_engine.py:378) and an external vLLM-style
backend. On TPU generation is the same jitted program family as training
(a ``lax.scan`` decode loop over a static KV cache,
models/transformer.forward_step); when train and rollout use DIFFERENT
layouts (ZeRO-3 training, replicated decode), the hybrid engine's weight
remap collapses to one ``jax.device_put`` into the rollout shardings
(RLHFEngine(train_mesh=, rollout_mesh=)). The reward model is trainable
from preference pairs (rl/reward.py, Bradley–Terry) behind the same
reward_fn seam a programmatic reward uses.
"""

from dlrover_tpu.rl.generation import generate  # noqa: F401
from dlrover_tpu.rl.buffer import ReplayBuffer  # noqa: F401
from dlrover_tpu.rl.ppo import PPOConfig, RLHFEngine  # noqa: F401
from dlrover_tpu.rl.reward import RewardModel  # noqa: F401
