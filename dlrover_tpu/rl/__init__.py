"""RLHF engine (parity: atorch/atorch/rl/ — model engine, PPO trainer,
replay buffer, generation backend).

TPU-native re-design: the reference juggles four torch models across a
DeepSpeed hybrid engine (train ↔ inference mode switches,
ds_hybrid_engine/hybrid_engine.py:378) and an external vLLM-style
backend. On TPU none of that split exists: generation is the same jitted
program family as training (a ``lax.scan`` decode loop over a static
KV cache, models/transformer.forward_step), so actor rollouts, reward
scoring and PPO updates all run under one mesh with no weight shuttling.
"""

from dlrover_tpu.rl.generation import generate  # noqa: F401
from dlrover_tpu.rl.buffer import ReplayBuffer  # noqa: F401
from dlrover_tpu.rl.ppo import PPOConfig, RLHFEngine  # noqa: F401
