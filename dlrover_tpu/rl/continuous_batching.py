"""Continuous-batching generation engine (the vLLM-backend analog).

Parity: the reference's RLHF engine generates rollouts through a
vLLM-style inference backend (atorch/rl/model_engine/model_engine.py +
its inference-backend seam). vLLM's throughput comes from *continuous
batching*: finished sequences leave the batch immediately and new
prompts take their slots, so short completions never leave the device
idle waiting for the batch's longest sequence.

The TPU-native redesign keeps everything static-shaped inside ONE
compiled program — no dynamic batch, no host scheduler in the loop:

- ``slots`` fixed sequence slots, each with its own region of the
  preallocated KV cache ``[L, slots, T, H, D]``.
- **Unified chunked-prefill/decode step**: every iteration feeds
  exactly one token per slot through ``forward_step_ragged``
  (per-slot positions). A slot mid-prompt consumes its next PROMPT
  token (prefill rides along with decode, vLLM's chunked-prefill); a
  slot past its prompt consumes the token it just sampled.
- **In-graph refill**: a slot finishing (EOS / token budget) scatters
  its completed sequence to the output buffers and loads the next
  queued prompt in the same compiled step — stale cache needs no
  clearing because position ``i`` is rewritten before anything can
  attend to it.
- One ``lax.while_loop`` runs until every prompt is emitted; the whole
  engine is a single ``jit`` with static knobs.

Sampling uses the same temperature/top-k/top-p support restriction as
``rl.generation`` (shared ``_mask_logits``), and the recorded logprobs
are behavior-policy logprobs under the actual sampling distribution.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.models.config import TransformerConfig
from dlrover_tpu.models.transformer import (
    Params,
    forward_step_ragged,
    init_kv_cache,
)
from dlrover_tpu.rl.generation import _mask_logits, _rollout_pins


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "eos_id", "slots", "temperature",
        "greedy", "top_k", "top_p", "mesh",
    ),
)
def continuous_generate(
    params: Params,
    prompts: jnp.ndarray,  # [N, P_max] int32, right-padded
    prompt_lens: jnp.ndarray,  # [N] int32
    key,
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    eos_id: int = -1,  # -1: no EOS — every sequence runs its budget
    slots: int = 8,
    temperature: float = 1.0,
    greedy: bool = False,
    top_k: int = 0,
    top_p: float = 1.0,
    mesh=None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Generate completions for ``N`` prompts through ``slots`` device
    slots with continuous refill.

    Returns ``(tokens [N, P_max+max_new], logps [N, max_new],
    out_lens [N])``: per prompt, its tokens (prompt + completion,
    zero-padded past ``out_lens``), the behavior logprobs of the
    generated part (zero-padded), and the total sequence length. A
    sequence stops at ``eos_id`` (the EOS token is kept, budget
    permitting) or after ``max_new_tokens``.

    Tail-latency note: once the prompt queue drains, idle slots
    (``pidx == N``) still run full forward passes and dummy sampling
    each iteration until the slowest active slot finishes — the price
    of static shapes under ``lax.while_loop``. With ``slots`` far above
    the expected concurrency, that idle work can dominate the tail;
    size ``slots`` to the live prompt count.
    """
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not greedy and temperature <= 0.0:
        raise ValueError(
            f"temperature must be > 0 for sampling, got {temperature}"
        )
    N, P_max = prompts.shape
    S = min(slots, N)
    T = P_max + max_new_tokens
    cache = init_kv_cache(cfg, S, T)
    if mesh is not None:
        params, prompts, cache = _rollout_pins(
            params, prompts, cache, cfg, mesh
        )

    pad_to_T = jnp.zeros((N, T - P_max), jnp.int32)
    prompts_T = jnp.concatenate([prompts, pad_to_T], axis=1)  # [N, T]

    # slot state. idle slots carry prompt_idx == N (the scatter dump row)
    slot_ix = jnp.arange(S)
    init_idx = slot_ix  # first S prompts occupy the slots (S <= N)
    state = dict(
        cache=cache,
        tokens=prompts_T[init_idx],  # [S, T] token buffer per slot
        logps=jnp.zeros((S, T), jnp.float32),
        cur=jnp.zeros((S,), jnp.int32),  # tokens already in cache
        plen=prompt_lens[init_idx].astype(jnp.int32),
        pidx=init_idx.astype(jnp.int32),
        next_p=jnp.int32(S),
        emitted=jnp.int32(0),
        # output buffers; row N is the dump row for idle-slot scatters
        out_tokens=jnp.zeros((N + 1, T), jnp.int32),
        out_logps=jnp.zeros((N + 1, T), jnp.float32),
        out_lens=jnp.zeros((N + 1,), jnp.int32),
        step=jnp.int32(0),
    )

    def sample(logits, k):
        if greedy:
            tok = jnp.argmax(logits, axis=-1)
            scaled = logits
        else:
            scaled = _mask_logits(logits / temperature, top_k, top_p)
            tok = jax.random.categorical(k, scaled, axis=-1)
        logp = jax.nn.log_softmax(scaled, axis=-1)
        tok_logp = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
        return tok.astype(jnp.int32), tok_logp

    def cond(st):
        return st["emitted"] < N

    def body(st):
        active = st["pidx"] < N  # idle slots (prompt queue drained)
        # feed one token per slot: the next unprocessed buffer entry
        feed = st["tokens"][slot_ix, st["cur"]]
        logits, cache = forward_step_ragged(
            params, feed, cfg, st["cache"], st["cur"]
        )
        new_cur = st["cur"] + jnp.where(active, 1, 0)

        # slots whose fed token completed the prompt (or continued the
        # completion) sample their next token from these logits
        in_decode = active & (new_cur >= st["plen"])
        tok, tok_logp = sample(
            logits, jax.random.fold_in(key, st["step"])
        )
        tokens = st["tokens"].at[slot_ix, new_cur].set(
            jnp.where(in_decode, tok, st["tokens"][slot_ix, new_cur])
        )
        logps = st["logps"].at[slot_ix, new_cur].set(
            jnp.where(in_decode, tok_logp, 0.0)
        )

        n_new = new_cur + 1 - st["plen"]  # completion tokens incl. this
        hit_eos = in_decode & (eos_id >= 0) & (tok == eos_id)
        out_of_budget = in_decode & (n_new >= max_new_tokens)
        done = hit_eos | out_of_budget

        # emit: sequence length counts the sampled token
        seq_len = new_cur + 1
        dump = jnp.where(done, st["pidx"], N)
        out_tokens = st["out_tokens"].at[dump].set(tokens)
        out_logps = st["out_logps"].at[dump].set(logps)
        out_lens = st["out_lens"].at[dump].set(seq_len)

        # refill: k-th finishing slot (slot order) takes prompt
        # next_p + k; slots beyond the queue go idle (pidx = N)
        order = jnp.cumsum(done.astype(jnp.int32)) - 1
        new_idx = st["next_p"] + order  # valid where done
        refillable = done & (new_idx < N)
        safe_idx = jnp.where(refillable, new_idx, 0)
        tokens = jnp.where(
            refillable[:, None], prompts_T[safe_idx], tokens
        )
        logps = jnp.where(refillable[:, None], 0.0, logps)
        cur = jnp.where(done, 0, new_cur)
        plen = jnp.where(
            refillable, prompt_lens[safe_idx].astype(jnp.int32),
            st["plen"],
        )
        pidx = jnp.where(
            done,
            jnp.where(refillable, new_idx, N).astype(jnp.int32),
            st["pidx"],
        )
        return dict(
            cache=cache,
            tokens=tokens,
            logps=logps,
            cur=cur,
            plen=plen,
            pidx=pidx,
            next_p=st["next_p"] + jnp.sum(done.astype(jnp.int32)),
            emitted=st["emitted"] + jnp.sum(done.astype(jnp.int32)),
            out_tokens=out_tokens,
            out_logps=out_logps,
            out_lens=out_lens,
            step=st["step"] + 1,
        )

    st = lax.while_loop(cond, body, state)
    out_tokens = st["out_tokens"][:N]
    out_lens = st["out_lens"][:N]
    # logps buffer is indexed by absolute position (completion starts
    # at each prompt's length); shift rows so it starts at column 0
    # (PPO consumes [N, max_new])
    cols = jnp.arange(max_new_tokens)[None, :]
    gather_ix = jnp.clip(
        prompt_lens.astype(jnp.int32)[:, None] + cols, 0, T - 1
    )
    logps_aligned = jnp.take_along_axis(
        st["out_logps"][:N], gather_ix, axis=1
    )
    n_new = out_lens - prompt_lens.astype(jnp.int32)
    logps_aligned = jnp.where(cols < n_new[:, None], logps_aligned, 0.0)
    return out_tokens, logps_aligned, out_lens
