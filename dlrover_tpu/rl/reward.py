"""Trainable reward model for RLHF.

Parity: the reference's reward model is one of the four managed models
in its RL engine (atorch/rl/model_engine/model_engine.py — actor /
critic / ref / reward), trained separately on preference pairs and then
frozen for PPO. Here the reward model is the same transformer trunk as
the actor/critic (``forward(..., return_hidden=True)`` — reward math can
never drift from the model path) with a scalar head read at each
sequence's LAST token, trained with the Bradley–Terry pairwise loss
-log σ(r_chosen − r_rejected) (the InstructGPT recipe).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.models.config import TransformerConfig
from dlrover_tpu.models.transformer import forward, init_params


def init_reward_params(key, cfg: TransformerConfig):
    """Reward model = transformer trunk + scalar reward head."""
    trunk = init_params(key, cfg)
    head = (
        jax.random.normal(jax.random.fold_in(key, 2), (cfg.model_dim,))
        * cfg.model_dim**-0.5
    )
    return {"trunk": trunk, "reward_head": head}


def reward_scores(
    rparams,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    pad_token_id: int | None = None,
):
    """tokens [B, T] → scalar reward per sequence [B].

    The head reads the hidden state at each sequence's LAST REAL token
    (the InstructGPT recipe): with ``pad_token_id`` set, that is the
    position before the first trailing pad (right-padding assumed —
    lengths are counted as non-pad tokens, so a pad id appearing inside
    the sequence is the caller's bug). Without it, inputs must be
    unpadded fixed-length sequences and the final position is scored."""
    hidden, _ = forward(rparams["trunk"], tokens, cfg, return_hidden=True)
    if pad_token_id is None:
        last = hidden[:, -1]
    else:
        idx = jnp.maximum(
            jnp.sum((tokens != pad_token_id).astype(jnp.int32), axis=-1) - 1,
            0,
        )
        last = jnp.take_along_axis(
            hidden, idx[:, None, None], axis=1
        )[:, 0]
    return jnp.einsum(
        "bd,d->b", last.astype(jnp.float32), rparams["reward_head"]
    )


def preference_loss(
    rparams,
    chosen,
    rejected,
    cfg: TransformerConfig,
    pad_token_id: int | None = None,
):
    """Bradley–Terry: -log σ(r_chosen − r_rejected), plus accuracy."""
    r_c = reward_scores(rparams, chosen, cfg, pad_token_id)
    r_r = reward_scores(rparams, rejected, cfg, pad_token_id)
    loss = -jnp.mean(jax.nn.log_sigmoid(r_c - r_r))
    acc = jnp.mean((r_c > r_r).astype(jnp.float32))
    return loss, acc


class RewardModel:
    """Preference-trained reward model + the ``reward_fn`` adapter the
    PPO engine consumes."""

    def __init__(
        self,
        cfg: TransformerConfig,
        lr: float = 1e-4,
        seed: int = 0,
        pad_token_id: int | None = None,
    ):
        self.cfg = cfg
        self.params = init_reward_params(jax.random.PRNGKey(seed), cfg)
        self.tx = optax.adamw(lr)
        self.opt_state = self.tx.init(self.params)
        self._step = jax.jit(
            functools.partial(
                _reward_update, cfg=cfg, tx=self.tx,
                pad_token_id=pad_token_id,
            )
        )
        self._scores = jax.jit(
            functools.partial(
                reward_scores, cfg=cfg, pad_token_id=pad_token_id
            )
        )

    def train_on_preferences(
        self, chosen: np.ndarray, rejected: np.ndarray, epochs: int = 1
    ) -> dict:
        """chosen/rejected [N, T] token pairs (chosen preferred).
        Returns the last step's {loss, accuracy}."""
        metrics = {}
        for _ in range(epochs):
            self.params, self.opt_state, metrics = self._step(
                self.params,
                self.opt_state,
                jnp.asarray(chosen),
                jnp.asarray(rejected),
            )
        return {k: float(v) for k, v in metrics.items()}

    def score(self, tokens: np.ndarray) -> np.ndarray:
        return np.asarray(self._scores(self.params, jnp.asarray(tokens)))

    def as_reward_fn(self):
        """The (tokens, prompt_len) → [B] callable RLHFEngine takes —
        a TRAINED model behind the same seam a programmatic reward uses."""
        return lambda tokens, prompt_len: self.score(tokens)


def _reward_update(
    params, opt_state, chosen, rejected, *, cfg, tx, pad_token_id=None
):
    (loss, acc), grads = jax.value_and_grad(
        preference_loss, has_aux=True
    )(params, chosen, rejected, cfg, pad_token_id)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, {"loss": loss, "accuracy": acc}
