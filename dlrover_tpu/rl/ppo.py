"""PPO for LM alignment: the RLHF engine.

Parity: atorch/rl/trainer (PPO trainer), rl/model_engine/model_engine.py
(actor/critic/ref/reward model management) and the DS hybrid engine's
train↔generate switching — which TPU doesn't need: rollout and update
are two jitted programs over the same mesh.

Pieces:
- actor = the trained LM; ref = frozen copy (KL anchor); critic = value
  head over the actor's architecture (own params); reward_fn = any
  callable scoring full sequences (a learned reward model or a
  programmatic reward).
- KL-shaped per-token rewards (reward at the last token, minus
  kl_coef·KL everywhere), GAE(λ) advantages, clipped policy + value
  losses — the standard InstructGPT/trlx recipe the reference implements.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.models.config import TransformerConfig
from dlrover_tpu.models.transformer import forward, init_params
from dlrover_tpu.rl.buffer import Experience, ReplayBuffer
from dlrover_tpu.rl.generation import generate, sequence_logprobs


@dataclass(frozen=True)
class PPOConfig:
    rollout_batch: int = 8
    max_new_tokens: int = 16
    temperature: float = 1.0
    # restricted-support sampling for rollouts; PPO's importance ratio
    # stays centered on 1 because make_experience re-scores old
    # logprobs with the SAME full-support sequence_logprobs the update
    # uses (the sampler's masked logprobs are diagnostics only)
    top_k: int = 0  # 0 = keep all
    top_p: float = 1.0  # 1.0 = keep all
    kl_coef: float = 0.1
    gamma: float = 1.0
    lam: float = 0.95
    clip_ratio: float = 0.2
    value_clip: float = 0.2
    vf_coef: float = 0.5
    ppo_epochs: int = 2
    minibatch_size: int = 8
    learning_rate: float = 1e-5


def init_critic_params(key, cfg: TransformerConfig):
    """Critic = transformer trunk + scalar value head."""
    trunk = init_params(key, cfg)
    head = (
        jax.random.normal(jax.random.fold_in(key, 1), (cfg.model_dim,))
        * cfg.model_dim**-0.5
    )
    return {"trunk": trunk, "value_head": head}


def critic_values(cparams, tokens, cfg: TransformerConfig, prompt_len: int):
    """Per-position values over the completion [B, N] (value of the
    state *before* each generated token). The trunk IS the LM forward
    (``return_hidden`` skips the vocab projection), so critic math can
    never drift from the model path and remat applies."""
    hidden, _ = forward(cparams["trunk"], tokens, cfg, return_hidden=True)
    values = jnp.einsum(
        "btd,d->bt", hidden.astype(jnp.float32), cparams["value_head"]
    )
    return values[:, prompt_len - 1 : -1]


def gae_advantages(rewards, values, gamma: float, lam: float):
    """[B, N] rewards/values → (advantages, returns), standard GAE(λ)."""
    B, N = rewards.shape

    def step(carry, t):
        adv_next = carry
        v_next = jnp.where(t + 1 < N, values[:, (t + 1) % N], 0.0)
        delta = rewards[:, t] + gamma * v_next - values[:, t]
        adv = delta + gamma * lam * adv_next
        return adv, adv

    _, advs = jax.lax.scan(
        step, jnp.zeros(B), jnp.arange(N - 1, -1, -1)
    )
    advantages = advs[::-1].T  # [B, N]
    return advantages, advantages + values


class RLHFEngine:
    """Owns actor/ref/critic state and the rollout→train cycle."""

    def __init__(
        self,
        cfg: TransformerConfig,
        reward_fn: Callable[[np.ndarray, int], np.ndarray],
        ppo: Optional[PPOConfig] = None,
        seed: int = 0,
        train_mesh=None,
        rollout_mesh=None,
    ):
        """``train_mesh``/``rollout_mesh``: when both are given, actor
        weights live TRAIN-sharded (e.g. ZeRO-3 over fsdp) and are
        explicitly resharded to the rollout layout before every
        generation phase — the DS hybrid engine's train↔inference weight
        remap (ref hybrid_engine.py:378), expressed as one
        ``jax.device_put`` (XLA emits the all-gather/all-to-all)."""
        self.cfg = cfg
        self.ppo = ppo or PPOConfig()
        self.reward_fn = reward_fn
        key = jax.random.PRNGKey(seed)
        self.actor_params = init_params(key, cfg)
        self.ref_params = jax.tree_util.tree_map(
            lambda x: x, self.actor_params
        )  # frozen copy
        self.critic_params = init_critic_params(
            jax.random.fold_in(key, 7), cfg
        )
        self._train_shardings = None
        self._rollout_shardings = None
        self._rollout_mesh = None
        if (train_mesh is None) != (rollout_mesh is None):
            # silently ignoring half a placement request would leave
            # weights in a layout the user didn't ask for (OOM or wrong
            # sharding with no visible cause)
            raise ValueError(
                "hybrid placement needs BOTH train_mesh and rollout_mesh"
            )
        if train_mesh is not None and rollout_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dlrover_tpu.models.transformer import logical_axes
            from dlrover_tpu.parallel.sharding_rules import (
                apply_rules,
                default_lm_rules,
            )

            # train layout: the LM rule table (fsdp/tp as the mesh says)
            self._train_shardings = apply_rules(
                logical_axes(cfg), default_lm_rules(), train_mesh
            )
            # rollout layout: the SAME rule table on the rollout mesh —
            # a dp×tp rollout mesh gives tp-sharded heads/vocab (an
            # actor larger than one chip can roll out) and, with no
            # fsdp axis, everything else replicated (no per-step weight
            # all-gathers in the decode loop). A dp-only rollout mesh
            # degenerates to full replication, the latency-optimal
            # layout for small actors.
            self._rollout_shardings = apply_rules(
                logical_axes(cfg), default_lm_rules(), rollout_mesh
            )
            self._rollout_mesh = rollout_mesh
            self.actor_params = jax.device_put(
                self.actor_params, self._train_shardings
            )
            self.ref_params = jax.device_put(
                self.ref_params, self._rollout_shardings
            )  # ref only ever scores rollouts
        self.tx = optax.adamw(self.ppo.learning_rate)
        self.opt_state = self.tx.init(
            {"actor": self.actor_params, "critic": self.critic_params}
        )
        self.buffer = ReplayBuffer()
        self._np_rng = np.random.default_rng(seed)
        self._key = jax.random.fold_in(key, 99)
        self._train_step = jax.jit(
            functools.partial(
                _ppo_update, cfg=cfg, ppo=self.ppo, tx=self.tx
            ),
            static_argnums=(3,),  # prompt_len slices the token axis
        )
        # rollout scoring is jitted too (two full forwards per rollout
        # would otherwise dispatch op-by-op); prompt_len stays static
        self._seq_logprobs = jax.jit(
            functools.partial(sequence_logprobs, cfg=cfg),
            static_argnames=("prompt_len",),
        )
        self._critic_values = jax.jit(
            functools.partial(critic_values, cfg=cfg),
            static_argnames=("prompt_len",),
        )

    # -- rollout --------------------------------------------------------
    def make_experience(self, prompts: np.ndarray) -> Experience:
        """Rollout + score + advantage (parity: trlx/atorch
        make_experience): generate with the actor, KL-shape rewards
        against the frozen ref, GAE with the critic."""
        P = prompts.shape[1]
        self._key, k = jax.random.split(self._key)
        # the hybrid-engine weight flow: reshard the (train-layout)
        # actor weights into the rollout layout before generating
        rollout_params = self.actor_params
        if self._rollout_shardings is not None:
            rollout_params = jax.device_put(
                self.actor_params, self._rollout_shardings
            )
        tokens, _ = generate(
            rollout_params,
            jnp.asarray(prompts),
            k,
            self.cfg,
            max_new_tokens=self.ppo.max_new_tokens,
            temperature=self.ppo.temperature,
            top_k=self.ppo.top_k,
            top_p=self.ppo.top_p,
            mesh=self._rollout_mesh,
        )
        # old-policy logprobs MUST come from the same scoring function
        # the update uses (full-support, temperature-1 sequence_logprobs)
        # — generate()'s returned logprobs are the temperature-scaled,
        # support-restricted SAMPLER statistics, and using them here
        # would center the PPO clip window off 1 and mix scales in the
        # KL term whenever temperature/top_k/top_p reshape the sampler
        logprobs = self._seq_logprobs(
            rollout_params, tokens, prompt_len=P
        )
        ref_logprobs = self._seq_logprobs(
            self.ref_params, tokens, prompt_len=P
        )
        values = self._critic_values(
            self.critic_params, tokens, prompt_len=P
        )
        tokens_np = np.asarray(tokens)
        # sequence-level reward lands on the final token; per-token KL
        # penalty shapes the rest (InstructGPT recipe)
        seq_reward = np.asarray(
            self.reward_fn(tokens_np, P), dtype=np.float32
        )
        kl = np.asarray(logprobs - ref_logprobs)
        rewards = -self.ppo.kl_coef * kl
        rewards[:, -1] += seq_reward
        advantages, returns = gae_advantages(
            jnp.asarray(rewards),
            jnp.asarray(values),
            self.ppo.gamma,
            self.ppo.lam,
        )
        exp = Experience(
            tokens=tokens_np,
            logprobs=np.asarray(logprobs),
            ref_logprobs=np.asarray(ref_logprobs),
            values=np.asarray(values),
            rewards=rewards,
            advantages=np.asarray(advantages),
            returns=np.asarray(returns),
        )
        self.buffer.add(exp)
        return exp

    # -- update ---------------------------------------------------------
    def train(self, prompt_len: int) -> dict:
        """PPO epochs over the buffer; returns last minibatch metrics."""
        metrics = {}
        params = {"actor": self.actor_params, "critic": self.critic_params}
        for _ in range(self.ppo.ppo_epochs):
            for mb in self.buffer.minibatches(
                self.ppo.minibatch_size, self._np_rng
            ):
                params, self.opt_state, metrics = self._train_step(
                    params,
                    self.opt_state,
                    {k: jnp.asarray(v) for k, v in mb.items()},
                    prompt_len,
                )
        self.actor_params = params["actor"]
        self.critic_params = params["critic"]
        self.buffer.clear()
        return {k: float(v) for k, v in metrics.items()}


def _ppo_update(params, opt_state, mb, prompt_len, *, cfg, ppo, tx):
    def loss_fn(params):
        new_logprobs = sequence_logprobs(
            params["actor"], mb["tokens"], cfg, prompt_len
        )
        ratio = jnp.exp(new_logprobs - mb["logprobs"])
        adv = mb["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg1 = ratio * adv
        pg2 = jnp.clip(ratio, 1 - ppo.clip_ratio, 1 + ppo.clip_ratio) * adv
        policy_loss = -jnp.mean(jnp.minimum(pg1, pg2))

        values = critic_values(
            params["critic"], mb["tokens"], cfg, prompt_len
        )
        v_clipped = mb["values"] + jnp.clip(
            values - mb["values"], -ppo.value_clip, ppo.value_clip
        )
        vf_loss = 0.5 * jnp.mean(
            jnp.maximum(
                (values - mb["returns"]) ** 2,
                (v_clipped - mb["returns"]) ** 2,
            )
        )
        loss = policy_loss + ppo.vf_coef * vf_loss
        return loss, {
            "loss": loss,
            "policy_loss": policy_loss,
            "value_loss": vf_loss,
            "approx_kl": jnp.mean(mb["logprobs"] - new_logprobs),
            "clip_frac": jnp.mean(
                (jnp.abs(ratio - 1) > ppo.clip_ratio).astype(jnp.float32)
            ),
        }

    (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params
    )
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, metrics
