"""Batched autoregressive generation with a static KV cache.

Parity: the reference's inference backend for RLHF rollouts
(atorch/rl/model_engine/model_engine.py generation path + its
vLLM-style backend). The TPU equivalent is a single compiled program:
prefill the prompt in one ``forward_step`` call, then ``lax.scan`` the
decode steps over a preallocated cache — static shapes throughout, so
XLA pipelines the whole rollout with no host round-trips.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.models.config import TransformerConfig
from dlrover_tpu.models.transformer import (
    Params,
    forward_step,
    init_kv_cache,
)


def _mask_logits(scaled, top_k: int, top_p: float):
    """Restrict the sampling support (vLLM-style knobs, all static):
    ``top_k`` keeps the k best logits; ``top_p`` keeps the smallest
    prefix of the probability-sorted vocab whose mass reaches p
    (nucleus). Masked entries go to -inf BEFORE the softmax, so the
    returned logprobs stay the true behavior-policy logprobs.

    One pass over the vocab: ``lax.top_k`` covers the k threshold
    without a full sort, and when the nucleus is active its single
    descending sort serves both knobs.
    """
    V = scaled.shape[-1]
    top_k = min(top_k, V) if top_k > 0 else 0  # clamp: keep-all
    if top_p < 1.0:
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
        if top_k > 0:
            # top-k first, nucleus over the RESTRICTED renormalized
            # distribution (the HF/vLLM composition order). No separate
            # kth mask on `scaled`: the nucleus cutoff below is always
            # >= the kth value, so its mask subsumes it.
            sorted_desc = jnp.where(
                jnp.arange(V)[None, :] < top_k, sorted_desc, -jnp.inf
            )
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep every token whose PRECEDING mass is < p (the boundary
        # token that crosses p stays in, per the nucleus definition)
        keep = cum - probs < top_p
        n_keep = jnp.sum(keep, axis=-1)  # >= 1 always
        cutoff = jnp.take_along_axis(
            sorted_desc, (n_keep - 1)[:, None], axis=-1
        )
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
    elif top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return scaled


def _rollout_pins(params, prompt, cache, cfg, mesh):
    """Pin the decode layouts on ``mesh``: weights per the LM rule
    table (heads/vocab → tp; fsdp is absent from rollout meshes so
    "embed" maps to replicated), KV cache batch → dp and kv-heads → tp,
    token batch → dp. This is what lets an actor larger than one chip
    roll out: the per-step attention/head matmuls run tp-sharded with
    XLA inserting the same collectives training uses (parity: the
    reference's multi-device inference engine, model_engine.py +
    ds_hybrid_engine/hybrid_engine.py:378)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlrover_tpu.models.transformer import logical_axes
    from dlrover_tpu.parallel.sharding_rules import (
        apply_rules,
        default_lm_rules,
    )

    shardings = apply_rules(logical_axes(cfg), default_lm_rules(), mesh)
    params = jax.tree_util.tree_map(
        lax.with_sharding_constraint, params, shardings
    )
    dp = "dp" if "dp" in mesh.shape else None
    tp = "tp" if "tp" in mesh.shape else None
    prompt = lax.with_sharding_constraint(
        prompt, NamedSharding(mesh, P(dp))
    )
    cache_spec = NamedSharding(mesh, P(None, dp, None, tp, None))
    cache = jax.tree_util.tree_map(
        lambda c: lax.with_sharding_constraint(c, cache_spec), cache
    )
    return params, prompt, cache


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "temperature", "greedy", "top_k",
        "top_p", "mesh",
    ),
)
def generate(
    params: Params,
    prompt: jnp.ndarray,
    key,
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    temperature: float = 1.0,
    greedy: bool = False,
    top_k: int = 0,
    top_p: float = 1.0,
    mesh=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """prompt [B, P] int32 → (tokens [B, P+N], logprobs [B, N]).

    ``logprobs`` are the BEHAVIOR-policy log-probs of each sampled
    token — computed under the actual sampling distribution
    (temperature-scaled, ``top_k``/``top_p``-restricted; 0 / 1.0
    disable the restrictions). They are sampler diagnostics: a PPO
    consumer must record its old-policy logprobs with the SAME scoring
    function its update uses (``sequence_logprobs``), which the RLHF
    engine does — mixing the two scales would off-center the clip
    window and the KL estimate.
    """
    if not 0.0 < top_p <= 1.0:
        # top_p=0 silently meaning "keep all" has bitten people; the
        # near-greedy limit is top_p -> 0+, not 0
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    B, P = prompt.shape
    N = max_new_tokens
    cache = init_kv_cache(cfg, B, P + N)
    if mesh is not None:
        # sharded rollout: weights tp-sharded, cache/batch dp-sharded.
        # One pin at entry — XLA propagates the layouts through the
        # whole prefill + decode scan
        params, prompt, cache = _rollout_pins(
            params, prompt, cache, cfg, mesh
        )

    # prefill: one chunked call for the whole prompt
    logits, cache = forward_step(params, prompt, cfg, cache, 0)
    last_logits = logits[:, -1]

    def sample(logits, key):
        if greedy:
            tok = jnp.argmax(logits, axis=-1)
            scaled = logits
        else:
            scaled = _mask_logits(logits / temperature, top_k, top_p)
            tok = jax.random.categorical(key, scaled, axis=-1)
        # logprobs under the ACTUAL sampling distribution (temperature-
        # scaled and support-restricted): these are PPO's behavior-
        # policy logprobs, and a mismatch here biases the importance
        # ratio and KL estimate
        logp = jax.nn.log_softmax(scaled, axis=-1)
        tok_logp = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
        return tok.astype(jnp.int32), tok_logp

    def step(carry, key):
        cache, last_logits, pos = carry
        tok, tok_logp = sample(last_logits, key)
        logits, cache = forward_step(
            params, tok[:, None], cfg, cache, pos
        )
        return (cache, logits[:, -1], pos + 1), (tok, tok_logp)

    keys = jax.random.split(key, N)
    (_, _, _), (toks, logps) = lax.scan(
        step, (cache, last_logits, P), keys
    )
    tokens = jnp.concatenate([prompt, toks.T], axis=1)
    return tokens, logps.T


def sequence_logprobs(
    params: Params,
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    prompt_len: int,
) -> jnp.ndarray:
    """Teacher-forced per-token log-probs of the completion part of
    ``tokens`` [B, P+N] → [B, N]. Used for the reference-policy KL and
    for re-scoring under updated actor weights."""
    from dlrover_tpu.models.transformer import forward

    logits, _ = forward(params, tokens[:, :-1], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    targets = tokens[:, 1:]
    tok_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[
        ..., 0
    ]
    return tok_logp[:, prompt_len - 1 :]
