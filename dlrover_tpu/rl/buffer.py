"""Experience replay buffer for PPO.

Parity: atorch/rl/replay_buffer (host-side batch store between rollout
and train phases). Numpy-backed: rollouts land as host arrays, minibatch
sampling re-shards onto the mesh per optimizer step.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List

import numpy as np


@dataclass
class Experience:
    tokens: np.ndarray  # [B, P+N]
    logprobs: np.ndarray  # [B, N] actor logprobs at rollout time
    ref_logprobs: np.ndarray  # [B, N]
    values: np.ndarray  # [B, N] critic values at rollout time
    rewards: np.ndarray  # [B, N] per-token (KL-shaped) rewards
    advantages: np.ndarray  # [B, N]
    returns: np.ndarray  # [B, N]


class ReplayBuffer:
    def __init__(self, capacity: int = 0):
        self._items: List[Experience] = []
        self._capacity = capacity

    def add(self, exp: Experience):
        self._items.append(exp)
        if self._capacity and len(self._items) > self._capacity:
            self._items.pop(0)

    def __len__(self) -> int:
        return sum(len(e.tokens) for e in self._items)

    def clear(self):
        self._items.clear()

    def _stacked(self) -> Dict[str, np.ndarray]:
        fields = (
            "tokens", "logprobs", "ref_logprobs", "values", "rewards",
            "advantages", "returns",
        )
        return {
            f: np.concatenate([getattr(e, f) for e in self._items])
            for f in fields
        }

    def minibatches(
        self, batch_size: int, rng: np.random.Generator
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Shuffled minibatches over everything stored (one PPO epoch).
        The final partial batch is yielded too — silently dropping it
        would make train() a no-op whenever n < batch_size."""
        data = self._stacked()
        n = len(data["tokens"])
        order = rng.permutation(n)
        for start in range(0, n, batch_size):
            idx = order[start : start + batch_size]
            yield {k: v[idx] for k, v in data.items()}
