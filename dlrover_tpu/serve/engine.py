"""Continuous-batching serving engine over subscriber-mapped weights.

Co-location contract (what keeps training whole while serving earns
tokens):

- **Weights**: adopted only from seqlock-validated, crc-verified
  ``PublishedFrame``s; a swap happens strictly BETWEEN batches — a
  sequence is always decoded end-to-end under one weight step. After
  the host→device copy the frame's generation is re-checked: a commit
  that landed mid-copy tears the views, so the copied params are
  dropped and the engine keeps serving the previous step.
- **Transfers**: every swap's host→device bytes ride a
  ``Priority.BACKGROUND`` arbiter stream — checkpoint staging and
  embedding spill always win the rails.
- **Sparse state**: serving-side embedding lookups go through the
  read-only probe (``gather(insert_missing=False)``), so serving
  traffic can neither admit rows to the trainer's hot tier nor perturb
  its LRU recency / pin state.
- **Scheduling**: with ``soak="idle_gaps"`` a batch starts only while
  the arbiter's compute-window marks read idle (between steps, resize
  drains, or no trainer at all); batch wall time is booked to the
  goodput ledger's ``serving_soak`` row, which ranks below every
  training category — serving can only claim seconds training left
  unclaimed.

Everything observable exports as ``dlrover_serving_*`` metrics
(docs/observability.md has the full table).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from dlrover_tpu.common import faults
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ckpt.sharding import ShardRecord, restore_state
from dlrover_tpu.ckpt.shm_handler import PublishedFrame, ShmSubscriber
from dlrover_tpu.obs import goodput
from dlrover_tpu.obs.metrics import MetricsRegistry, default_registry
from dlrover_tpu.parallel import transfer_sched

METRIC_PREFIX = "dlrover_serving_"


@dataclass
class ServingConfig:
    """Knobs of the co-located serving plane (docs/serving.md)."""

    max_new_tokens: int = 16
    slots: int = 4
    eos_id: int = -1
    temperature: float = 1.0
    greedy: bool = True
    top_k: int = 0
    top_p: float = 1.0
    # "idle_gaps": start a batch only while the trainer's arbiter
    # marks read idle (preferential soak); "always": serve whenever
    # asked (dedicated serving process, or tests)
    soak: str = "idle_gaps"
    # idle-gap gate: poll cadence and how long to wait for a gap
    # before serving anyway (a soak that can starve forever is an
    # outage, not a policy; forced batches are counted)
    gap_poll_interval_s: float = 0.002
    gap_wait_timeout_s: float = 2.0


class ServingEngine:
    """Decode continuous batches over the newest subscribed weights.

    ``params_template`` is a pytree shaped like the published params —
    concrete arrays or ``ShapeDtypeStruct``s carrying shardings (the
    same contract as ``restore_state``). ``param_prefix`` maps template
    leaf paths onto published record paths (a trainer that publishes a
    whole ``TrainState`` prefixes its params subtree, e.g.
    ``"params/"``; publishing bare params needs none).
    """

    def __init__(
        self,
        cfg,
        subscriber: ShmSubscriber,
        params_template: Any,
        serving: Optional[ServingConfig] = None,
        param_prefix: str = "",
        mesh=None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.cfg = cfg
        self.subscriber = subscriber
        self.serving = serving or ServingConfig()
        self.params_template = params_template
        self.param_prefix = param_prefix
        self.mesh = mesh
        self.registry = registry or default_registry()
        self.params: Optional[Any] = None
        self.weight_step: int = -1
        self.weight_generation: int = -1
        self.last_swap_ms: float = 0.0
        self.swaps = 0
        self.dropped_swaps = 0  # commit landed mid-copy; params dropped
        self.forced_batches = 0  # served without an idle gap (timeout)
        self._exported_crc = 0
        self._exported_torn = 0
        self._stream = transfer_sched.get_arbiter().register(
            "serve_h2d",
            priority=transfer_sched.Priority.BACKGROUND,
            direction="h2d",
        )
        r = self.registry
        self._m_tokens = r.counter(
            METRIC_PREFIX + "tokens_total",
            "completion tokens served by the co-located plane",
        )
        self._m_batches = r.counter(
            METRIC_PREFIX + "batches_total",
            "continuous batches decoded by the co-located plane",
        )
        self._m_tokens_per_s = r.gauge(
            METRIC_PREFIX + "tokens_per_s",
            "serving throughput over the last batch",
        )
        self._m_staleness = r.gauge(
            METRIC_PREFIX + "weight_staleness_steps",
            "steps the serving weights lag the newest shm commit",
        )
        self._m_swap_ms = r.gauge(
            METRIC_PREFIX + "swap_latency_ms",
            "host→device latency of the last adopted weight swap",
        )
        self._m_swaps = r.counter(
            METRIC_PREFIX + "swaps_total",
            "weight frames adopted by the serving engine",
        )
        self._m_crc = r.counter(
            METRIC_PREFIX + "crc_retries_total",
            "subscribed frames skipped on crc mismatch",
        )
        self._m_torn = r.counter(
            METRIC_PREFIX + "torn_retries_total",
            "subscribed frames dropped by the seqlock re-check",
        )
        self._m_forced = r.counter(
            METRIC_PREFIX + "forced_batches_total",
            "batches served without finding an idle gap (gate timeout)",
        )
        self._m_probe_rows = r.counter(
            METRIC_PREFIX + "embedding_probe_rows_total",
            "rows served via the read-only embedding probe",
        )

    # -- weight swaps ---------------------------------------------------
    def try_swap(self) -> bool:
        """Adopt the newest committed frame, if any. Called between
        batches only — never while a sequence is mid-decode.

        Fault point ``serve.swap``: an armed io_error makes this swap
        attempt fail closed (the engine keeps serving the weights it
        already holds; the next commit retries)."""
        frame = self.subscriber.poll()
        self._fold_subscriber_counters()
        if frame is None:
            return False
        try:
            faults.fire("serve.swap")
            params = self._adopt(frame)
        except Exception as e:
            logger.warning(
                f"serving: swap to step {frame.step} failed ({e}); "
                f"keeping step {self.weight_step}"
            )
            return False
        if params is None:
            self.dropped_swaps += 1
            self._m_torn.inc()
            return False
        self.params = params
        self.weight_step = frame.step
        self.weight_generation = frame.generation
        self.swaps += 1
        self._m_swaps.inc()
        self._m_swap_ms.set(self.last_swap_ms)
        return True

    def _adopt(self, frame: PublishedFrame) -> Optional[Any]:
        """Host→device copy of a frame, priced BACKGROUND, generation
        re-checked after the bytes left the views."""
        import jax

        by_path: Dict[str, List[ShardRecord]] = {}
        for r in frame.records:
            by_path.setdefault(r.path, []).append(r)
        prefix = self.param_prefix

        def read_records(path: str) -> List[ShardRecord]:
            return by_path.get(prefix + path, by_path.get(path, []))

        nbytes = sum(r.data.nbytes for r in frame.records)
        t0 = time.perf_counter()
        # ignore_window: the swap runs in exactly the inter-step gaps
        # the window gate reserves, and it must finish before the views
        # rot — it still queues BACKGROUND behind every training
        # transfer contending for the rail
        with self._stream.transfer(max(nbytes, 1), ignore_window=True):
            params = restore_state(self.params_template, read_records)
            jax.block_until_ready(params)
        self.last_swap_ms = (time.perf_counter() - t0) * 1e3
        # the views fed restore_state's host packing; a commit during
        # that window may have torn them — seqlock re-check decides
        if not self.subscriber.frame_is_current(frame):
            logger.warning(
                f"serving: commit raced the swap copy of step "
                f"{frame.step}; dropping the torn params"
            )
            return None
        return params

    def _fold_subscriber_counters(self) -> None:
        """Fold the subscriber's retry counts into the counters by
        delta, so repeated polls never double-count."""
        sub = self.subscriber
        if sub.crc_retries > self._exported_crc:
            self._m_crc.inc(sub.crc_retries - self._exported_crc)
            self._exported_crc = sub.crc_retries
        if sub.torn_retries > self._exported_torn:
            self._m_torn.inc(sub.torn_retries - self._exported_torn)
            self._exported_torn = sub.torn_retries

    def staleness_steps(self) -> int:
        """How many steps the serving weights lag the newest commit."""
        try:
            meta = self.subscriber.handler.metadata()
        except Exception:
            return 0
        if not meta.get("valid") or self.weight_step < 0:
            return 0
        return max(0, int(meta.get("step", 0)) - self.weight_step)

    # -- decoding -------------------------------------------------------
    def _wait_for_gap(self) -> bool:
        """Block until the trainer is between compute spans (or the
        wait times out). Returns True when a genuine gap was found."""
        if self.serving.soak != "idle_gaps":
            return True
        arb = transfer_sched.get_arbiter()
        deadline = time.monotonic() + self.serving.gap_wait_timeout_s
        while arb.in_compute_window():
            if time.monotonic() >= deadline:
                return False
            time.sleep(self.serving.gap_poll_interval_s)
        return True

    def serve_batch(self, prompts, prompt_lens, key):
        """Decode one continuous batch under the current weights.

        Returns ``(tokens, logps, out_lens)`` exactly as
        ``continuous_generate`` does. Weight identity is frozen for the
        whole call — swaps happen only via ``try_swap`` between
        batches."""
        import jax

        from dlrover_tpu.rl.continuous_batching import continuous_generate

        if self.params is None:
            raise RuntimeError(
                "serving engine holds no weights yet — call try_swap() "
                "after the first commit"
            )
        s = self.serving
        if not self._wait_for_gap():
            self.forced_batches += 1
            self._m_forced.inc()
        self._m_staleness.set(float(self.staleness_steps()))
        t0 = time.perf_counter()
        goodput.note_serving(True)
        try:
            tokens, logps, out_lens = continuous_generate(
                self.params,
                prompts,
                prompt_lens,
                key,
                self.cfg,
                max_new_tokens=s.max_new_tokens,
                eos_id=s.eos_id,
                slots=s.slots,
                temperature=s.temperature,
                greedy=s.greedy,
                top_k=s.top_k,
                top_p=s.top_p,
                mesh=self.mesh,
            )
            jax.block_until_ready(out_lens)
        finally:
            goodput.note_serving(False)
        dt = time.perf_counter() - t0
        new_tokens = int(
            np.sum(
                np.maximum(
                    np.asarray(out_lens) - np.asarray(prompt_lens), 0
                )
            )
        )
        self._m_tokens.inc(new_tokens)
        self._m_batches.inc()
        if dt > 0:
            self._m_tokens_per_s.set(new_tokens / dt)
        return tokens, logps, out_lens

    # -- sparse features ------------------------------------------------
    def embedding_probe(self, table, ids):
        """Serving-side sparse gather: the read-only probe. Never
        admits rows to the trainer's hot tier, never touches recency or
        pins — serving traffic cannot evict what training needs."""
        rows = table.gather(ids, insert_missing=False)
        self._m_probe_rows.inc(int(np.asarray(ids).size))
        return rows

    def stats(self) -> Dict[str, float]:
        """Engine-side counters for bench legs and tests."""
        return {
            "weight_step": self.weight_step,
            "swaps": self.swaps,
            "dropped_swaps": self.dropped_swaps,
            "forced_batches": self.forced_batches,
            "last_swap_ms": round(self.last_swap_ms, 3),
            "crc_retries": self.subscriber.crc_retries,
            "torn_retries": self.subscriber.torn_retries,
            "staleness_steps": self.staleness_steps(),
        }
