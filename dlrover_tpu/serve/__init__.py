"""Serve-while-training: a co-located inference plane over the shm
checkpoint publication.

The trainer already leaves two assets on the table every step: a
crc-checksummed, consistent copy of the params in shared memory
(refreshed at every ``commit_save``) and idle host/device windows
between compute spans. This package monetizes both — a
``ShmSubscriber`` (ckpt/shm_handler.py) follows commits zero-copy and
seqlock-safe, and :class:`ServingEngine` decodes continuous batches
over the subscribed weights, swapping to step N+k between batches
(never mid-sequence), with host transfers priced at the arbiter's
``Priority.BACKGROUND`` and wall time booked to the goodput ledger's
``serving_soak`` row. The perf headline it exists to measure: tokens/s
served per % of training step time lost.
"""

from dlrover_tpu.ckpt.shm_handler import (  # noqa: F401
    PublishedFrame,
    ShmCrcError,
    ShmSubscriber,
)
from dlrover_tpu.serve.engine import (  # noqa: F401
    METRIC_PREFIX,
    ServingConfig,
    ServingEngine,
)
