"""Ray platform layer (parity: dlrover/python/master/scaler/ray_scaler.py:134,
watcher/ray_watcher.py, client/platform/ray/ray_job_submitter.py).

Same shape as the k8s layer: a narrow ``RayApi`` seam (real SDK gated on
``import ray``; in-memory fake for tests/simulation), a Scaler, a
watcher, and a job submitter. On TPU, Ray actors map to per-host agent
processes exactly like pods do.
"""

from dlrover_tpu.ray.platform import (  # noqa: F401
    FakeRayApi,
    RayActorScaler,
    RayApi,
    RayJobSubmitter,
    RayWatcher,
)
