"""Ray scaler/watcher/submitter over a narrow API seam.

Parity: the reference's ray path — ``ActorScaler``
(master/scaler/ray_scaler.py:134) converges scale plans into named
actors, ``ActorWatcher`` polls actor states into node events, and
``RayJobSubmitter`` (client/platform/ray/ray_job_submitter.py) submits
the whole job. The SDK never appears outside ``RealRayApi`` so the
control logic tests against ``FakeRayApi`` (the reference mocks ray the
same way) and the master can be built rayless.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeEventType, NodeStatus
from dlrover_tpu.common.daemon import PollingDaemon
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.job_manager import JobManager, NodeEvent
from dlrover_tpu.master.scaler import ScalePlan, Scaler


def actor_name(job: str, node: Node) -> str:
    return f"{job}-{node.type}-{node.id}"


class RayApi:
    """What the control plane needs from a Ray cluster."""

    def create_actor(self, name: str, spec: dict) -> None:
        raise NotImplementedError

    def remove_actor(self, name: str) -> bool:
        raise NotImplementedError

    def list_actors(self, job: str) -> Dict[str, str]:
        """{actor_name: state} — state in ALIVE/PENDING/DEAD."""
        raise NotImplementedError

    def submit_job(self, entrypoint: str, runtime_env: dict) -> str:
        raise NotImplementedError


class RealRayApi(RayApi):  # pragma: no cover - needs a ray cluster
    def __init__(self, address: str = "auto"):
        try:
            import ray
        except ImportError as e:
            raise ImportError(
                "the 'ray' package is required for the ray platform"
            ) from e
        self._ray = ray
        ray.init(address=address, ignore_reinit_error=True)

    def create_actor(self, name: str, spec: dict) -> None:
        import subprocess

        @self._ray.remote(num_cpus=spec.get("num_cpus", 1))
        class _Agent:
            def run(self, cmd):
                return subprocess.run(cmd).returncode

        actor = _Agent.options(name=name, lifetime="detached").remote()
        actor.run.remote(spec["cmd"])

    def remove_actor(self, name: str) -> bool:
        try:
            self._ray.kill(self._ray.get_actor(name))
            return True
        except ValueError:
            return False

    def list_actors(self, job: str) -> Dict[str, str]:
        from ray.util.state import list_actors

        return {
            a.name: a.state
            for a in list_actors()
            if a.name and a.name.startswith(f"{job}-")
        }

    def submit_job(self, entrypoint: str, runtime_env: dict) -> str:
        from ray.job_submission import JobSubmissionClient

        client = JobSubmissionClient()
        return client.submit_job(
            entrypoint=entrypoint, runtime_env=runtime_env
        )


class FakeRayApi(RayApi):
    """In-memory cluster double (reference pattern: mocked ray)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.actors: Dict[str, dict] = {}
        self.states: Dict[str, str] = {}
        self.submitted: List[dict] = []

    def create_actor(self, name, spec):
        with self._lock:
            self.actors[name] = spec
            self.states[name] = "PENDING"

    def remove_actor(self, name):
        with self._lock:
            self.states.pop(name, None)
            return self.actors.pop(name, None) is not None

    def list_actors(self, job):
        with self._lock:
            return {
                n: s
                for n, s in self.states.items()
                if n.startswith(f"{job}-")
            }

    def set_state(self, name, state):
        with self._lock:
            if name in self.states:
                self.states[name] = state

    def submit_job(self, entrypoint, runtime_env):
        with self._lock:
            self.submitted.append(
                {"entrypoint": entrypoint, "runtime_env": runtime_env}
            )
            return f"raysubmit_{len(self.submitted)}"


class RayActorScaler(Scaler):
    """ScalePlan → named detached actors running the launcher
    (parity: ray_scaler.py:134)."""

    def __init__(
        self,
        api: RayApi,
        job_name: str,
        training_cmd: Optional[List[str]] = None,
        master_addr: str = "",
        nproc_per_node: int = 1,
        num_cpus: int = 1,
    ):
        self._api = api
        self._job = job_name
        # training script + args — the launcher's required positional;
        # without it every actor would die on argparse at startup
        self._training_cmd = training_cmd or []
        self._master_addr = master_addr
        self._nproc = nproc_per_node
        self._num_cpus = num_cpus

    def set_master_addr(self, addr: str):
        self._master_addr = addr

    def scale(self, plan: ScalePlan) -> None:
        for node in plan.remove_nodes:
            self._api.remove_actor(actor_name(self._job, node))
        for node in plan.launch_nodes:
            name = actor_name(self._job, node)
            cmd = [
                "python",
                "-m",
                "dlrover_tpu.trainer.run",
                f"--master-addr={self._master_addr}",
                f"--node-rank={node.rank_index}",
                f"--nproc-per-node={self._nproc}",
                *self._training_cmd,
            ]
            logger.info(f"ray scaler creating actor {name}")
            self._api.create_actor(
                name, {"cmd": cmd, "num_cpus": self._num_cpus}
            )


_STATE_MAP = {
    "PENDING": NodeStatus.PENDING,
    "ALIVE": NodeStatus.RUNNING,
    "RESTARTING": NodeStatus.PENDING,
    "DEAD": NodeStatus.FAILED,
}


class RayWatcher(PollingDaemon):
    """Actor states → NodeEvents (parity: ray_watcher.py)."""

    def __init__(
        self,
        api: RayApi,
        job_manager: JobManager,
        job_name: str,
        interval: float = 5.0,
    ):
        super().__init__("ray-watcher", interval)
        self._api = api
        self._job_manager = job_manager
        self._job = job_name
        self._last: Dict[str, str] = {}

    def _tick(self):
        states = self._api.list_actors(self._job)
        for name, state in states.items():
            status = _STATE_MAP.get(state, NodeStatus.PENDING)
            if self._last.get(name) == status:
                continue
            event = (
                NodeEventType.ADDED
                if name not in self._last
                else NodeEventType.MODIFIED
            )
            self._last[name] = status
            try:
                node_type, node_id = name[len(self._job) + 1 :].rsplit(
                    "-", 1
                )
                node = Node(node_type=node_type, node_id=int(node_id))
            except ValueError:
                continue
            node.status = status
            self._job_manager.process_event(NodeEvent(event, node))


class RayJobSubmitter:
    """Submit a whole dlrover-tpu job to a Ray cluster (parity:
    ray_job_submitter.py)."""

    def __init__(self, api: RayApi):
        self._api = api

    def submit(
        self,
        training_script: str,
        num_nodes: int,
        nproc_per_node: int = 1,
        script_args: Optional[List[str]] = None,
        working_dir: str = ".",
    ) -> str:
        import shlex

        # the entrypoint is executed by a shell: quote everything so
        # spaces/metacharacters in script paths or args survive intact
        parts = [
            "python", "-m", "dlrover_tpu.trainer.run",
            f"--nnodes={num_nodes}",
            f"--nproc-per-node={nproc_per_node}",
            training_script,
            *(script_args or []),
        ]
        entrypoint = shlex.join(parts)
        return self._api.submit_job(
            entrypoint, {"working_dir": working_dir}
        )
