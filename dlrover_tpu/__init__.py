"""dlrover-tpu: a TPU-native elastic training framework.

A ground-up JAX/XLA re-design of the capabilities of DLRover (elastic job
master, per-host elastic agent, flash checkpoint, dynamic data sharding,
network health checks) and ATorch (auto_accelerate strategy search, TP/SP/EP
modules, AGD/WSAM optimizers, flash-attention kernels) for TPU pods:

- parallelism is expressed as a ``jax.sharding.Mesh`` plus named sharding
  rules compiled by GSPMD, not explicit process groups;
- collectives ride ICI/DCN via XLA (``psum``/``all_gather``/``ppermute``),
  not NCCL;
- hot kernels (flash attention, quantized optimizer math) are Pallas;
- the elastic control plane (master, agent, rendezvous, checkpoints) is the
  part XLA does not give you, and is built here natively.
"""

__version__ = "0.1.0"
