"""8-bit (blockwise-quantized state) AdamW for TPU.

Parity: ATorch's low-bit optimizer — python driver
atorch/atorch/optimizers/low_bit/functional.py (vectorwise/blockwise
quantization, linear + nonlinear qmaps) backed by the CUDA kernels in
atorch/atorch/ops/csrc/{quantize.cu,dequantize.cu,quantization_optimizer.cu}.

TPU-native design: optimizer moments are stored as int8 codes + one f32
scale per 128-element block. The hot path (dequantize -> Adam moment
update -> requantize -> parameter delta) is a single fused Pallas kernel
— one HBM read of (g, codes, scales) and one write of (codes', scales',
update), the same memory-traffic win the reference's fused CUDA kernel
gets. Block size 128 = one VPU lane row, so per-block reductions
(max|m|) are single-row reductions with no cross-lane shuffles.

Quantization is *linear* blockwise (codes = round(x/scale * 127)): on
TPU a nonlinear 256-entry codebook lookup per element (the reference's
dynamic map) would serialize into gathers; linear keeps the whole update
elementwise on the VPU. The f32 scale per 128 values bounds relative
error to ~0.4% of the block max, and Adam's moments are smooth enough
that this matches fp32 training loss in the tests.

The same math runs as plain jnp off-TPU (``use_pallas=False`` or CPU
backend), so numerics are identical across paths.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 128  # quantization block = one VPU lane row
_ROWS = 256  # rows per pallas grid step (256*128 elems/step)


@jax.tree_util.register_pytree_node_class
class Quantized8:
    """Blockwise linearly quantized tensor: ``x ~ codes * scales / qmax``.

    ``codes``/``scales`` are pytree children; ``shape``/``signed`` are
    static aux data so jit never traces them.
    """

    def __init__(self, codes, scales, shape, signed):
        self.codes = codes  # int8 [nblocks, BLOCK]
        self.scales = scales  # f32 [nblocks, 1]
        self.shape = tuple(shape)
        self.signed = bool(signed)

    def tree_flatten(self):
        return (self.codes, self.scales), (self.shape, self.signed)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def __repr__(self):
        return (
            f"Quantized8(shape={self.shape}, signed={self.signed}, "
            f"nblocks={self.codes.shape[0]})"
        )


def _to_blocks(x):
    flat = x.reshape(-1)
    pad = (-flat.size) % BLOCK
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, BLOCK)


def _from_blocks(blocks, shape):
    n = 1
    for d in shape:
        n *= d
    return blocks.reshape(-1)[:n].reshape(shape)


def _sqrt_map_quant(x, signed, qmax):
    """Shared sqrt-map core: x [rows, N] f32 → (float codes in
    [-qmax, qmax] or [0, qmax], scales [rows, 1]).

    Power-2 ("sqrt") map, the reference's ``power-2`` qmap
    (low_bit/functional.py:531 ``create_pow_map``): normalize to the block
    max, code = round(sign(y)*sqrt(|y|)*qmax). The sqrt spreads codes
    toward zero, so the smallest representable nonzero value is
    scale/qmax^2 instead of scale/qmax — without it Adam's second moment
    underflows to 0 for small-magnitude coordinates and the update blows
    up through the eps denominator. Purely elementwise (no codebook
    gather), so it stays on the VPU.
    """
    if signed:
        scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    else:
        scale = jnp.max(x, axis=-1, keepdims=True)
    safe = jnp.maximum(scale, 1e-30)
    y = x / safe
    codes = jnp.round(jnp.sign(y) * jnp.sqrt(jnp.abs(y)) * qmax)
    lo = -float(qmax) if signed else 0.0
    return jnp.clip(codes, lo, float(qmax)), scale


def _sqrt_map_dequant(codes_f, scales, qmax):
    c = codes_f / qmax
    return jnp.sign(c) * c * c * scales


def _quant_block_math(x, signed):
    codes, scale = _sqrt_map_quant(x, signed, 127.0)
    return codes.astype(jnp.int8), scale


def _dequant_block_math(codes, scales):
    return _sqrt_map_dequant(codes.astype(jnp.float32), scales, 127.0)


def quantize_8bit(x, signed: bool = True) -> Quantized8:
    codes, scales = _quant_block_math(
        _to_blocks(x.astype(jnp.float32)), signed
    )
    return Quantized8(codes, scales, tuple(x.shape), signed)


def dequantize_8bit(q: Quantized8):
    return _from_blocks(_dequant_block_math(q.codes, q.scales), q.shape)


# ---------------------------------------------------------------------------
# fused 8-bit adam update
# ---------------------------------------------------------------------------
def _adam8_block_math(g, m, v, lr, b1, b2, eps, bc1, bc2):
    """Shared fp32 math: returns (m_new, v_new, delta). All [rows, BLOCK]."""
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    m_hat = m_new / bc1
    v_hat = v_new / bc2
    delta = -lr * m_hat / (jnp.sqrt(v_hat) + eps)
    return m_new, v_new, delta


def _adam8_kernel(
    scalar_ref,  # SMEM [4]: lr, bc1, bc2, eps  (f32)
    g_ref,  # [R, BLOCK] f32
    mc_ref,  # [R, BLOCK] i8
    ms_ref,  # [R, 1] f32
    vc_ref,  # [R, BLOCK] i8
    vs_ref,  # [R, 1] f32
    mc_out,
    ms_out,
    vc_out,
    vs_out,
    delta_out,  # [R, BLOCK] f32
    *,
    b1: float,
    b2: float,
):
    lr, bc1, bc2, eps = (
        scalar_ref[0],
        scalar_ref[1],
        scalar_ref[2],
        scalar_ref[3],
    )
    g = g_ref[:].astype(jnp.float32)
    m = _dequant_block_math(mc_ref[:], ms_ref[:])
    v = _dequant_block_math(vc_ref[:], vs_ref[:])
    m_new, v_new, delta = _adam8_block_math(
        g, m, v, lr, b1, b2, eps, bc1, bc2
    )
    mc, ms = _quant_block_math(m_new, signed=True)
    vc, vs = _quant_block_math(v_new, signed=False)
    mc_out[:] = mc
    ms_out[:] = ms
    vc_out[:] = vc
    vs_out[:] = vs
    delta_out[:] = delta


def _adam8_update_pallas(g_blocks, mq, vq, scalars, b1, b2, interpret):
    rows = g_blocks.shape[0]
    r = min(_ROWS, rows)
    if rows % r:
        # pad rows to the grid chunk; padded rows carry zeros
        pad = (-rows) % r
        g_blocks = jnp.pad(g_blocks, ((0, pad), (0, 0)))
        mq = Quantized8(
            jnp.pad(mq.codes, ((0, pad), (0, 0))),
            jnp.pad(mq.scales, ((0, pad), (0, 0))),
            mq.shape,
            mq.signed,
        )
        vq = Quantized8(
            jnp.pad(vq.codes, ((0, pad), (0, 0))),
            jnp.pad(vq.scales, ((0, pad), (0, 0))),
            vq.shape,
            vq.signed,
        )
    nrows = g_blocks.shape[0]
    grid = (nrows // r,)
    row_spec = pl.BlockSpec((r, BLOCK), lambda i: (i, 0))
    scale_spec = pl.BlockSpec((r, 1), lambda i: (i, 0))
    outs = pl.pallas_call(
        functools.partial(_adam8_kernel, b1=b1, b2=b2),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            row_spec,
            row_spec,
            scale_spec,
            row_spec,
            scale_spec,
        ],
        out_specs=[row_spec, scale_spec, row_spec, scale_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((nrows, 1), jnp.float32),
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((nrows, 1), jnp.float32),
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.float32),
        ],
        interpret=interpret,
    )(scalars, g_blocks, mq.codes, mq.scales, vq.codes, vq.scales)
    mc, ms, vc, vs, delta = outs
    return (
        Quantized8(mc[:rows], ms[:rows], mq.shape, True),
        Quantized8(vc[:rows], vs[:rows], vq.shape, False),
        delta[:rows],
    )


def _adam8_update_jnp(g_blocks, mq, vq, scalars, b1, b2):
    lr, bc1, bc2, eps = scalars[0], scalars[1], scalars[2], scalars[3]
    m = _dequant_block_math(mq.codes, mq.scales)
    v = _dequant_block_math(vq.codes, vq.scales)
    m_new, v_new, delta = _adam8_block_math(
        g_blocks, m, v, lr, b1, b2, eps, bc1, bc2
    )
    mc, ms = _quant_block_math(m_new, signed=True)
    vc, vs = _quant_block_math(v_new, signed=False)
    return (
        Quantized8(mc, ms, mq.shape, True),
        Quantized8(vc, vs, vq.shape, False),
        delta,
    )


# ---------------------------------------------------------------------------
# 4-bit (nibble-packed) state
# ---------------------------------------------------------------------------
@jax.tree_util.register_pytree_node_class
class Quantized4:
    """Blockwise sqrt-map quantized tensor at 4 bits: two codes per
    byte (the platform's int4 dtype is not usable here, so packing is
    explicit). Signed codes live in [-7,7] stored as code+8; unsigned
    in [0,15]. 8x less HBM than fp32 state."""

    def __init__(self, packed, scales, shape, signed):
        self.packed = packed  # uint8 [nblocks, BLOCK//2]
        self.scales = scales  # f32 [nblocks, 1]
        self.shape = tuple(shape)
        self.signed = bool(signed)

    def tree_flatten(self):
        return (self.packed, self.scales), (self.shape, self.signed)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def __repr__(self):
        return (
            f"Quantized4(shape={self.shape}, signed={self.signed}, "
            f"nblocks={self.packed.shape[0]})"
        )


def _quant_block_math4(x, signed):
    """x: [rows, BLOCK] f32 → (uint8 packed [rows, BLOCK//2], scales).
    Same sqrt map as 8-bit at qmax 7 (signed, stored +8) / 15
    (unsigned); only the nibble packing is 4-bit-specific."""
    qmax = 7.0 if signed else 15.0
    c, scale = _sqrt_map_quant(x, signed, qmax)
    if signed:
        c = c + 8.0  # [1, 15]
    packed_src = c.astype(jnp.uint8)
    packed = packed_src[:, 0::2] | (packed_src[:, 1::2] << 4)
    return packed, scale


def _dequant_block_math4(packed, scales, signed):
    lo = packed & 0xF
    hi = (packed >> 4) & 0xF
    # interleave back to [rows, BLOCK]
    c = jnp.stack([lo, hi], axis=-1).reshape(packed.shape[0], -1)
    c = c.astype(jnp.float32)
    if signed:
        return _sqrt_map_dequant(c - 8.0, scales, 7.0)
    return _sqrt_map_dequant(c, scales, 15.0)


def quantize_4bit(x, signed: bool = True) -> Quantized4:
    packed, scales = _quant_block_math4(
        _to_blocks(x.astype(jnp.float32)), signed
    )
    return Quantized4(packed, scales, tuple(x.shape), signed)


def dequantize_4bit(q: Quantized4):
    return _from_blocks(
        _dequant_block_math4(q.packed, q.scales, q.signed), q.shape
    )


def _adam4_update_jnp(g_blocks, mq, vq, scalars, b1, b2):
    """4-bit first moment, 8-bit second moment. Requantizing v at 4
    bits makes Adam's effective per-coordinate LR noisy enough to stall
    convergence (measured: 3x worse terminal loss on a quadratic);
    the first moment tolerates 4 bits fine — same conclusion as the
    4-bit-optimizer literature, which spends its complexity (rank-1
    factorized scaling) exactly on the second moment."""
    lr, bc1, bc2, eps = scalars[0], scalars[1], scalars[2], scalars[3]
    m = _dequant_block_math4(mq.packed, mq.scales, True)
    v = _dequant_block_math(vq.codes, vq.scales)
    m_new, v_new, delta = _adam8_block_math(
        g_blocks, m, v, lr, b1, b2, eps, bc1, bc2
    )
    mp, ms = _quant_block_math4(m_new, signed=True)
    vc, vs = _quant_block_math(v_new, signed=False)
    return (
        Quantized4(mp, ms, mq.shape, True),
        Quantized8(vc, vs, vq.shape, False),
        delta,
    )


class Adam8State(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates  # pytree of Quantized8
    nu: optax.Updates  # pytree of Quantized8


def adamw_8bit(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    min_quantized_size: int = 4096,
    use_pallas: bool | None = None,
    bits: int = 8,
) -> optax.GradientTransformation:
    """AdamW whose moments live in int8 (4x less optimizer-state HBM
    than fp32 Adam) or, with ``bits=4``, a nibble-packed first moment +
    int8 second moment (1.5 B/param, ~5.3x less) — the FSDP/ZeRO memory
    ceiling on big models. Parity: the reference ships both 4- and
    8-bit variants (low_bit/functional.py).

    Tensors smaller than ``min_quantized_size`` keep fp32 moments (the
    reference does the same for small params, where block stats are
    noisy and savings negligible). The fused Pallas kernel covers the
    8-bit path; the 4-bit path (nibble-packed first moment + int8
    second moment, 1.5 B/param state) runs the jnp math — XLA fuses the
    unpack→update→repack chain, and the platform's int4 dtype is not
    usable.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    # bits=4 packs the FIRST moment into nibbles; the second moment
    # stays int8 (see _adam4_update_jnp) → 1.5 bytes/param of state
    quantize_m = quantize_8bit if bits == 8 else quantize_4bit
    quantize_v = quantize_8bit

    def _pallas_enabled():
        if bits != 8:
            return False
        if use_pallas is not None:
            return use_pallas
        return jax.default_backend() == "tpu"

    def init_fn(params):
        def _init_m(p):
            if p.size < min_quantized_size:
                return jnp.zeros_like(p, jnp.float32)
            return quantize_m(jnp.zeros_like(p, jnp.float32), True)

        def _init_v(p):
            if p.size < min_quantized_size:
                return jnp.zeros_like(p, jnp.float32)
            return quantize_v(jnp.zeros_like(p, jnp.float32), False)

        return Adam8State(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(_init_m, params),
            nu=jax.tree.map(_init_v, params),
        )

    def update_fn(grads, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1**cf
        bc2 = 1.0 - b2**cf
        scalars = jnp.stack(
            [jnp.asarray(learning_rate, jnp.float32), bc1, bc2, eps]
        )

        def _one(g, m, v):
            if not isinstance(m, (Quantized8, Quantized4)):
                # small tensor: plain fp32 adam
                m_new = b1 * m + (1.0 - b1) * g
                v_new = b2 * v + (1.0 - b2) * g * g
                delta = (
                    -learning_rate
                    * (m_new / bc1)
                    / (jnp.sqrt(v_new / bc2) + eps)
                )
                return delta.astype(g.dtype), m_new, v_new
            g_blocks = _to_blocks(g.astype(jnp.float32))
            if isinstance(m, Quantized4):
                mq, vq, delta = _adam4_update_jnp(
                    g_blocks, m, v, scalars, b1, b2
                )
            elif _pallas_enabled():
                mq, vq, delta = _adam8_update_pallas(
                    g_blocks, m, v, scalars, b1, b2, interpret=False
                )
            else:
                mq, vq, delta = _adam8_update_jnp(
                    g_blocks, m, v, scalars, b1, b2
                )
            return _from_blocks(delta, g.shape).astype(g.dtype), mq, vq

        flat_g, treedef = jax.tree.flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        results = [
            _one(g, m, v) for g, m, v in zip(flat_g, flat_m, flat_v)
        ]
        updates = treedef.unflatten([r[0] for r in results])
        mu = treedef.unflatten([r[1] for r in results])
        nu = treedef.unflatten([r[2] for r in results])

        if weight_decay and params is not None:
            updates = jax.tree.map(
                lambda u, p: u - learning_rate * weight_decay * p,
                updates,
                params,
            )
        return updates, Adam8State(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def adamw_4bit(**kwargs) -> optax.GradientTransformation:
    """"4-bit" AdamW (nibble-packed first moment + int8 second moment):
    1.5 B/param of optimizer state vs 8 for fp32 Adam. Parity: the
    reference's 4-bit low-bit optimizer (which spends rank-1 factorized
    scaling on the second moment; here it keeps 8 bits instead — same
    memory class, far simpler, and it tracks fp32 trajectories in
    tests)."""
    return adamw_8bit(bits=4, **kwargs)
