"""8-bit (blockwise-quantized state) AdamW for TPU.

Parity: ATorch's low-bit optimizer — python driver
atorch/atorch/optimizers/low_bit/functional.py (vectorwise/blockwise
quantization, linear + nonlinear qmaps) backed by the CUDA kernels in
atorch/atorch/ops/csrc/{quantize.cu,dequantize.cu,quantization_optimizer.cu}.

TPU-native design: optimizer moments are stored as int8 codes + one f32
scale per 128-element block. The hot path (dequantize -> Adam moment
update -> requantize -> parameter delta) is a single fused Pallas kernel
— one HBM read of (g, codes, scales) and one write of (codes', scales',
update), the same memory-traffic win the reference's fused CUDA kernel
gets. Block size 128 = one VPU lane row, so per-block reductions
(max|m|) are single-row reductions with no cross-lane shuffles.

Quantization is *linear* blockwise (codes = round(x/scale * 127)): on
TPU a nonlinear 256-entry codebook lookup per element (the reference's
dynamic map) would serialize into gathers; linear keeps the whole update
elementwise on the VPU. The f32 scale per 128 values bounds relative
error to ~0.4% of the block max, and Adam's moments are smooth enough
that this matches fp32 training loss in the tests.

The same math runs as plain jnp off-TPU (``use_pallas=False`` or CPU
backend), so numerics are identical across paths.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 128  # quantization block = one VPU lane row
_ROWS = 256  # rows per pallas grid step (256*128 elems/step), tree form
# rows per grid step for the FLAT path. The per-grid-step overhead is
# ~3.6 us (measured: both the tree form and a 256-row flat form sit at
# ~47k total steps for 1.5B params and ~170 ms — step-bound, not
# HBM-bound). 2048*128 = 262k elems/step cuts the step count 8x and
# puts the pass back on the HBM roofline. VMEM at 2048 rows: ~4.5 MB
# of tiles + f32 intermediates, inside the ~16 MB budget.
_FLAT_ROWS = 2048


@jax.tree_util.register_pytree_node_class
class Quantized8:
    """Blockwise linearly quantized tensor: ``x ~ codes * scales / qmax``.

    ``codes``/``scales`` are pytree children; ``shape``/``signed`` are
    static aux data so jit never traces them.
    """

    def __init__(self, codes, scales, shape, signed):
        self.codes = codes  # int8 [nblocks, BLOCK]
        self.scales = scales  # f32 [nblocks, 1]
        self.shape = tuple(shape)
        self.signed = bool(signed)

    def tree_flatten(self):
        return (self.codes, self.scales), (self.shape, self.signed)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def __repr__(self):
        return (
            f"Quantized8(shape={self.shape}, signed={self.signed}, "
            f"nblocks={self.codes.shape[0]})"
        )


def _to_blocks(x):
    flat = x.reshape(-1)
    pad = (-flat.size) % BLOCK
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, BLOCK)


def _from_blocks(blocks, shape):
    n = 1
    for d in shape:
        n *= d
    return blocks.reshape(-1)[:n].reshape(shape)


def _sqrt_map_quant(x, signed, qmax):
    """Shared sqrt-map core: x [rows, N] f32 → (float codes in
    [-qmax, qmax] or [0, qmax], scales [rows, 1]).

    Power-2 ("sqrt") map, the reference's ``power-2`` qmap
    (low_bit/functional.py:531 ``create_pow_map``): normalize to the block
    max, code = round(sign(y)*sqrt(|y|)*qmax). The sqrt spreads codes
    toward zero, so the smallest representable nonzero value is
    scale/qmax^2 instead of scale/qmax — without it Adam's second moment
    underflows to 0 for small-magnitude coordinates and the update blows
    up through the eps denominator. Purely elementwise (no codebook
    gather), so it stays on the VPU.
    """
    if signed:
        scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    else:
        scale = jnp.max(x, axis=-1, keepdims=True)
    safe = jnp.maximum(scale, 1e-30)
    y = x / safe
    codes = jnp.round(jnp.sign(y) * jnp.sqrt(jnp.abs(y)) * qmax)
    lo = -float(qmax) if signed else 0.0
    return jnp.clip(codes, lo, float(qmax)), scale


def _sqrt_map_dequant(codes_f, scales, qmax):
    c = codes_f / qmax
    return jnp.sign(c) * c * c * scales


def _quant_block_math(x, signed):
    codes, scale = _sqrt_map_quant(x, signed, 127.0)
    return codes.astype(jnp.int8), scale


def _dequant_block_math(codes, scales):
    return _sqrt_map_dequant(codes.astype(jnp.float32), scales, 127.0)


# -- "wide" scale layout (the FLAT path) -------------------------------------
# A [nblocks, 1] f32 scale tensor is XLA-tile-padded to 128 lanes at
# rest — a 128x memory blowup (measured: 1.83 GB instead of 15 MB per
# moment at 1.5B params, enough to OOM the one-jit update). The flat
# path stores scales DENSE as [nblocks//128, 128]: scale of codes row
# r lives at [r//128, r%128]. The (R,128)->(R//128,128,128) reshapes
# below split only the sublane dim — free in VMEM.
def _quant_block_math_wide(x, signed):
    R = x.shape[0]
    x3 = x.reshape(R // 128, 128, 128)
    s = jnp.max(jnp.abs(x3) if signed else x3, axis=-1)  # [R//128, 128]
    safe = jnp.maximum(s, 1e-30)
    y = x3 / safe[:, :, None]
    codes = jnp.round(jnp.sign(y) * jnp.sqrt(jnp.abs(y)) * 127.0)
    lo = -127.0 if signed else 0.0
    codes = jnp.clip(codes, lo, 127.0).reshape(R, BLOCK)
    return codes.astype(jnp.int8), s


def _dequant_block_math_wide(codes, s2d):
    R = codes.shape[0]
    c = codes.astype(jnp.float32) / 127.0
    y = jnp.sign(c) * c * c
    y3 = y.reshape(R // 128, 128, 128)
    return (y3 * s2d[:, :, None]).reshape(R, BLOCK)


def quantize_8bit(x, signed: bool = True) -> Quantized8:
    codes, scales = _quant_block_math(
        _to_blocks(x.astype(jnp.float32)), signed
    )
    return Quantized8(codes, scales, tuple(x.shape), signed)


def dequantize_8bit(q: Quantized8):
    return _from_blocks(_dequant_block_math(q.codes, q.scales), q.shape)


# ---------------------------------------------------------------------------
# fused 8-bit adam update
# ---------------------------------------------------------------------------
def _adam8_block_math(
    g, m, v, lrA, invbc2, eps, b1, b2, classic_eps: bool = True
):
    """Shared fp32 math: returns (m_new, v_new, delta). All [rows, BLOCK].

    Written for the VPU hot path (the 1.5B kernel measured COMPUTE-
    bound, not HBM-bound): the bias corrections arrive premultiplied
    (``lrA = lr/bc1``, ``invbc2 = 1/bc2`` — scalars, computed once per
    update). ``classic_eps`` is a STATIC switch for where the traced
    ``eps`` scalar sits: True = outside the sqrt (the Adam paper form,
    the public default — exact 1/(sqrt+eps) via the rsqrt identity),
    False = inside (adafactor/optax ``eps_root`` convention, one rsqrt
    and no divide — the fastest form, selectable via the optimizers'
    ``eps_root`` argument)."""
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    if classic_eps:
        # the straightforward form: sqrt+divide is safe at v == 0
        # (rsqrt identities NaN there), and the kernel is measured
        # structure-bound, not VPU-bound, so the extra op is free
        delta = -lrA * m_new / (jnp.sqrt(v_new * invbc2) + eps)
    else:
        delta = -lrA * m_new * lax.rsqrt(v_new * invbc2 + eps)
    return m_new, v_new, delta


def _adam8_kernel(
    scalar_ref,  # SMEM [3]: lrA (= lr/bc1), invbc2, eps_root  (f32)
    g_ref,  # [R, BLOCK] f32
    mc_ref,  # [R, BLOCK] i8
    ms_ref,  # [R, 1] f32
    vc_ref,  # [R, BLOCK] i8
    vs_ref,  # [R, 1] f32
    mc_out,
    ms_out,
    vc_out,
    vs_out,
    delta_out,  # [R, BLOCK] f32
    *,
    b1: float,
    b2: float,
    classic_eps: bool = True,
):
    lrA, invbc2, eps = (
        scalar_ref[0],
        scalar_ref[1],
        scalar_ref[2],
    )
    g = g_ref[:].astype(jnp.float32)
    m = _dequant_block_math(mc_ref[:], ms_ref[:])
    v = _dequant_block_math(vc_ref[:], vs_ref[:])
    m_new, v_new, delta = _adam8_block_math(
        g, m, v, lrA, invbc2, eps, b1, b2, classic_eps
    )
    mc, ms = _quant_block_math(m_new, signed=True)
    vc, vs = _quant_block_math(v_new, signed=False)
    mc_out[:] = mc
    ms_out[:] = ms
    vc_out[:] = vc
    vs_out[:] = vs
    delta_out[:] = delta.astype(delta_out.dtype)


def _adam8_update_pallas(
    g_blocks, mq, vq, scalars, b1, b2, interpret, classic_eps=True
):
    rows = g_blocks.shape[0]
    r = min(_ROWS, rows)
    if rows % r:
        # pad rows to the grid chunk; padded rows carry zeros
        pad = (-rows) % r
        g_blocks = jnp.pad(g_blocks, ((0, pad), (0, 0)))
        mq = Quantized8(
            jnp.pad(mq.codes, ((0, pad), (0, 0))),
            jnp.pad(mq.scales, ((0, pad), (0, 0))),
            mq.shape,
            mq.signed,
        )
        vq = Quantized8(
            jnp.pad(vq.codes, ((0, pad), (0, 0))),
            jnp.pad(vq.scales, ((0, pad), (0, 0))),
            vq.shape,
            vq.signed,
        )
    nrows = g_blocks.shape[0]
    grid = (nrows // r,)
    row_spec = pl.BlockSpec((r, BLOCK), lambda i: (i, 0))
    scale_spec = pl.BlockSpec((r, 1), lambda i: (i, 0))
    outs = pl.pallas_call(
        functools.partial(
            _adam8_kernel, b1=b1, b2=b2, classic_eps=classic_eps
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            row_spec,
            row_spec,
            scale_spec,
            row_spec,
            scale_spec,
        ],
        out_specs=[row_spec, scale_spec, row_spec, scale_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((nrows, 1), jnp.float32),
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((nrows, 1), jnp.float32),
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.float32),
        ],
        interpret=interpret,
    )(scalars, g_blocks, mq.codes, mq.scales, vq.codes, vq.scales)
    mc, ms, vc, vs, delta = outs
    return (
        Quantized8(mc[:rows], ms[:rows], mq.shape, True),
        Quantized8(vc[:rows], vs[:rows], vq.shape, False),
        delta[:rows],
    )


def _adam8_update_jnp(
    g_blocks, mq, vq, scalars, b1, b2, classic_eps=True
):
    lrA, invbc2, eps = scalars[0], scalars[1], scalars[2]
    wide = mq.scales.shape[-1] == BLOCK  # flat path's dense scale layout
    dequant = _dequant_block_math_wide if wide else _dequant_block_math
    quant = _quant_block_math_wide if wide else _quant_block_math
    m = dequant(mq.codes, mq.scales)
    v = dequant(vq.codes, vq.scales)
    m_new, v_new, delta = _adam8_block_math(
        g_blocks, m, v, lrA, invbc2, eps, b1, b2, classic_eps
    )
    mc, ms = quant(m_new, signed=True)
    vc, vs = quant(v_new, signed=False)
    return (
        Quantized8(mc, ms, mq.shape, True),
        Quantized8(vc, vs, vq.shape, False),
        delta,
    )


# ---------------------------------------------------------------------------
# 4-bit (nibble-packed) state
# ---------------------------------------------------------------------------
@jax.tree_util.register_pytree_node_class
class Quantized4:
    """Blockwise sqrt-map quantized tensor at 4 bits: two codes per
    byte (the platform's int4 dtype is not usable here, so packing is
    explicit). Signed codes live in [-7,7] stored as code+8; unsigned
    in [0,15]. 8x less HBM than fp32 state."""

    def __init__(self, packed, scales, shape, signed):
        self.packed = packed  # uint8 [nblocks, BLOCK//2]
        self.scales = scales  # f32 [nblocks, 1]
        self.shape = tuple(shape)
        self.signed = bool(signed)

    def tree_flatten(self):
        return (self.packed, self.scales), (self.shape, self.signed)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def __repr__(self):
        return (
            f"Quantized4(shape={self.shape}, signed={self.signed}, "
            f"nblocks={self.packed.shape[0]})"
        )


def _quant_block_math4(x, signed):
    """x: [rows, BLOCK] f32 → (uint8 packed [rows, BLOCK//2], scales).
    Same sqrt map as 8-bit at qmax 7 (signed, stored +8) / 15
    (unsigned); only the nibble packing is 4-bit-specific."""
    qmax = 7.0 if signed else 15.0
    c, scale = _sqrt_map_quant(x, signed, qmax)
    if signed:
        c = c + 8.0  # [1, 15]
    packed_src = c.astype(jnp.uint8)
    packed = packed_src[:, 0::2] | (packed_src[:, 1::2] << 4)
    return packed, scale


def _dequant_block_math4(packed, scales, signed):
    lo = packed & 0xF
    hi = (packed >> 4) & 0xF
    # interleave back to [rows, BLOCK]
    c = jnp.stack([lo, hi], axis=-1).reshape(packed.shape[0], -1)
    c = c.astype(jnp.float32)
    if signed:
        return _sqrt_map_dequant(c - 8.0, scales, 7.0)
    return _sqrt_map_dequant(c, scales, 15.0)


def quantize_4bit(x, signed: bool = True) -> Quantized4:
    packed, scales = _quant_block_math4(
        _to_blocks(x.astype(jnp.float32)), signed
    )
    return Quantized4(packed, scales, tuple(x.shape), signed)


def dequantize_4bit(q: Quantized4):
    return _from_blocks(
        _dequant_block_math4(q.packed, q.scales, q.signed), q.shape
    )


def _adam4_update_jnp(
    g_blocks, mq, vq, scalars, b1, b2, classic_eps=True
):
    """4-bit first moment, 8-bit second moment. Requantizing v at 4
    bits makes Adam's effective per-coordinate LR noisy enough to stall
    convergence (measured: 3x worse terminal loss on a quadratic);
    the first moment tolerates 4 bits fine — same conclusion as the
    4-bit-optimizer literature, which spends its complexity (rank-1
    factorized scaling) exactly on the second moment."""
    m = _dequant_block_math4(mq.packed, mq.scales, True)
    v = _dequant_block_math(vq.codes, vq.scales)
    m_new, v_new, delta = _adam8_block_math(
        g_blocks, m, v, scalars[0], scalars[1], scalars[2], b1, b2,
        classic_eps,
    )
    mp, ms = _quant_block_math4(m_new, signed=True)
    vc, vs = _quant_block_math(v_new, signed=False)
    return (
        Quantized4(mp, ms, mq.shape, True),
        Quantized8(vc, vs, vq.shape, False),
        delta,
    )


def _adam8_kernel_wide(
    scalar_ref,  # SMEM [3]: lrA (= lr/bc1), invbc2, eps_root  (f32)
    g_ref,  # [R, BLOCK] any float dtype
    mc_ref,  # [R, BLOCK] i8
    ms_ref,  # [R//128, 128] f32 — dense ("wide") scale layout
    vc_ref,
    vs_ref,
    mc_out,
    ms_out,
    vc_out,
    vs_out,
    delta_out,  # [R, BLOCK] in g's dtype
    *,
    b1: float,
    b2: float,
    classic_eps: bool = True,
):
    lrA, invbc2, eps = (
        scalar_ref[0],
        scalar_ref[1],
        scalar_ref[2],
    )
    g = g_ref[:].astype(jnp.float32)
    m = _dequant_block_math_wide(mc_ref[:], ms_ref[:])
    v = _dequant_block_math_wide(vc_ref[:], vs_ref[:])
    m_new, v_new, delta = _adam8_block_math(
        g, m, v, lrA, invbc2, eps, b1, b2, classic_eps
    )
    mc, ms = _quant_block_math_wide(m_new, signed=True)
    vc, vs = _quant_block_math_wide(v_new, signed=False)
    mc_out[:] = mc
    ms_out[:] = ms
    vc_out[:] = vc
    vs_out[:] = vs
    delta_out[:] = delta.astype(delta_out.dtype)


def _adam8_update_pallas_flat(
    g_blocks, mq, vq, scalars, b1, b2, interpret, classic_eps=True
):
    """One pallas pass over a pre-padded flat buffer (rows already a
    multiple of ``_FLAT_ROWS`` — the flat packer guarantees it, so no
    padding copies of GB-scale code arrays happen here). Moment codes
    and scales alias in-place (input_output_aliases): at 1.5B params
    the old+new codes would otherwise double the optimizer state's
    footprint mid-update. Scales use the dense wide layout (see
    ``_quant_block_math_wide``)."""
    nrows = g_blocks.shape[0]
    grid = (nrows // _FLAT_ROWS,)
    row_spec = pl.BlockSpec((_FLAT_ROWS, BLOCK), lambda i: (i, 0))
    scale_spec = pl.BlockSpec((_FLAT_ROWS // 128, 128), lambda i: (i, 0))
    mc, ms, vc, vs, delta = pl.pallas_call(
        functools.partial(
            _adam8_kernel_wide, b1=b1, b2=b2, classic_eps=classic_eps
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            row_spec,
            row_spec,
            scale_spec,
            row_spec,
            scale_spec,
        ],
        out_specs=[row_spec, scale_spec, row_spec, scale_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((nrows // 128, 128), jnp.float32),
            jax.ShapeDtypeStruct((nrows, BLOCK), jnp.int8),
            jax.ShapeDtypeStruct((nrows // 128, 128), jnp.float32),
            jax.ShapeDtypeStruct((nrows, BLOCK), g_blocks.dtype),
        ],
        input_output_aliases={2: 0, 3: 1, 4: 2, 5: 3},
        interpret=interpret,
    )(scalars, g_blocks, mq.codes, mq.scales, vq.codes, vq.scales)
    return (
        Quantized8(mc, ms, mq.shape, True),
        Quantized8(vc, vs, vq.shape, False),
        delta,
    )


class Adam8State(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates  # pytree of Quantized8
    nu: optax.Updates  # pytree of Quantized8


def adamw_8bit(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    min_quantized_size: int = 4096,
    use_pallas: bool | None = None,
    bits: int = 8,
    eps_root: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW whose moments live in int8 (4x less optimizer-state HBM
    than fp32 Adam) or, with ``bits=4``, a nibble-packed first moment +
    int8 second moment (1.5 B/param, ~5.3x less) — the FSDP/ZeRO memory
    ceiling on big models. Parity: the reference ships both 4- and
    8-bit variants (low_bit/functional.py).

    Tensors smaller than ``min_quantized_size`` keep fp32 moments (the
    reference does the same for small params, where block stats are
    noisy and savings negligible). The fused Pallas kernel covers the
    8-bit path; the 4-bit path (nibble-packed first moment + int8
    second moment, 1.5 B/param state) runs the jnp math — XLA fuses the
    unpack→update→repack chain, and the platform's int4 dtype is not
    usable.

    ``eps`` is the classic Adam epsilon (outside the sqrt). Passing
    ``eps_root`` instead (with eps=0) moves the damping inside the
    sqrt (the optax ``eps_root`` convention) — one rsqrt, the fastest
    form; the two are mutually exclusive to keep the semantics obvious.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    if eps_root and eps:
        raise ValueError(
            "pass either eps (classic, outside the sqrt) or eps_root "
            "(inside), not both"
        )
    classic = eps_root == 0.0
    eps_val = eps if classic else eps_root
    # bits=4 packs the FIRST moment into nibbles; the second moment
    # stays int8 (see _adam4_update_jnp) → 1.5 bytes/param of state
    quantize_m = quantize_8bit if bits == 8 else quantize_4bit
    quantize_v = quantize_8bit

    def _pallas_enabled():
        if bits != 8:
            return False
        if use_pallas is not None:
            return use_pallas
        return jax.default_backend() == "tpu"

    def init_fn(params):
        def _init_m(p):
            if p.size < min_quantized_size:
                return jnp.zeros_like(p, jnp.float32)
            return quantize_m(jnp.zeros_like(p, jnp.float32), True)

        def _init_v(p):
            if p.size < min_quantized_size:
                return jnp.zeros_like(p, jnp.float32)
            return quantize_v(jnp.zeros_like(p, jnp.float32), False)

        return Adam8State(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(_init_m, params),
            nu=jax.tree.map(_init_v, params),
        )

    def update_fn(grads, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        lrA = jnp.asarray(learning_rate, jnp.float32) / (1.0 - b1**cf)
        invbc2 = 1.0 / (1.0 - b2**cf)
        scalars = jnp.stack([lrA, invbc2, jnp.float32(eps_val)])

        def _one(g, m, v):
            if not isinstance(m, (Quantized8, Quantized4)):
                # small tensor: plain fp32 adam, same eps placement as
                # the kernel so small and big leaves share semantics
                m_new, v_new, delta = _adam8_block_math(
                    g, m, v, lrA, invbc2, eps_val, b1, b2, classic
                )
                return delta.astype(g.dtype), m_new, v_new
            g_blocks = _to_blocks(g.astype(jnp.float32))
            if isinstance(m, Quantized4):
                mq, vq, delta = _adam4_update_jnp(
                    g_blocks, m, v, scalars, b1, b2, classic
                )
            elif _pallas_enabled():
                mq, vq, delta = _adam8_update_pallas(
                    g_blocks, m, v, scalars, b1, b2, interpret=False,
                    classic_eps=classic,
                )
            else:
                mq, vq, delta = _adam8_update_jnp(
                    g_blocks, m, v, scalars, b1, b2, classic
                )
            return _from_blocks(delta, g.shape).astype(g.dtype), mq, vq

        flat_g, treedef = jax.tree.flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        results = [
            _one(g, m, v) for g, m, v in zip(flat_g, flat_m, flat_v)
        ]
        updates = treedef.unflatten([r[0] for r in results])
        mu = treedef.unflatten([r[1] for r in results])
        nu = treedef.unflatten([r[2] for r in results])

        if weight_decay and params is not None:
            updates = jax.tree.map(
                lambda u, p: u - learning_rate * weight_decay * p,
                updates,
                params,
            )
        return updates, Adam8State(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


class Adam8FlatState(NamedTuple):
    count: jnp.ndarray
    mu: tuple  # per-GROUP Quantized8 buffers over the big leaves
    nu: tuple
    mu_small: jnp.ndarray  # [S] f32 — all small leaves, flat
    nu_small: jnp.ndarray


class _FlatGroup(NamedTuple):
    """One packed group of big leaves (static — computed at trace time
    from leaf shapes, free under jit)."""

    idx: tuple  # leaf positions in this group
    offsets: tuple  # start offset of each leaf (BLOCK-aligned)
    total: int  # padded group size (multiple of BLOCK*_ROWS)


class _FlatLayout(NamedTuple):
    groups: tuple  # of _FlatGroup
    small_idx: tuple
    small_offsets: tuple
    small_total: int


def _flat_layout(
    leaves, min_quantized_size: int, group_elems: int
) -> _FlatLayout:
    """Pack big leaves into groups of ~``group_elems`` elements. Groups
    bound the transient HBM of the update (one group's grad concat +
    delta live at a time) — a single 1.5B-param flat buffer measured
    +6 GB of transients and OOMed next to bf16 params+grads, while
    per-group transients are ~2×group_elems bytes. Each leaf is padded
    to a BLOCK boundary so quantization blocks never straddle leaves
    (numerics identical to the per-leaf tree form)."""
    chunk = BLOCK * _FLAT_ROWS
    groups, g_idx, g_off, off = [], [], [], 0
    g_dtype = None
    small_idx, small_off, soff = [], [], 0

    def _close_group():
        nonlocal g_idx, g_off, off, g_dtype
        if g_idx:
            groups.append(
                _FlatGroup(
                    tuple(g_idx), tuple(g_off), -(-off // chunk) * chunk
                )
            )
            g_idx, g_off, off, g_dtype = [], [], 0, None

    for i, leaf in enumerate(leaves):
        if leaf.size >= min_quantized_size:
            # groups are dtype-HOMOGENEOUS: packing an f32 leaf into a
            # bf16 group would round its grads (and its delta) through
            # bf16, silently diverging from the per-leaf tree form
            if off and (
                off + leaf.size > group_elems or leaf.dtype != g_dtype
            ):
                _close_group()
            g_idx.append(i)
            g_off.append(off)
            g_dtype = leaf.dtype
            off += -(-leaf.size // BLOCK) * BLOCK
        else:
            small_idx.append(i)
            small_off.append(soff)
            soff += leaf.size
    _close_group()
    return _FlatLayout(
        tuple(groups), tuple(small_idx), tuple(small_off), soff
    )


def _pack_group(leaves, group: _FlatGroup, dtype):
    """Concatenate one group's leaves (each zero-padded to its
    BLOCK-aligned slot) into a flat [group.total] buffer — one fused
    concat pass per group."""
    segs = []
    for i in group.idx:
        n = leaves[i].size
        pad = -(-n // BLOCK) * BLOCK - n
        seg = leaves[i].reshape(-1).astype(dtype)
        if pad:
            seg = jnp.pad(seg, (0, pad))
        segs.append(seg)
    used = group.offsets[-1] + -(-leaves[group.idx[-1]].size // BLOCK) * BLOCK
    if group.total - used:
        segs.append(jnp.zeros((group.total - used,), dtype))
    return jnp.concatenate(segs)


def adamw_8bit_flat(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    min_quantized_size: int = 4096,
    use_pallas: bool | None = None,
    group_elems: int = 1 << 27,
    eps_root: float = 0.0,
) -> optax.GradientTransformation:
    """``adamw_8bit`` with FLAT-BUFFER state: big leaves' moments live
    in a handful of group-packed Quantized8 pairs and the hot path is
    one pallas pass per ~134M-element group (~12 at GPT-2 XL) plus one
    fused concat each — the per-leaf slices back out fuse into the
    apply. The per-leaf (tree) form dispatches ~5 kernels per leaf,
    ~800 launches on GPT-2 XL, measured 170-200 ms against a 38 ms
    flat-buffer roofline (VERDICT r3 #1); this form closes that gap.
    ``group_elems`` bounds the transient HBM (one group's grad concat +
    delta at a time) — a single 1.5B flat buffer OOMed next to bf16
    params+grads.

    Numerics are IDENTICAL to ``adamw_8bit``: each leaf is padded to a
    BLOCK boundary inside its group, so quantization blocks (and their
    scales) never straddle leaves. Small leaves (< ``min_quantized_
    size``) keep fp32 moments, packed into one flat f32 vector pair —
    one fused elementwise update instead of ~100 tiny kernels.

    Intended for replicated / single-device training states (the 1.5B
    single-chip bench). Sharded states keep the tree form: a flat
    buffer would force cross-shard concats of every leaf.

    ``eps``/``eps_root`` follow ``adamw_8bit``: classic outside-sqrt
    epsilon, or the faster inside-sqrt form — mutually exclusive.
    """
    if eps_root and eps:
        raise ValueError(
            "pass either eps (classic, outside the sqrt) or eps_root "
            "(inside), not both"
        )
    classic = eps_root == 0.0
    eps_val = eps if classic else eps_root

    def _pallas_enabled():
        if use_pallas is not None:
            return use_pallas
        return jax.default_backend() == "tpu"

    def init_fn(params):
        leaves = jax.tree.flatten(params)[0]
        layout = _flat_layout(leaves, min_quantized_size, group_elems)
        mu, nu = [], []
        for g in layout.groups:
            nblocks = g.total // BLOCK
            # scales in the dense wide layout [nblocks//128, 128] — the
            # natural [nblocks, 1] gets XLA-padded to 128 lanes at
            # rest, a 128x (GBs at 1.5B params) memory blowup
            mu.append(
                Quantized8(
                    jnp.zeros((nblocks, BLOCK), jnp.int8),
                    jnp.zeros((nblocks // 128, 128), jnp.float32),
                    (g.total,),
                    True,
                )
            )
            nu.append(
                Quantized8(
                    jnp.zeros((nblocks, BLOCK), jnp.int8),
                    jnp.zeros((nblocks // 128, 128), jnp.float32),
                    (g.total,),
                    False,
                )
            )
        return Adam8FlatState(
            count=jnp.zeros((), jnp.int32),
            mu=tuple(mu),
            nu=tuple(nu),
            mu_small=jnp.zeros((layout.small_total,), jnp.float32),
            nu_small=jnp.zeros((layout.small_total,), jnp.float32),
        )

    def update_fn(grads, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        lrA = jnp.asarray(learning_rate, jnp.float32) / (1.0 - b1**cf)
        invbc2 = 1.0 / (1.0 - b2**cf)
        scalars = jnp.stack([lrA, invbc2, jnp.float32(eps_val)])
        leaves, treedef = jax.tree.flatten(grads)
        layout = _flat_layout(leaves, min_quantized_size, group_elems)
        out = [None] * len(leaves)

        mq_groups, vq_groups = [], []
        for gi, group in enumerate(layout.groups):
            # grads stay in their own dtype (bf16 on the big bench) —
            # the kernel upcasts per block in VMEM; a f32 flat buffer
            # would double the transient HBM
            gflat = _pack_group(leaves, group, leaves[group.idx[0]].dtype)
            g_blocks = gflat.reshape(-1, BLOCK)
            if _pallas_enabled():
                mq, vq, delta = _adam8_update_pallas_flat(
                    g_blocks, state.mu[gi], state.nu[gi], scalars,
                    b1, b2, interpret=False, classic_eps=classic,
                )
            else:
                mq, vq, delta = _adam8_update_jnp(
                    g_blocks.astype(jnp.float32), state.mu[gi],
                    state.nu[gi], scalars, b1, b2, classic,
                )
            mq_groups.append(mq)
            vq_groups.append(vq)
            delta_flat = delta.reshape(-1)
            for k, i in enumerate(group.idx):
                n = leaves[i].size
                off = group.offsets[k]
                out[i] = (
                    lax.slice(delta_flat, (off,), (off + n,))
                    .reshape(leaves[i].shape)
                    .astype(leaves[i].dtype)
                )

        if layout.small_idx:
            gs = jnp.concatenate(
                [
                    leaves[i].reshape(-1).astype(jnp.float32)
                    for i in layout.small_idx
                ]
            )
            m_new, v_new, ds = _adam8_block_math(
                gs, state.mu_small, state.nu_small, lrA, invbc2,
                eps_val, b1, b2, classic,
            )
            for k, i in enumerate(layout.small_idx):
                n = leaves[i].size
                off = layout.small_offsets[k]
                out[i] = (
                    lax.slice(ds, (off,), (off + n,))
                    .reshape(leaves[i].shape)
                    .astype(leaves[i].dtype)
                )
        else:
            m_new, v_new = state.mu_small, state.nu_small

        updates = treedef.unflatten(out)
        if weight_decay and params is not None:
            updates = jax.tree.map(
                lambda u, p: u - learning_rate * weight_decay * p,
                updates,
                params,
            )
        return updates, Adam8FlatState(
            count=count,
            mu=tuple(mq_groups),
            nu=tuple(vq_groups),
            mu_small=m_new,
            nu_small=v_new,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def adamw_4bit(**kwargs) -> optax.GradientTransformation:
    """"4-bit" AdamW (nibble-packed first moment + int8 second moment):
    1.5 B/param of optimizer state vs 8 for fp32 Adam. Parity: the
    reference's 4-bit low-bit optimizer (which spends rank-1 factorized
    scaling on the second moment; here it keeps 8 bits instead — same
    memory class, far simpler, and it tracks fp32 trajectories in
    tests)."""
    return adamw_8bit(bits=4, **kwargs)
