"""Block-tiled flash attention for TPU (Pallas) with a jnp fallback.

Parity: the reference integrates CUDA flash-attention (FA1/FA2 + GLM
custom-mask kernels) via wrapper modules at
atorch/atorch/modules/transformer/layers.py:54-1168 and TF bindings at
tfplus/tfplus/flash_attn/kernels/flash_attention_fwd_kernel.cc:172. The
TPU-native equivalent is a Pallas kernel: the (q_block, kv_block) tiles
ride the MXU, the online-softmax state (running max / sum) lives in VMEM
scratch, and HBM traffic is O(T) per query block instead of the O(T^2)
score matrix.

Design:

- ``flash_attention(q, k, v)`` — public entry, [B, T, H, D] layout, GQA
  (H_kv divides H, resolved in the BlockSpec index map — KV heads are
  never materialized ``H/H_kv`` times), causal or custom position masks,
  dynamic block offsets so ring attention (parallel/ring_attention.py)
  can reuse the same kernel per KV hop.
- Differentiable via ``jax.custom_vjp``: backward is two more Pallas
  kernels (dq pass and dk/dv pass) using the saved (o, logsumexp)
  residuals, the standard FA2 recomputation split.
- On non-TPU backends it dispatches to ``flash_attention_reference`` —
  identical math, pure jnp — so CPU tests are fast; the kernels
  themselves are tested under ``interpret=True``.
- **Short-sequence fused kernels**: when the [T, T] score tile fits
  VMEM (T <= 1024, measured crossover), the streaming form is pure
  overhead — at seq 512 / head_dim 64 the MXU work per program is tiny,
  so grid count, online-softmax rescaling passes, and the backward's
  double (s, p, dp) recompute dominate. The fused path runs one program
  per (batch element, head chunk) with a python-unrolled head loop,
  single-pass softmax, and ONE backward kernel computing s/p/dp once
  and emitting dq/dk/dv together (5 matmuls vs the streaming split's
  7). Programs cover head CHUNKS sized so the unrolled per-head [T,T]
  f32 temporaries stay within scoped VMEM (``_head_chunk``).
- ``layout="bhtd"`` lets callers hand over kernel-native [B, H, T, D]
  tensors (the model emits them straight from its QKV einsums), skipping
  the 25 MB-per-tensor relayout transposes on every call.

Mask contract: ``mask_fn(q_pos, k_pos)`` receives broadcastable int32
position arrays (shapes ``[bq, 1]`` and ``[1, bk]``) and must return an
elementwise bool mask, e.g. ``lambda q, k: q >= k`` for causal.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlrover_tpu.common.jax_compat import (
    pallas_tpu_compiler_params as _compiler_params,
)

MaskFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]

NEG_INF = -1e30  # finite stand-in for -inf: keeps exp()=0 without NaN risk
_LANES = 128  # f32 VMEM tile lane count; scratch vectors are padded to it


def _mask_for_block(q_pos, k_pos, causal, mask_fn):
    """[bq,1] x [1,bk] positions -> bool mask or None (= all visible)."""
    if mask_fn is not None:
        return mask_fn(q_pos, k_pos)
    if causal:
        return q_pos >= k_pos
    return None


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(
    off_ref,  # SMEM [2]: (q_offset, k_offset) global position offsets
    q_ref,  # VMEM [1, 1, bq, D]
    k_ref,  # VMEM [1, 1, bk, D]
    v_ref,  # VMEM [1, 1, bk, D]
    o_ref,  # VMEM [1, 1, bq, D]
    lse_ref,  # VMEM [1, 1, bq, 1]
    acc_ref,  # scratch [bq, D] f32
    m_ref,  # scratch [bq, _LANES] f32
    l_ref,  # scratch [bq, _LANES] f32
    *,
    causal: bool,
    mask_fn: Optional[MaskFn],
    sm_scale: float,
    block_q: int,
    block_k: int,
):
    jk = pl.program_id(3)
    nk = pl.num_programs(3)
    q_off = off_ref[0] + pl.program_id(2) * block_q
    k_off = off_ref[1] + jk * block_k

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # whole-block causal skip: no query in this block can see any key
    visible = True
    if causal and mask_fn is None:
        visible = q_off + block_q - 1 >= k_off

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        s = s * sm_scale
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        mask = _mask_for_block(q_pos, k_pos, causal, mask_fn)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]  # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # fully-masked-so-far rows keep m_new == NEG_INF; exponentiate
        # against 0 there so p = exp(NEG_INF) = 0 instead of exp(0) = 1
        m_safe = jnp.where(m_new > NEG_INF * 0.5, m_new, 0.0)
        alpha = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p in input precision for the MXU (f32 operands run the
        # systolic array at a fraction of bf16 rate); f32 accumulator
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        # fully-masked rows: l == 0 -> output 0, lse = NEG_INF
        safe_l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        m = m_ref[:, :1]
        lse = jnp.where(l > 0.0, m + jnp.log(safe_l), NEG_INF)
        lse_ref[0, 0] = lse


def _fwd_pallas(
    q,
    k,
    v,
    offsets,
    *,
    causal,
    mask_fn,
    sm_scale,
    block_q,
    block_k,
    interpret,
    layout="bthd",
    allow_fused=True,
):
    # Kernel layout is [B, H, T, D]: TPU tiling needs the last two block
    # dims to be (seq_block, head_dim) — (8,128)-aligned or full-size.
    # ``layout="bhtd"`` callers hand kernel-native tensors (no relayout).
    if layout == "bhtd":
        B, H, Tq, D = q.shape
        Hkv, Tk = k.shape[1], k.shape[2]
        qt, kt, vt = q, k, v
    else:
        B, Tq, H, D = q.shape
        Tk, Hkv = k.shape[1], k.shape[2]
        qt = q.transpose(0, 2, 1, 3)
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
    nq, nk = Tq // block_q, Tk // block_k
    group = H // Hkv

    if allow_fused and _fused_eligible(qt.shape, kt.shape, "bhtd"):
        ot, lse4 = _fused_fwd_call(
            qt, kt, vt, offsets,
            causal=causal, mask_fn=mask_fn, sm_scale=sm_scale,
            interpret=interpret,
        )
        if layout == "bhtd":
            return ot, lse4[..., 0]
        return ot.transpose(0, 2, 1, 3), lse4[..., 0]

    kernel = functools.partial(
        _fwd_kernel,
        causal=causal,
        mask_fn=mask_fn,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
    )
    grid = (B, H, nq, nk)
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, i, j: (b, h // group, j, 0)
    )
    # minor dim 1 == full array dim, so the tile is legal and lse costs
    # [B,H,T] f32 in HBM instead of 128x that
    lse_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)
    )
    ot, lse4 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            q_spec,
            kv_spec,
            kv_spec,
        ],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=(
                "parallel",
                "parallel",
                "parallel",
                "arbitrary",
            ),
        ),
        interpret=interpret,
    )(offsets, qt, kt, vt)
    if layout == "bhtd":
        return ot, lse4[..., 0]
    return ot.transpose(0, 2, 1, 3), lse4[..., 0]


# ---------------------------------------------------------------------------
# fused short-sequence kernels (one program per batch element)
# ---------------------------------------------------------------------------
# Eligibility: the [T, T] f32 score tile must fit scoped VMEM (see
# _head_chunk, which sizes head chunks against a 48 MB live-set budget
# under the raised _FUSED_VMEM_LIMIT). At T=2048 a single head's
# backward live set (~3.5 x 16 MB) no longer fits; the streaming
# kernels take over there.
_FUSED_MAX_T = 1024


def _fused_eligible(q_shape, k_shape, layout: str) -> bool:
    if layout == "bhtd":
        B, H, Tq, D = q_shape
        Hkv, Tk = k_shape[1], k_shape[2]
    else:
        B, Tq, H, D = q_shape
        Tk, Hkv = k_shape[1], k_shape[2]
    return Tq == Tk and Tq <= _FUSED_MAX_T and H == Hkv


def _fused_fwd_kernel(
    off_ref,  # SMEM [2]
    q_ref,  # VMEM [1, Hc, T, D]
    k_ref,
    v_ref,
    o_ref,  # VMEM [1, Hc, T, D]
    lse_ref,  # VMEM [1, Hc, T, 1]
    *,
    causal: bool,
    mask_fn: Optional[MaskFn],
    sm_scale: float,
    n_heads: int,
):
    T = q_ref.shape[2]

    def _compute():
        q_pos = off_ref[0] + lax.broadcasted_iota(jnp.int32, (T, 1), 0)
        k_pos = off_ref[1] + lax.broadcasted_iota(jnp.int32, (1, T), 1)
        mask = _mask_for_block(q_pos, k_pos, causal, mask_fn)
        # static unroll: one [T,T] live set at a time
        for h in range(n_heads):
            q = q_ref[0, h]
            k = k_ref[0, h]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            s = s * sm_scale
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            m = jnp.max(s, axis=-1, keepdims=True)  # [T, 1]
            m_safe = jnp.where(m > NEG_INF * 0.5, m, 0.0)
            p = jnp.exp(s - m_safe)
            l = jnp.sum(p, axis=-1, keepdims=True)
            # p rides the MXU in the INPUT precision (f32 operands run
            # the systolic array at a fraction of bf16 rate); the
            # accumulator stays f32 via preferred_element_type
            acc = jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0, h],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            safe_l = jnp.where(l > 0.0, l, 1.0)
            o_ref[0, h] = (acc / safe_l).astype(o_ref.dtype)
            lse_ref[0, h] = jnp.where(
                l > 0.0, m_safe + jnp.log(safe_l), NEG_INF
            )

    if causal and mask_fn is None:
        # whole-program causal skip: ring attention's fully-future KV
        # hops (k_offset past every query) stay near-free, as in the
        # streaming kernel's per-block pl.when gate
        visible = off_ref[0] + T - 1 >= off_ref[1]

        @pl.when(jnp.logical_not(visible))
        def _skip():
            o_ref[0] = jnp.zeros_like(o_ref[0])
            lse_ref[0] = jnp.full_like(lse_ref[0], NEG_INF)

        pl.when(visible)(_compute)
    else:
        _compute()


def _fused_bwd_kernel(
    off_ref,  # SMEM [2]
    q_ref,  # VMEM [1, H, T, D]
    k_ref,
    v_ref,
    do_ref,
    lse_ref,  # VMEM [1, H, T, 1]
    delta_ref,
    dq_ref,  # out [1, H, T, D]
    dk_ref,
    dv_ref,
    *,
    causal: bool,
    mask_fn: Optional[MaskFn],
    sm_scale: float,
    n_heads: int,
):
    """One pass per head: s and p computed ONCE, then the three grad
    matmuls — the streaming FA2 split recomputes (s, p, dp) in both its
    dq and dk/dv kernels (7 matmuls/head vs 5 here)."""
    T = q_ref.shape[2]

    def _compute():
        q_pos = off_ref[0] + lax.broadcasted_iota(jnp.int32, (T, 1), 0)
        k_pos = off_ref[1] + lax.broadcasted_iota(jnp.int32, (1, T), 1)
        mask = _mask_for_block(q_pos, k_pos, causal, mask_fn)
        for h in range(n_heads):
            q = q_ref[0, h]
            k = k_ref[0, h]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * sm_scale
            )
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            lse = lse_ref[0, h]  # [T, 1]
            row_valid = lse > NEG_INF * 0.5
            p = jnp.where(row_valid, jnp.exp(s - lse), 0.0)  # [T, T]
            # every grad matmul feeds the MXU input-precision operands
            # (f32 operands run the systolic array at a fraction of
            # bf16 rate); accumulation stays f32
            p_lo = p.astype(q_ref.dtype)
            do = do_ref[0, h]
            # dv = p^T @ do
            dv_ref[0, h] = jax.lax.dot_general(
                p_lo, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(dv_ref.dtype)
            dp = jax.lax.dot_general(
                do, v_ref[0, h], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta_ref[0, h]) * sm_scale  # [T, T]
            ds_lo = ds.astype(q_ref.dtype)
            dq_ref[0, h] = jax.lax.dot_general(
                ds_lo, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(dq_ref.dtype)
            # dk = ds^T @ q
            dk_ref[0, h] = jax.lax.dot_general(
                ds_lo, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(dk_ref.dtype)


    if causal and mask_fn is None:
        # mirror of the forward's whole-program causal skip
        visible = off_ref[0] + T - 1 >= off_ref[1]

        @pl.when(jnp.logical_not(visible))
        def _skip():
            dq_ref[0] = jnp.zeros_like(dq_ref[0])
            dk_ref[0] = jnp.zeros_like(dk_ref[0])
            dv_ref[0] = jnp.zeros_like(dv_ref[0])

        pl.when(visible)(_compute)
    else:
        _compute()

def _head_chunk(H: int, T: int, live_f32_per_head: float) -> int:
    """Heads per program: the unrolled head loop's [T, T] f32 temporaries
    occupy scoped VMEM stack; chunk so ``Hc * live set`` stays under a
    conservative budget (the raised ``vmem_limit_bytes`` leaves slack for
    the compiler's own scheduling)."""
    # measured on v5e (bf16, D=64/128): larger chunks amortize
    # per-program overhead — T=512 all-12-heads beats 9 by 27%, T=1024
    # Hc=4 beats Hc=2 by 28% — and Mosaic tolerates a live set past
    # physical VMEM by scheduling spills; the hard compile failure on
    # v5e lands near ~64 MB x live-factor, so 48 MB keeps margin
    budget = 48 * 1024 * 1024
    per_head = live_f32_per_head * T * T * 4
    best = 1
    for d in range(1, H + 1):
        if H % d == 0 and d * per_head <= budget:
            best = d
    return best


_FUSED_VMEM_LIMIT = 100 * 1024 * 1024


def _fused_fwd_call(qt, kt, vt, offsets, *, causal, mask_fn, sm_scale,
                    interpret):
    """[B,H,T,D] in -> (o [B,H,T,D], lse4 [B,H,T,1])."""
    B, H, T, D = qt.shape
    Hc = _head_chunk(H, T, live_f32_per_head=2.5)
    spec = pl.BlockSpec((1, Hc, T, D), lambda b, hc: (b, hc, 0, 0))
    row_spec = pl.BlockSpec((1, Hc, T, 1), lambda b, hc: (b, hc, 0, 0))
    return pl.pallas_call(
        functools.partial(
            _fused_fwd_kernel,
            causal=causal,
            mask_fn=mask_fn,
            sm_scale=sm_scale,
            n_heads=Hc,
        ),
        grid=(B, H // Hc),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), spec, spec, spec],
        out_specs=[spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), qt.dtype),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=_FUSED_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(offsets, qt, kt, vt)


def _fused_bwd_call(qt, kt, vt, dot, lse4, delta4, offsets, *, causal,
                    mask_fn, sm_scale, interpret):
    """[B,H,T,D] in -> (dq, dk, dv) each [B,H,T,D] (q dtype)."""
    B, H, T, D = qt.shape
    Hc = _head_chunk(H, T, live_f32_per_head=3.5)
    spec = pl.BlockSpec((1, Hc, T, D), lambda b, hc: (b, hc, 0, 0))
    row_spec = pl.BlockSpec((1, Hc, T, 1), lambda b, hc: (b, hc, 0, 0))
    return pl.pallas_call(
        functools.partial(
            _fused_bwd_kernel,
            causal=causal,
            mask_fn=mask_fn,
            sm_scale=sm_scale,
            n_heads=Hc,
        ),
        grid=(B, H // Hc),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec, spec, spec, spec, row_spec, row_spec,
        ],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), qt.dtype),
            jax.ShapeDtypeStruct((B, H, T, D), qt.dtype),
            jax.ShapeDtypeStruct((B, H, T, D), qt.dtype),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=_FUSED_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(offsets, qt, kt, vt, dot, lse4, delta4)


# ---------------------------------------------------------------------------
# backward kernels (FA2 split: dq pass, then dk/dv pass)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(
    off_ref,
    q_ref,  # [1, 1, bq, D]
    k_ref,  # [1, 1, bk, D]
    v_ref,
    do_ref,  # [1, 1, bq, D]
    lse_ref,  # [1, 1, bq, 1]
    delta_ref,  # [1, 1, bq, 1]
    dq_ref,  # out [1, 1, bq, D]
    dq_acc,  # scratch [bq, D] f32
    *,
    causal,
    mask_fn,
    sm_scale,
    block_q,
    block_k,
):
    jk = pl.program_id(3)
    nk = pl.num_programs(3)
    q_off = off_ref[0] + pl.program_id(2) * block_q
    k_off = off_ref[1] + jk * block_k

    @pl.when(jk == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    visible = True
    if causal and mask_fn is None:
        visible = q_off + block_q - 1 >= k_off

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        mask = _mask_for_block(q_pos, k_pos, causal, mask_fn)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        lse = lse_ref[0, 0, :, :1]  # [bq, 1]
        # fully-masked rows have lse == NEG_INF; exp(s - lse) would be
        # exp(0) = 1 there, leaking gradient through positions the
        # forward zeroed — zero p explicitly
        row_valid = lse > NEG_INF * 0.5
        p = jnp.where(row_valid, jnp.exp(s - lse), 0.0)
        # MXU operands stay in input precision (f32 operands run the
        # systolic array at a fraction of bf16 rate); f32 accumulation
        do = do_ref[0, 0]
        dp = jax.lax.dot_general(
            do,
            v_ref[0, 0],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0, 0, :, :1]
        ds = p * (dp - delta) * sm_scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k.dtype),
            k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jk == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    off_ref,
    q_ref,  # [1, 1, bq, D]
    k_ref,  # [1, 1, bk, D]
    v_ref,
    do_ref,
    lse_ref,  # [1, 1, bq, 1]
    delta_ref,
    dk_ref,  # out [1, 1, bk, D]  (per q-head; summed over groups outside)
    dv_ref,
    dk_acc,  # scratch [bk, D] f32
    dv_acc,
    *,
    causal,
    mask_fn,
    sm_scale,
    block_q,
    block_k,
):
    iq = pl.program_id(3)
    nq = pl.num_programs(3)
    q_off = off_ref[0] + iq * block_q
    k_off = off_ref[1] + pl.program_id(2) * block_k

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    visible = True
    if causal and mask_fn is None:
        visible = q_off + block_q - 1 >= k_off

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        mask = _mask_for_block(q_pos, k_pos, causal, mask_fn)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        lse = lse_ref[0, 0, :, :1]
        # zero p on fully-masked rows (see _bwd_dq_kernel)
        row_valid = lse > NEG_INF * 0.5
        p = jnp.where(row_valid, jnp.exp(s - lse), 0.0)  # [bq, bk]
        # MXU operands stay in input precision (f32 operands run the
        # systolic array at a fraction of bf16 rate); f32 accumulation
        do = do_ref[0, 0]
        # dv += p^T @ do
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype),
            do,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do,
            v_ref[0, 0],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0, 0, :, :1]
        ds = p * (dp - delta) * sm_scale  # [bq, bk]
        # dk += ds^T @ q
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype),
            q,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(
    q,
    k,
    v,
    offsets,
    o,
    lse,
    do,
    *,
    causal,
    mask_fn,
    sm_scale,
    block_q,
    block_k,
    interpret,
    layout="bthd",
    allow_fused=True,
):
    if layout == "bhtd":
        B, H, Tq, D = q.shape
        Hkv, Tk = k.shape[1], k.shape[2]
        qt, kt, vt, dot = q, k, v, do
        delta = jnp.einsum(
            "bhqd,bhqd->bhq",
            do.astype(jnp.float32),
            o.astype(jnp.float32),
        )
    else:
        B, Tq, H, D = q.shape
        Tk, Hkv = k.shape[1], k.shape[2]
        qt = q.transpose(0, 2, 1, 3)  # [B,H,T,D] kernel layout
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
        dot = do.transpose(0, 2, 1, 3)
        # delta_i = rowsum(do_i * o_i) — bandwidth-bound, XLA fuses it
        delta = jnp.einsum(
            "bqhd,bqhd->bhq",
            do.astype(jnp.float32),
            o.astype(jnp.float32),
        )
    nq, nk = Tq // block_q, Tk // block_k
    group = H // Hkv
    delta4 = delta[..., None]  # [B,H,Tq,1]
    lse4 = lse[..., None]

    if allow_fused and _fused_eligible(qt.shape, kt.shape, "bhtd"):
        dqt, dkt, dvt = _fused_bwd_call(
            qt, kt, vt, dot, lse4, delta4, offsets,
            causal=causal, mask_fn=mask_fn, sm_scale=sm_scale,
            interpret=interpret,
        )
        if layout == "bhtd":
            return dqt, dkt.astype(k.dtype), dvt.astype(v.dtype)
        return (
            dqt.transpose(0, 2, 1, 3),
            dkt.transpose(0, 2, 1, 3).astype(k.dtype),
            dvt.transpose(0, 2, 1, 3).astype(v.dtype),
        )

    common = dict(
        causal=causal,
        mask_fn=mask_fn,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
    )
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, i, j: (b, h // group, j, 0)
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)
    )

    dqt = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            q_spec,
            kv_spec,
            kv_spec,
            q_spec,
            row_spec,
            row_spec,
        ],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=(
                "parallel",
                "parallel",
                "parallel",
                "arbitrary",
            ),
        ),
        interpret=interpret,
    )(offsets, qt, kt, vt, dot, lse4, delta4)

    # dk/dv pass: grid iterates k blocks outer, q blocks inner. Outputs are
    # per q-head ([B,H,Tk,D]); GQA folds the head group by summing outside.
    q_spec2 = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, j, i: (b, h, i, 0)
    )
    kv_spec2 = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, j, i: (b, h // group, j, 0)
    )
    kv_out_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, j, i: (b, h, j, 0)
    )
    row_spec2 = pl.BlockSpec(
        (1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0)
    )
    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            q_spec2,
            kv_spec2,
            kv_spec2,
            q_spec2,
            row_spec2,
            row_spec2,
        ],
        out_specs=[kv_out_spec, kv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=(
                "parallel",
                "parallel",
                "parallel",
                "arbitrary",
            ),
        ),
        interpret=interpret,
    )(offsets, qt, kt, vt, dot, lse4, delta4)

    if layout == "bhtd":
        if group > 1:
            dk = dk_full.reshape(B, Hkv, group, Tk, D).sum(2)
            dv = dv_full.reshape(B, Hkv, group, Tk, D).sum(2)
        else:
            dk, dv = dk_full, dv_full
        return dqt, dk.astype(k.dtype), dv.astype(v.dtype)
    dq = dqt.transpose(0, 2, 1, 3)
    dk_t = dk_full.transpose(0, 2, 1, 3)  # [B,Tk,H,D]
    dv_t = dv_full.transpose(0, 2, 1, 3)
    if group > 1:
        dk = dk_t.reshape(B, Tk, Hkv, group, D).sum(3)
        dv = dv_t.reshape(B, Tk, Hkv, group, D).sum(3)
    else:
        dk, dv = dk_t, dv_t
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# custom-vjp wrapper around the pallas path (static offsets)
# ---------------------------------------------------------------------------
# Offsets are static here so they can ride nondiff_argnums; callers with
# *traced* offsets (ring attention's per-hop global positions) use the raw
# ``flash_attention_fwd``/``flash_attention_bwd`` pair and define their own
# VJP at the ring level, where the lse residual's gradient is handled.
@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash_pallas(
    q, k, v, offsets, causal, mask_fn, sm_scale, block_q, block_k, layout,
    allow_fused,
):
    o, _ = _fwd_pallas(
        q,
        k,
        v,
        jnp.asarray(offsets, jnp.int32),
        causal=causal,
        mask_fn=mask_fn,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=_interpret_default(),
        layout=layout,
        allow_fused=allow_fused,
    )
    return o


def _flash_fwd_rule(
    q, k, v, offsets, causal, mask_fn, sm_scale, block_q, block_k, layout,
    allow_fused,
):
    o, lse = _fwd_pallas(
        q,
        k,
        v,
        jnp.asarray(offsets, jnp.int32),
        causal=causal,
        mask_fn=mask_fn,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=_interpret_default(),
        layout=layout,
        allow_fused=allow_fused,
    )
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(
    offsets, causal, mask_fn, sm_scale, block_q, block_k, layout,
    allow_fused, res, do,
):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd_pallas(
        q,
        k,
        v,
        jnp.asarray(offsets, jnp.int32),
        o,
        lse,
        do,
        causal=causal,
        mask_fn=mask_fn,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=_interpret_default(),
        layout=layout,
        allow_fused=allow_fused,
    )
    return dq, dk, dv


_flash_pallas.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _interpret_default() -> bool:
    """Pallas kernels only compile on TPU; interpret elsewhere (tests)."""
    return jax.default_backend() != "tpu"


# Raw (non-differentiable) kernel entries for callers composing their own
# VJP — ring attention merges per-hop (o, lse) partials across devices.
def flash_attention_fwd(
    q,
    k,
    v,
    *,
    causal=True,
    sm_scale=None,
    mask_fn=None,
    q_offset=0,
    k_offset=0,
    block_q=512,
    block_k=512,
    interpret=None,
    layout="bthd",
    allow_fused=True,
):
    """Forward kernel; returns ``(o, lse)`` with lse ``[B,H,Tq]`` f32.

    ``allow_fused=False`` pins the streaming (block-tiled) kernels even
    when the fused short-seq form is eligible — for tests and A/B
    timing."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    bq, bk = _validate_blocks(q, k, block_q, block_k, layout)
    return _fwd_pallas(
        q,
        k,
        v,
        jnp.asarray(jnp.stack([q_offset, k_offset]), jnp.int32),
        causal=causal,
        mask_fn=mask_fn,
        sm_scale=scale,
        block_q=bq,
        block_k=bk,
        interpret=_interpret_default() if interpret is None else interpret,
        layout=layout,
        allow_fused=allow_fused,
    )


def merge_partials(o_a, lse_a, o_b, lse_b):
    """Online-softmax merge of two partial attention results over the
    same queries, different key sets: ``o`` [B,T,H,D] f32 normalized,
    ``lse`` [B,H,T] f32 log-sum-exp. The algebra ring attention uses
    per hop (parallel/ring_attention.py), shared here so chunked
    single-device attention and cross-device merges cannot diverge."""
    lse_new = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.exp(lse_a - lse_new)
    w_b = jnp.exp(lse_b - lse_new)

    def to_o(w):  # [B,H,T] -> [B,T,H,1]
        return w.transpose(0, 2, 1)[..., None]

    return o_a * to_o(w_a) + o_b * to_o(w_b), lse_new


def flash_attention_fwd_chunked(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale=None,
    mask_fn: Optional[MaskFn] = None,
    q_offset: int = 0,
    k_offset: int = 0,
    chunk: int = _FUSED_MAX_T,
):
    """Long-sequence forward as fused [chunk x chunk] tile calls plus
    online-softmax merges (``merge_partials``) — the streaming kernel's
    outer loop lifted to XLA level so every tile rides the fused
    short-seq kernel. ``[B,T,H,D]`` layout; T must divide by ``chunk``.
    Returns ``(o, lse[B,H,Tq])`` like ``flash_attention_fwd``.

    Exists because the fused kernel caps at T=``_FUSED_MAX_T`` (the
    [T,T] score tile must fit VMEM): a full-sequence caller (Ulysses'
    per-device attention after its all-to-all) otherwise drops to the
    streaming kernels for the WHOLE sequence, paying a different
    kernel strategy than ring attention's naturally-chunked hops — the
    like-for-like gap VERDICT r4 #8 flagged. Causal chunks below the
    diagonal are skipped entirely (the work-skipping a causal streaming
    grid does with masked blocks)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if Tq % chunk or Tk % chunk or chunk % 8:
        raise ValueError(
            f"{Tq=}/{Tk=} must divide into 8-aligned {chunk=}"
        )
    if not isinstance(q_offset, int) or not isinstance(k_offset, int):
        raise ValueError(
            "chunked driver needs static int offsets (tile skipping "
            "is decided at trace time)"
        )
    n_q, n_k = Tq // chunk, Tk // chunk
    o_parts, lse_parts = [], []
    for i in range(n_q):
        qi = lax.slice_in_dim(q, i * chunk, (i + 1) * chunk, axis=1)
        o_acc = None
        lse_acc = None
        for j in range(n_k):
            if causal and (
                q_offset + (i + 1) * chunk - 1 < k_offset + j * chunk
            ):
                continue  # tile fully above the causal diagonal
            o_j, lse_j = flash_attention_fwd(
                qi,
                lax.slice_in_dim(k, j * chunk, (j + 1) * chunk, axis=1),
                lax.slice_in_dim(v, j * chunk, (j + 1) * chunk, axis=1),
                causal=causal,
                sm_scale=sm_scale,
                mask_fn=mask_fn,
                q_offset=q_offset + i * chunk,
                k_offset=k_offset + j * chunk,
            )
            o_j = o_j.astype(jnp.float32)
            if o_acc is None:
                o_acc, lse_acc = o_j, lse_j
            else:
                o_acc, lse_acc = merge_partials(o_acc, lse_acc, o_j, lse_j)
        if o_acc is None:  # every key after every query: empty softmax
            o_acc = jnp.zeros((B, chunk, H, D), jnp.float32)
            lse_acc = jnp.full((B, H, chunk), NEG_INF, jnp.float32)
        o_parts.append(o_acc)
        lse_parts.append(lse_acc)
    o = jnp.concatenate(o_parts, axis=1).astype(q.dtype)
    lse = jnp.concatenate(lse_parts, axis=2)
    return o, lse


def flash_attention_bwd(
    q,
    k,
    v,
    o,
    lse,
    do,
    *,
    causal=True,
    sm_scale=None,
    mask_fn=None,
    q_offset=0,
    k_offset=0,
    block_q=512,
    block_k=512,
    interpret=None,
    layout="bthd",
    allow_fused=True,
):
    """Backward kernels; returns ``(dq, dk, dv)`` given saved residuals."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    bq, bk = _validate_blocks(q, k, block_q, block_k, layout)
    return _bwd_pallas(
        q,
        k,
        v,
        jnp.asarray(jnp.stack([q_offset, k_offset]), jnp.int32),
        o,
        lse,
        do,
        causal=causal,
        mask_fn=mask_fn,
        sm_scale=scale,
        block_q=bq,
        block_k=bk,
        interpret=_interpret_default() if interpret is None else interpret,
        layout=layout,
        allow_fused=allow_fused,
    )


def _validate_blocks(q, k, block_q, block_k, layout="bthd"):
    seq_axis = 2 if layout == "bhtd" else 1
    Tq, Tk = q.shape[seq_axis], k.shape[seq_axis]
    bq, bk = min(block_q, Tq), min(block_k, Tk)
    if Tq % bq or Tk % bk or bq % 8 or bk % 8:
        # TPU sublane tiling wants 8-aligned seq blocks; the public entry
        # falls back to the jnp path on this error
        raise ValueError(
            f"sequence lengths ({Tq=}, {Tk=}) must divide into 8-aligned "
            f"blocks ({bq=}, {bk=}); pad inputs or pass other block sizes"
        )
    return bq, bk


# ---------------------------------------------------------------------------
# jnp reference (CPU fallback + numerics oracle)
# ---------------------------------------------------------------------------
def flash_attention_reference(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    mask_fn: Optional[MaskFn] = None,
    q_offset=0,
    k_offset=0,
    return_residuals: bool = False,
):
    """Same semantics as the kernel, materialized scores. Differentiable."""
    D = q.shape[-1]
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    scale = sm_scale if sm_scale is not None else D**-0.5
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    Tq, Tk = q.shape[1], k.shape[1]
    q_pos = (q_offset + jnp.arange(Tq))[:, None]
    k_pos = (k_offset + jnp.arange(Tk))[None, :]
    mask = _mask_for_block(q_pos, k_pos, causal, mask_fn)
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.maximum(m, NEG_INF)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    visible = m > NEG_INF / 2
    o = jnp.einsum("bhqk,bkhd->bqhd", p / jnp.maximum(l, 1e-30), v)
    o = jnp.where(
        visible.squeeze(-1)[..., None].transpose(0, 2, 1, 3), o, 0.0
    ).astype(q.dtype)
    if not return_residuals:
        return o
    lse = jnp.where(
        visible, m_safe + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF
    ).squeeze(-1)
    return o, lse


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------
def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    mask_fn: Optional[MaskFn] = None,
    q_offset=0,
    k_offset=0,
    block_q: int = 512,
    block_k: int = 512,
    return_residuals: bool = False,
    force: Optional[str] = None,
    layout: str = "bthd",
    allow_fused: bool = True,
):
    """Flash attention over ``q:[B,Tq,H,D] k,v:[B,Tk,Hkv,D]`` (or the
    kernel-native ``[B,H,T,D]`` with ``layout="bhtd"`` — no relayout
    transposes; the model's QKV einsums emit this directly).

    ``q_offset``/``k_offset`` are global position offsets (scalars, may be
    traced) so a caller holding one ring hop's KV block can evaluate the
    correct causal/custom mask. ``return_residuals`` adds the f32
    logsumexp ``[B,H,Tq]``, letting callers merge partial attention
    results across devices (online-softmax merge in ring attention).

    ``force``: ``None`` auto-picks (pallas on TPU, jnp elsewhere),
    ``"pallas"``/``"reference"`` override.

    The differentiable pallas path requires static int offsets; for
    traced offsets or ``return_residuals`` gradients, compose
    ``flash_attention_fwd``/``flash_attention_bwd`` directly (see ring
    attention).
    """
    mode = force
    if mode is None:
        mode = "pallas" if jax.default_backend() == "tpu" else "reference"
    if mode == "reference":
        # one reference call site: bhtd just transposes around it
        if layout == "bhtd":
            q, k, v = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        r = flash_attention_reference(
            q,
            k,
            v,
            causal=causal,
            sm_scale=sm_scale,
            mask_fn=mask_fn,
            q_offset=q_offset,
            k_offset=k_offset,
            return_residuals=return_residuals,
        )
        if layout != "bhtd":
            return r
        if return_residuals:
            return r[0].transpose(0, 2, 1, 3), r[1]
        return r.transpose(0, 2, 1, 3)

    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    try:
        bq, bk = _validate_blocks(q, k, block_q, block_k, layout)
    except ValueError:
        if force is not None:
            raise
        seq_axis = 2 if layout == "bhtd" else 1
        if (
            allow_fused
            and q.shape[seq_axis] % 8 == 0
            and k.shape[seq_axis] % 8 == 0
            and _fused_eligible(q.shape, k.shape, layout)
            # the differentiable pallas path below needs static offsets;
            # traced-offset callers keep the jnp fallback (the raw-fwd
            # return_residuals path handles traced offsets fine)
            and (
                return_residuals
                or (
                    isinstance(q_offset, int)
                    and isinstance(k_offset, int)
                )
            )
        ):
            # block tiling is a STREAMING-kernel constraint; fused-kernel
            # shapes (T<=_FUSED_MAX_T, e.g. T=520) have none beyond
            # 8-alignment, so they stay on the Pallas path. The block
            # sizes are unused there but must be valid.
            bq = bk = 8
        else:
            # odd sequence length: the jnp path has no tiling constraint
            return flash_attention(
                q,
                k,
                v,
                causal=causal,
                sm_scale=scale,
                mask_fn=mask_fn,
                q_offset=q_offset,
                k_offset=k_offset,
                return_residuals=return_residuals,
                force="reference",
                layout=layout,
            )
    if return_residuals:
        # raw forward — callers own the VJP (e.g. the ring merge)
        return flash_attention_fwd(
            q,
            k,
            v,
            causal=causal,
            sm_scale=scale,
            mask_fn=mask_fn,
            q_offset=q_offset,
            k_offset=k_offset,
            block_q=bq,
            block_k=bk,
            layout=layout,
            allow_fused=allow_fused,
        )
    if not isinstance(q_offset, int) or not isinstance(k_offset, int):
        raise ValueError(
            "the differentiable pallas path needs static int offsets; "
            "use flash_attention_fwd/_bwd for traced offsets"
        )
    return _flash_pallas(
        q, k, v, (q_offset, k_offset), causal, mask_fn, scale, bq, bk,
        layout, allow_fused
    )
