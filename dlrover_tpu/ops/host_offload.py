"""Host-offloaded optimizer state (the CPU-offload Adam analog).

Parity: the reference ships a CPU-offload Adam that keeps Adam moments
in host DRAM and streams them through the GPU per update
(atorch/atorch/optimizers/, SURVEY.md §2.3 optimizers row). The
TPU-native equivalent needs no custom optimizer at all: XLA's memory
spaces ("pinned_host") make host residency a SHARDING property. Any
optax transformation's state can live in host DRAM — ``jax.device_put``
with ``sharding.with_memory_kind("pinned_host")`` inside the jitted
step becomes a device↔host stream that XLA schedules/overlaps, and the
optimizer math itself is unchanged.

What it buys: HBM for the optimizer state (fp32 Adam = 8 bytes/param;
even the 8-bit moments are ~2.1 bytes/param) is freed for
params/activations — e.g. GPT-2 XL (1.557B) with plain fp32 Adam needs
~12.5 GB of moments that do not fit a 16 GB v5e chip next to params and
activations; offloaded, the config runs. The cost is one
state-sized h2d + d2h stream per optimizer update, amortized exactly
like the reference amortizes PCIe: gradient accumulation (strategy
``grad_accum``) makes it a per-K-microbatch cost.

Support matrix (measured on this stack, jax 0.9): on TPU the
streaming is real — in-jit ``device_put`` to a pinned-host sharding
verified to place and round-trip on the chip. The CPU backend cannot
execute placement annotations at all ("No registered implementation
for ... annotate_device_placement"), and its SPMD partitioner rejects
them multi-partition, so off-TPU the feature degrades to an explicit
NUMERIC NO-OP (:func:`placement_active` is False: shardings keep their
default memory kind, fetch/offload return their inputs). Tests and the
virtual-mesh dryrun exercise the full strategy plumbing; placement
assertions are TPU-only.

Composition: ``Strategy(offload_opt=True)`` (or the opt-lib entry
``"offload_opt"``) threads this through ``init_sharded_state`` (state
is *initialized directly into* host memory — it never materializes in
HBM) and ``build_train_step`` (fetch before ``tx.update``, offload the
new state after). Multi-device states keep their NamedShardings — only
the memory kind changes, so ZeRO-sharded moments offload shard-wise.
"""

from __future__ import annotations

import jax

HOST_KIND = "pinned_host"
DEVICE_KIND = "device"


_warned = False


def placement_active() -> bool:
    """True where memory-kind placement actually executes (TPU). Off
    TPU the offload API is a numeric no-op — warn once so a CPU run
    never silently believes its optimizer state left device memory."""
    if jax.default_backend() == "tpu":
        return True
    global _warned
    if not _warned:
        from dlrover_tpu.common.log import default_logger

        default_logger.info(
            "host_offload: %s backend cannot execute memory-kind "
            "placement; offload_opt_state is a numeric no-op here "
            "(real on TPU)", jax.default_backend(),
        )
        _warned = True
    return False


def offload_shardings(sharding_tree, shape_tree):
    """Sharding tree with tensor leaves moved to pinned-host memory.

    Scalars (optimizer step counts) STAY device-resident: the SPMD
    partitioner rejects host-placement annotations on replicated
    scalars ("Side-effect HLO must have sharding"), and a scalar holds
    no memory worth offloading. The partitioning itself is unchanged —
    ZeRO-sharded moments offload shard-wise."""
    if not placement_active():
        return sharding_tree
    return jax.tree_util.tree_map(
        lambda s, sh: s.with_memory_kind(HOST_KIND) if sh.ndim else s,
        sharding_tree,
        shape_tree,
    )


def offload_tree(tree, mixed_sharding_tree):
    """``device_put`` every leaf to its (possibly host-kind) sharding
    from :func:`offload_shardings`. Traceable: inside ``jit`` this
    lowers to an annotated d2h stream. No-op off TPU."""
    if not placement_active():
        return tree
    return jax.tree_util.tree_map(
        jax.device_put, tree, mixed_sharding_tree
    )


def fetch_tree(tree, sharding_tree):
    """Inverse of :func:`offload_tree`: stream host-resident leaves back
    into device (HBM) memory for compute. No-op off TPU."""
    if not placement_active():
        return tree
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s.with_memory_kind(DEVICE_KIND)),
        tree,
        sharding_tree,
    )
