"""Tiered (hybrid) embedding storage: hot rows in memory, cold on disk.

Parity: TFPlus hybrid embedding storage
(tfplus/kv_variable/kernels/hybrid_embedding/{table_manager.h:547,
storage_table.h:199, embedding_context.h:177}) — recommender vocabularies
outgrow host RAM, but access frequency is zipfian, so rarely-touched
rows live in a disk tier and fault back into the native hash table on
access. The TPU build keeps the C++ store as the hot tier and uses a
stdlib sqlite file as the cold tier (random-access by key, atomic,
survives restarts); policy lives in Python because eviction runs at
checkpoint cadence, not per step.

Semantics:
- ``gather``: keys absent from memory but present on disk are faulted
  in first (values AND optimizer slots travel); untouched keys follow
  the base store's init/zero rules. A row lives in exactly one tier,
  and the move happens atomically under the cold-tier lock.
- ``evict_cold(ts_limit)``: rows last touched before ``ts_limit`` move
  to disk and leave memory.
- ``export_state``: merges BOTH tiers — checkpoints must not silently
  drop evicted rows. Delta exports include cold rows evicted since the
  previous export (tracked by an eviction sequence number).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.embedding.store import ShardedKvEmbedding

_IN_CHUNK = 500  # sqlite host-parameter limit safety (999 on old builds)


class _RWLock:
    """Readers-writer lock: gathers run concurrently (the hot path, the
    C++ store handles its own per-shard locking); a tier move (eviction)
    excludes them so no gather can probe the hot tier before a row is
    evicted and re-insert it after (a TOCTOU that would shadow the cold
    copy with a freshly initialized row, losing trained values).

    Writer-preferring: new readers also wait while a writer is *queued*,
    otherwise continuously-overlapping gather traffic would starve
    eviction forever (and unbounded hot-tier growth is the exact failure
    the tier exists to prevent).
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class TieredKvEmbedding:
    def __init__(self, hot: ShardedKvEmbedding, cold_path: str):
        self.hot = hot
        self._conn = sqlite3.connect(cold_path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS rows ("
            "key INTEGER PRIMARY KEY, row BLOB, freq INTEGER, "
            "ts INTEGER, evict_seq INTEGER)"
        )
        self._lock = threading.Lock()
        self._tier_lock = _RWLock()  # gathers read / eviction writes
        self.dim = hot.dim
        self.row_floats = hot.dim * (1 + hot.num_slots)
        with self._lock:
            (mx,) = self._conn.execute(
                "SELECT COALESCE(MAX(evict_seq), 0) FROM rows"
            ).fetchone()
            (cnt,) = self._conn.execute(
                "SELECT COUNT(*) FROM rows"
            ).fetchone()
        self._evict_seq = mx
        self._exported_seq = 0  # cold rows > this are new to a delta
        # maintained counter: gather's fault-in probe short-circuits
        # while the cold tier is empty (the common pre-eviction state)
        self._cold_count = cnt

    # -- introspection --------------------------------------------------
    def hot_rows(self) -> int:
        return len(self.hot)

    def cold_rows(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM rows"
            ).fetchone()
        return n

    def __len__(self) -> int:
        # a row lives in exactly one tier, so the total is the sum
        # (dunders bypass __getattr__, so the passthrough can't serve
        # len())
        return self.hot_rows() + self.cold_rows()

    # -- fault-in -------------------------------------------------------
    def _fault_in(self, keys: np.ndarray) -> int:
        """Move any cold ``keys`` into the hot tier. Import-then-delete
        under the lock: a concurrent gather of the same key either waits
        here or finds the row already hot — never in neither tier."""
        if self._cold_count == 0:
            return 0  # nothing evicted: skip the extra meta probe
        f, _ = self.hot.meta(keys)  # reads only, no freq/ts bump
        missing = np.unique(keys[f < 0])
        if len(missing) == 0:
            return 0
        moved = 0
        with self._lock:
            for start in range(0, len(missing), _IN_CHUNK):
                chunk = [
                    int(k) for k in missing[start : start + _IN_CHUNK]
                ]
                qmarks = ",".join("?" * len(chunk))
                rows = self._conn.execute(
                    f"SELECT key, row, freq, ts FROM rows "
                    f"WHERE key IN ({qmarks})",
                    chunk,
                ).fetchall()
                if not rows:
                    continue
                k = np.array([r[0] for r in rows], np.int64)
                data = np.stack(
                    [np.frombuffer(r[1], np.float32) for r in rows]
                ).reshape(len(rows), self.row_floats)
                self.hot.import_state(
                    {
                        "keys": k,
                        "rows": data,
                        "freq": np.array([r[2] for r in rows], np.int64),
                        "ts": np.array([r[3] for r in rows], np.int64),
                    }
                )
                self._conn.execute(
                    f"DELETE FROM rows WHERE key IN "
                    f"({','.join('?' * len(rows))})",
                    [r[0] for r in rows],
                )
                moved += len(rows)
            self._conn.commit()
            self._cold_count -= moved
        return moved

    # -- public surface (hot-store API + fault-in) ---------------------
    def gather(self, keys, insert_missing: bool = True) -> np.ndarray:
        k = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        # read-side of the tier lock: without it a gather could probe the
        # hot tier just before eviction moves a row out and then
        # re-initialize it (insert_missing) just after — shadowing the
        # cold copy with a fresh row and losing the trained values
        self._tier_lock.acquire_read()
        try:
            self._fault_in(k)
            return self.hot.gather(k, insert_missing)
        finally:
            self._tier_lock.release_read()

    def __getattr__(self, name):
        # sparse_* updates / scatter pass through to the hot tier —
        # callers gather() first (which faults in), the same contract
        # the training loop already follows
        return getattr(self.hot, name)

    # -- checkpoint (both tiers!) ---------------------------------------
    def _cold_rows(self, min_seq: int = 0):
        with self._lock:
            return self._conn.execute(
                "SELECT key, row, freq, ts FROM rows WHERE evict_seq > ?",
                (min_seq,),
            ).fetchall()

    def export_state(
        self, since_versions: Optional[List[int]] = None
    ) -> Dict[str, np.ndarray]:
        """Hot export (full or delta) merged with the cold tier: full
        export carries every cold row; delta export carries cold rows
        evicted since the previous DELTA export — a checkpoint of a
        tiered store must never silently drop evicted rows.

        The delta cursor advances only on delta exports, so unrelated
        full exports (e.g. SparseTrainer's own save over the same
        store) cannot consume rows out of a checkpoint manager's delta
        stream. One delta consumer per store is the supported shape.
        Cold rows come FIRST so that when a key transiently has copies
        in both tiers the fresher hot row wins the last-wins import.

        The tier read lock is held across the cold+hot pair: it
        excludes eviction (hot→cold) mid-export, which with any
        ordering could move a row between the two snapshots so it lands
        in neither. Fault-in (cold→hot) runs under the same read side
        and stays legal because cold is exported BEFORE hot — a row
        that moves mid-export was already captured cold (and the hot
        copy, if also captured, wins the merge).
        """
        self._tier_lock.acquire_read()
        try:
            if since_versions:
                cold = self._cold_rows(self._exported_seq)
                self._exported_seq = self._evict_seq
            else:
                cold = self._cold_rows(0)
            state = self.hot.export_state(since_versions)
        finally:
            self._tier_lock.release_read()
        if cold:
            state = {
                "keys": np.concatenate(
                    [[r[0] for r in cold], state["keys"]]
                ).astype(np.int64),
                "rows": np.concatenate(
                    [
                        np.stack(
                            [
                                np.frombuffer(r[1], np.float32)
                                for r in cold
                            ]
                        ),
                        state["rows"].reshape(-1, self.row_floats),
                    ]
                ),
                "freq": np.concatenate(
                    [[r[2] for r in cold], state["freq"]]
                ).astype(np.int64),
                "ts": np.concatenate(
                    [[r[3] for r in cold], state["ts"]]
                ).astype(np.int64),
            }
        return state

    def warm_reshard(self, new_num_shards: int):
        """Move-only reshard of the hot store under the tier write
        lock. The sqlite cold tier is keyed by row key (not by shard),
        so cold rows stay valid across any hot shard-count change."""
        self._tier_lock.acquire_write()
        try:
            return self.hot.warm_reshard(new_num_shards)
        finally:
            self._tier_lock.release_write()

    # -- eviction -------------------------------------------------------
    def evict_cold(self, ts_limit: int) -> int:
        """Move rows last touched before ``ts_limit`` to disk.

        Processed one hot shard at a time (peak host memory = largest
        shard, not the whole table — the tier exists because RAM is
        short). A row touched between the snapshot and the in-memory
        eviction survives hot; its just-written stale disk copy is
        removed afterwards so no key ever has copies in both tiers.
        """
        total = 0
        self._evict_seq += 1
        for shard in self.hot.shards:
            # writer side of the tier lock, per shard (gathers of other
            # shards' keys proceed between shards): the snapshot →
            # insert → evict → stale-delete sequence must not interleave
            # with a gather's probe-then-insert of the same keys
            self._tier_lock.acquire_write()
            try:
                total += self._evict_shard(shard, ts_limit)
            finally:
                self._tier_lock.release_write()
        # settle the maintained counter to the exact value (it may have
        # overshot when INSERT OR REPLACE overwrote existing rows)
        with self._lock:
            (self._cold_count,) = self._conn.execute(
                "SELECT COUNT(*) FROM rows"
            ).fetchone()
        if total:
            logger.info(f"evicted {total} cold embedding rows to disk")
        return total

    def _evict_shard(self, shard, ts_limit: int) -> int:
        keys, rows, freq, ts = shard.export()
        cold = ts < ts_limit
        n = int(cold.sum())
        if n:
            idx = np.nonzero(cold)[0]
            with self._lock:
                self._conn.executemany(
                    "INSERT OR REPLACE INTO rows VALUES (?,?,?,?,?)",
                    [
                        (
                            int(keys[i]),
                            rows[i].tobytes(),
                            int(freq[i]),
                            int(ts[i]),
                            self._evict_seq,
                        )
                        for i in idx
                    ],
                )
                self._conn.commit()
                # keep the maintained counter >= the true cold count at
                # every point a gather can run (between per-shard write
                # sections): a false zero would short-circuit fault-in
                # for rows this shard just evicted. Transient overshoot
                # is safe; evict_cold settles the exact value at the end
                self._cold_count += n
            shard.evict_older_than(ts_limit)
            # rows touched in the snapshot→evict window stayed hot: drop
            # their (stale) disk copies before anything can re-export them
            survivors_f, _ = shard.meta(keys[idx])
            still_hot = keys[idx][survivors_f >= 0]
            if len(still_hot):
                with self._lock:
                    for start in range(0, len(still_hot), _IN_CHUNK):
                        chunk = [
                            int(k)
                            for k in still_hot[start : start + _IN_CHUNK]
                        ]
                        self._conn.execute(
                            f"DELETE FROM rows WHERE key IN "
                            f"({','.join('?' * len(chunk))})",
                            chunk,
                        )
                    self._conn.commit()
                    self._cold_count -= len(still_hot)
                n -= len(still_hot)
        return n

    def close(self):
        with self._lock:
            self._conn.close()


class NativeTieredKvEmbedding:
    """Hybrid embedding storage with the tier manager NATIVE (VERDICT
    r4 missing #6; parity: tfplus hybrid_embedding table_manager.h:547,
    storage_table.h:199): hot→cold eviction and cold→hot fault-in move
    rows entirely inside the C++ layer (one pass over the hash buckets
    into an append-only spill log per shard), so recommender-scale
    gathers with faulting never marshal rows through Python/sqlite.

    Same public surface and semantics as :class:`TieredKvEmbedding`
    (a row lives in exactly one tier; gathers fault in; ``export_state``
    merges both tiers, cold rows first so hot wins last-wins imports;
    delta exports carry cold rows evicted since the previous delta).
    The spill logs survive restarts — reopen with the same
    ``cold_path`` and the per-shard indices rebuild by one scan.
    """

    def __init__(self, hot: ShardedKvEmbedding, cold_path: str):
        from dlrover_tpu.ops.embedding.store import _load_library

        self.hot = hot
        self._lib = _load_library()
        self._tier_lock = _RWLock()
        self._cold_path = cold_path
        self.dim = hot.dim
        self.row_floats = hot.dim * (1 + hot.num_slots)
        self._cold = []
        self._open_cold_logs()
        # spill logs are keyed BY SHARD (fault-in routes by shard): a
        # reopen with fewer shards would silently strand the extra
        # logs' rows — refuse instead
        i = hot.num_shards
        while os.path.exists(f"{cold_path}.shard{i}"):
            extra = self._lib.cold_open(
                f"{cold_path}.shard{i}".encode(), self.row_floats
            )
            live = self._lib.cold_count(extra) if extra else 0
            if extra:
                self._lib.cold_close(extra)
            if live:
                self.close()
                raise ValueError(
                    f"spill log {cold_path}.shard{i} holds {live} live "
                    f"rows but the store has only {hot.num_shards} "
                    f"shards — reopen with the original shard count "
                    f"(or reshard() through a live store)"
                )
            i += 1
        self._evict_seq = max(
            (self._lib.cold_max_seq(h) for h in self._cold), default=0
        )
        self._exported_seq = 0

    def _open_cold_logs(self):
        for i in range(self.hot.num_shards):
            h = self._lib.cold_open(
                f"{self._cold_path}.shard{i}".encode(), self.row_floats
            )
            if not h:
                raise OSError(
                    f"cannot open cold spill log "
                    f"{self._cold_path}.shard{i}"
                )
            self._cold.append(h)

    def _drain_cold_to_hot(self):
        """Fault every cold row back hot and retire the spill logs —
        the shared prelude of both reshard flavors (per-shard logs
        cannot survive a shard-count change). Caller holds the tier
        write lock."""
        for shard, cold in zip(self.hot.shards, self._cold):
            n = self._lib.cold_count(cold)
            if n:
                keys = np.empty(n, np.int64)
                rows = np.empty((n, self.row_floats), np.float32)
                freq = np.empty(n, np.int64)
                ts = np.empty(n, np.int64)
                got = self._lib.cold_export(
                    cold, 0, keys, rows, freq, ts, n
                )
                if got < 0:
                    raise OSError("cold-tier read failed in reshard")
                moved = self._lib.kv_fault_from_cold(
                    shard._h, cold, keys[:got], got
                )
                if moved < 0:
                    raise OSError(
                        "cold-tier fault-in failed in reshard"
                    )
        old_n = len(self._cold)
        for h in self._cold:
            self._lib.cold_close(h)
        self._cold = []
        for i in range(old_n):
            os.unlink(f"{self._cold_path}.shard{i}")

    def reshard(self, new_num_shards: int):
        """Elastic reshard of a tiered store: every cold row faults back
        hot first (key→shard routing changes with the shard count, so
        per-shard spill logs cannot survive a reshard), the hot store
        reshards, and fresh empty logs are opened for the new layout."""
        self._tier_lock.acquire_write()
        try:
            self._drain_cold_to_hot()
            self.hot.reshard(new_num_shards)
            self._open_cold_logs()
        finally:
            self._tier_lock.release_write()

    def warm_reshard(self, new_num_shards: int):
        """Move-only reshard. Spill logs are keyed BY SHARD here, so
        cold rows fault back hot first (same rule as :meth:`reshard`),
        then the hot store moves only re-routed rows and fresh logs
        open for the new layout."""
        self._tier_lock.acquire_write()
        try:
            self._drain_cold_to_hot()
            report = self.hot.warm_reshard(new_num_shards)
            self._open_cold_logs()
            return report
        finally:
            self._tier_lock.release_write()

    # -- introspection --------------------------------------------------
    def hot_rows(self) -> int:
        return len(self.hot)

    def cold_rows(self) -> int:
        return sum(self._lib.cold_count(h) for h in self._cold)

    def __len__(self) -> int:
        return self.hot_rows() + self.cold_rows()

    # -- fault-in + gather ----------------------------------------------
    def _fault_in(self, keys: np.ndarray) -> int:
        moved = 0
        route = self.hot._route(keys)
        for i, (shard, cold) in enumerate(zip(self.hot.shards, self._cold)):
            if not self._lib.cold_count(cold):
                continue
            sk = np.ascontiguousarray(keys[route == i])
            if not len(sk):
                continue
            n = self._lib.kv_fault_from_cold(shard._h, cold, sk, len(sk))
            if n < 0:
                raise OSError("cold-tier fault-in failed (IO error)")
            moved += n
        return moved

    def gather(self, keys, insert_missing: bool = True) -> np.ndarray:
        k = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        # read-side of the tier lock (same TOCTOU as TieredKvEmbedding:
        # a gather must not re-initialize a key eviction just moved out)
        self._tier_lock.acquire_read()
        try:
            self._fault_in(k)
            return self.hot.gather(k, insert_missing)
        finally:
            self._tier_lock.release_read()

    def __getattr__(self, name):
        # sparse_* updates / scatter pass through to the hot tier —
        # callers gather() first (which faults in)
        return getattr(self.hot, name)

    # -- eviction -------------------------------------------------------
    def evict_cold(self, ts_limit: int) -> int:
        """Move rows last touched before ``ts_limit`` to the spill logs.
        The move is atomic per shard inside the native layer (bucket
        mutexes held across copy+erase), so no key ever has live copies
        in both tiers and no stale-copy cleanup pass is needed."""
        total = 0
        self._evict_seq += 1
        for shard, cold in zip(self.hot.shards, self._cold):
            self._tier_lock.acquire_write()
            try:
                n = self._lib.kv_evict_to_cold(
                    shard._h, cold, ts_limit, self._evict_seq
                )
                if n < 0:
                    raise OSError("cold-tier eviction failed (IO error)")
                total += n
            finally:
                self._tier_lock.release_write()
        if total:
            logger.info(
                f"evicted {total} cold embedding rows to spill logs"
            )
        return total

    # -- checkpoint (both tiers!) ---------------------------------------
    def _cold_export(self, since_seq: int):
        out = []
        for cold in self._cold:
            # buffers sized to the DELTA, not the whole tier (a 50M-row
            # cold tier must not allocate gigabytes for a 1k-row delta)
            while True:
                cap = self._lib.cold_export_count(cold, since_seq)
                if not cap:
                    break
                keys = np.empty(cap, np.int64)
                rows = np.empty((cap, self.row_floats), np.float32)
                freq = np.empty(cap, np.int64)
                ts = np.empty(cap, np.int64)
                n = self._lib.cold_export(
                    cold, since_seq, keys, rows, freq, ts, cap
                )
                if n == -1:
                    continue  # an eviction raced the count: retry
                if n < 0:
                    raise OSError("cold-tier export failed (IO error)")
                if n:
                    out.append((keys[:n], rows[:n], freq[:n], ts[:n]))
                break
        return out

    def export_state(
        self, since_versions: Optional[List[int]] = None
    ) -> Dict[str, np.ndarray]:
        # tier read lock across the cold+hot pair (same reasoning as
        # TieredKvEmbedding.export_state): eviction is excluded, and a
        # concurrent fault-in cannot drop a trained row from the
        # checkpoint because cold is snapshotted FIRST — a row moving
        # cold→hot mid-export was already captured, and the merged dict
        # puts cold first so a fresher hot copy wins the import
        self._tier_lock.acquire_read()
        try:
            if since_versions:
                cold = self._cold_export(self._exported_seq)
                self._exported_seq = self._evict_seq
            else:
                cold = self._cold_export(0)
            state = self.hot.export_state(since_versions)
        finally:
            self._tier_lock.release_read()
        if cold:
            ck = np.concatenate([c[0] for c in cold])
            cr = np.concatenate([c[1] for c in cold])
            cf = np.concatenate([c[2] for c in cold])
            ct = np.concatenate([c[3] for c in cold])
            state = {
                "keys": np.concatenate([ck, state["keys"]]).astype(
                    np.int64
                ),
                "rows": np.concatenate(
                    [cr, state["rows"].reshape(-1, self.row_floats)]
                ),
                "freq": np.concatenate([cf, state["freq"]]).astype(
                    np.int64
                ),
                "ts": np.concatenate([ct, state["ts"]]).astype(np.int64),
            }
        return state

    def close(self):
        for h in self._cold:
            self._lib.cold_close(h)
        self._cold = []


def three_tier_embedding(
    num_shards: int,
    dim: int,
    cold_path: str,
    num_slots: int = 1,
    seed: int = 0,
    init_scale: float = 0.05,
    hbm_budget_bytes: Optional[int] = None,
    native_cold: bool = True,
    version_service=None,
    **device_kwargs,
):
    """The full hierarchy in one call: HBM hot tier (device-resident,
    Pallas gather/scatter, bounded by ``hbm_budget_bytes``) over a host
    C++ store over a disk cold tier. The HBM→host boundary mirrors the
    host→disk one: bounded by a byte budget, spilled at checkpoint
    cadence (``DeviceSparseEmbedding.evict_to_host`` ≙ ``evict_cold``),
    rows fault back in on access with optimizer slots travelling.
    Returns a :class:`~dlrover_tpu.ops.embedding.device_tier.
    DeviceSparseEmbedding` whose ``host`` is the two-host-tier store.
    """
    from dlrover_tpu.ops.embedding.device_tier import (
        _DEF_HBM_BUDGET,
        DeviceSparseEmbedding,
    )

    hot = ShardedKvEmbedding(
        num_shards, dim, num_slots=num_slots, seed=seed,
        init_scale=init_scale, version_service=version_service,
    )
    tier_cls = (
        NativeTieredKvEmbedding if native_cold else TieredKvEmbedding
    )
    host = tier_cls(hot, cold_path)
    return DeviceSparseEmbedding(
        host,
        hbm_budget_bytes=(
            hbm_budget_bytes
            if hbm_budget_bytes is not None
            else _DEF_HBM_BUDGET
        ),
        **device_kwargs,
    )
