"""Tiered (hybrid) embedding storage: hot rows in memory, cold on disk.

Parity: TFPlus hybrid embedding storage
(tfplus/kv_variable/kernels/hybrid_embedding/{table_manager.h:547,
storage_table.h:199, embedding_context.h:177}) — recommender vocabularies
outgrow host RAM, but access frequency is zipfian, so rarely-touched
rows live in a disk tier and fault back into the native hash table on
access. The TPU build keeps the C++ store as the hot tier and uses a
stdlib sqlite file as the cold tier (random-access by key, atomic,
survives restarts); policy lives in Python because eviction runs at
checkpoint cadence, not per step.

Semantics:
- ``gather``: keys absent from memory but present on disk are faulted
  in first (values AND optimizer slots travel); untouched keys follow
  the base store's init/zero rules. A row lives in exactly one tier,
  and the move happens atomically under the cold-tier lock.
- ``evict_cold(ts_limit)``: rows last touched before ``ts_limit`` move
  to disk and leave memory.
- ``export_state``: merges BOTH tiers — checkpoints must not silently
  drop evicted rows. Delta exports include cold rows evicted since the
  previous export (tracked by an eviction sequence number).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.embedding.store import ShardedKvEmbedding

_IN_CHUNK = 500  # sqlite host-parameter limit safety (999 on old builds)


class _RWLock:
    """Readers-writer lock: gathers run concurrently (the hot path, the
    C++ store handles its own per-shard locking); a tier move (eviction)
    excludes them so no gather can probe the hot tier before a row is
    evicted and re-insert it after (a TOCTOU that would shadow the cold
    copy with a freshly initialized row, losing trained values).

    Writer-preferring: new readers also wait while a writer is *queued*,
    otherwise continuously-overlapping gather traffic would starve
    eviction forever (and unbounded hot-tier growth is the exact failure
    the tier exists to prevent).
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class TieredKvEmbedding:
    def __init__(self, hot: ShardedKvEmbedding, cold_path: str):
        self.hot = hot
        self._conn = sqlite3.connect(cold_path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS rows ("
            "key INTEGER PRIMARY KEY, row BLOB, freq INTEGER, "
            "ts INTEGER, evict_seq INTEGER)"
        )
        self._lock = threading.Lock()
        self._tier_lock = _RWLock()  # gathers read / eviction writes
        self.dim = hot.dim
        self.row_floats = hot.dim * (1 + hot.num_slots)
        with self._lock:
            (mx,) = self._conn.execute(
                "SELECT COALESCE(MAX(evict_seq), 0) FROM rows"
            ).fetchone()
            (cnt,) = self._conn.execute(
                "SELECT COUNT(*) FROM rows"
            ).fetchone()
        self._evict_seq = mx
        self._exported_seq = 0  # cold rows > this are new to a delta
        # maintained counter: gather's fault-in probe short-circuits
        # while the cold tier is empty (the common pre-eviction state)
        self._cold_count = cnt

    # -- introspection --------------------------------------------------
    def hot_rows(self) -> int:
        return len(self.hot)

    def cold_rows(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM rows"
            ).fetchone()
        return n

    def __len__(self) -> int:
        # a row lives in exactly one tier, so the total is the sum
        # (dunders bypass __getattr__, so the passthrough can't serve
        # len())
        return self.hot_rows() + self.cold_rows()

    # -- fault-in -------------------------------------------------------
    def _fault_in(self, keys: np.ndarray) -> int:
        """Move any cold ``keys`` into the hot tier. Import-then-delete
        under the lock: a concurrent gather of the same key either waits
        here or finds the row already hot — never in neither tier."""
        if self._cold_count == 0:
            return 0  # nothing evicted: skip the extra meta probe
        f, _ = self.hot.meta(keys)  # reads only, no freq/ts bump
        missing = np.unique(keys[f < 0])
        if len(missing) == 0:
            return 0
        moved = 0
        with self._lock:
            for start in range(0, len(missing), _IN_CHUNK):
                chunk = [
                    int(k) for k in missing[start : start + _IN_CHUNK]
                ]
                qmarks = ",".join("?" * len(chunk))
                rows = self._conn.execute(
                    f"SELECT key, row, freq, ts FROM rows "
                    f"WHERE key IN ({qmarks})",
                    chunk,
                ).fetchall()
                if not rows:
                    continue
                k = np.array([r[0] for r in rows], np.int64)
                data = np.stack(
                    [np.frombuffer(r[1], np.float32) for r in rows]
                ).reshape(len(rows), self.row_floats)
                self.hot.import_state(
                    {
                        "keys": k,
                        "rows": data,
                        "freq": np.array([r[2] for r in rows], np.int64),
                        "ts": np.array([r[3] for r in rows], np.int64),
                    }
                )
                self._conn.execute(
                    f"DELETE FROM rows WHERE key IN "
                    f"({','.join('?' * len(rows))})",
                    [r[0] for r in rows],
                )
                moved += len(rows)
            self._conn.commit()
            self._cold_count -= moved
        return moved

    # -- public surface (hot-store API + fault-in) ---------------------
    def gather(self, keys, insert_missing: bool = True) -> np.ndarray:
        k = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        # read-side of the tier lock: without it a gather could probe the
        # hot tier just before eviction moves a row out and then
        # re-initialize it (insert_missing) just after — shadowing the
        # cold copy with a fresh row and losing the trained values
        self._tier_lock.acquire_read()
        try:
            self._fault_in(k)
            return self.hot.gather(k, insert_missing)
        finally:
            self._tier_lock.release_read()

    def __getattr__(self, name):
        # sparse_* updates / scatter pass through to the hot tier —
        # callers gather() first (which faults in), the same contract
        # the training loop already follows
        return getattr(self.hot, name)

    # -- checkpoint (both tiers!) ---------------------------------------
    def _cold_rows(self, min_seq: int = 0):
        with self._lock:
            return self._conn.execute(
                "SELECT key, row, freq, ts FROM rows WHERE evict_seq > ?",
                (min_seq,),
            ).fetchall()

    def export_state(
        self, since_versions: Optional[List[int]] = None
    ) -> Dict[str, np.ndarray]:
        """Hot export (full or delta) merged with the cold tier: full
        export carries every cold row; delta export carries cold rows
        evicted since the previous DELTA export — a checkpoint of a
        tiered store must never silently drop evicted rows.

        The delta cursor advances only on delta exports, so unrelated
        full exports (e.g. SparseTrainer's own save over the same
        store) cannot consume rows out of a checkpoint manager's delta
        stream. One delta consumer per store is the supported shape.
        Cold rows come FIRST so that when a key transiently has copies
        in both tiers the fresher hot row wins the last-wins import.
        """
        state = self.hot.export_state(since_versions)
        if since_versions:
            cold = self._cold_rows(self._exported_seq)
            self._exported_seq = self._evict_seq
        else:
            cold = self._cold_rows(0)
        if cold:
            state = {
                "keys": np.concatenate(
                    [[r[0] for r in cold], state["keys"]]
                ).astype(np.int64),
                "rows": np.concatenate(
                    [
                        np.stack(
                            [
                                np.frombuffer(r[1], np.float32)
                                for r in cold
                            ]
                        ),
                        state["rows"].reshape(-1, self.row_floats),
                    ]
                ),
                "freq": np.concatenate(
                    [[r[2] for r in cold], state["freq"]]
                ).astype(np.int64),
                "ts": np.concatenate(
                    [[r[3] for r in cold], state["ts"]]
                ).astype(np.int64),
            }
        return state

    # -- eviction -------------------------------------------------------
    def evict_cold(self, ts_limit: int) -> int:
        """Move rows last touched before ``ts_limit`` to disk.

        Processed one hot shard at a time (peak host memory = largest
        shard, not the whole table — the tier exists because RAM is
        short). A row touched between the snapshot and the in-memory
        eviction survives hot; its just-written stale disk copy is
        removed afterwards so no key ever has copies in both tiers.
        """
        total = 0
        self._evict_seq += 1
        for shard in self.hot.shards:
            # writer side of the tier lock, per shard (gathers of other
            # shards' keys proceed between shards): the snapshot →
            # insert → evict → stale-delete sequence must not interleave
            # with a gather's probe-then-insert of the same keys
            self._tier_lock.acquire_write()
            try:
                total += self._evict_shard(shard, ts_limit)
            finally:
                self._tier_lock.release_write()
        # settle the maintained counter to the exact value (it may have
        # overshot when INSERT OR REPLACE overwrote existing rows)
        with self._lock:
            (self._cold_count,) = self._conn.execute(
                "SELECT COUNT(*) FROM rows"
            ).fetchone()
        if total:
            logger.info(f"evicted {total} cold embedding rows to disk")
        return total

    def _evict_shard(self, shard, ts_limit: int) -> int:
        keys, rows, freq, ts = shard.export()
        cold = ts < ts_limit
        n = int(cold.sum())
        if n:
            idx = np.nonzero(cold)[0]
            with self._lock:
                self._conn.executemany(
                    "INSERT OR REPLACE INTO rows VALUES (?,?,?,?,?)",
                    [
                        (
                            int(keys[i]),
                            rows[i].tobytes(),
                            int(freq[i]),
                            int(ts[i]),
                            self._evict_seq,
                        )
                        for i in idx
                    ],
                )
                self._conn.commit()
                # keep the maintained counter >= the true cold count at
                # every point a gather can run (between per-shard write
                # sections): a false zero would short-circuit fault-in
                # for rows this shard just evicted. Transient overshoot
                # is safe; evict_cold settles the exact value at the end
                self._cold_count += n
            shard.evict_older_than(ts_limit)
            # rows touched in the snapshot→evict window stayed hot: drop
            # their (stale) disk copies before anything can re-export them
            survivors_f, _ = shard.meta(keys[idx])
            still_hot = keys[idx][survivors_f >= 0]
            if len(still_hot):
                with self._lock:
                    for start in range(0, len(still_hot), _IN_CHUNK):
                        chunk = [
                            int(k)
                            for k in still_hot[start : start + _IN_CHUNK]
                        ]
                        self._conn.execute(
                            f"DELETE FROM rows WHERE key IN "
                            f"({','.join('?' * len(chunk))})",
                            chunk,
                        )
                    self._conn.commit()
                    self._cold_count -= len(still_hot)
                n -= len(still_hot)
        return n

    def close(self):
        with self._lock:
            self._conn.close()
