"""Tiered (hybrid) embedding storage: hot rows in memory, cold on disk.

Parity: TFPlus hybrid embedding storage
(tfplus/kv_variable/kernels/hybrid_embedding/{table_manager.h:547,
storage_table.h:199, embedding_context.h:177}) — recommender vocabularies
outgrow host RAM, but access frequency is zipfian, so rarely-touched
rows live in a disk tier and fault back into the native hash table on
access. The TPU build keeps the C++ store as the hot tier and uses a
stdlib sqlite file as the cold tier (random-access by key, atomic,
survives restarts); policy lives in Python because eviction runs at
checkpoint cadence, not per step.

Semantics:
- ``gather``: keys absent from memory but present on disk are faulted
  in first (values AND optimizer slots travel); untouched keys follow
  the base store's init/zero rules. A row lives in exactly one tier,
  and the move happens atomically under the cold-tier lock.
- ``evict_cold(ts_limit)``: rows last touched before ``ts_limit`` move
  to disk and leave memory.
- ``export_state``: merges BOTH tiers — checkpoints must not silently
  drop evicted rows. Delta exports include cold rows evicted since the
  previous export (tracked by an eviction sequence number).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.embedding.store import ShardedKvEmbedding

_IN_CHUNK = 500  # sqlite host-parameter limit safety (999 on old builds)


class TieredKvEmbedding:
    def __init__(self, hot: ShardedKvEmbedding, cold_path: str):
        self.hot = hot
        self._conn = sqlite3.connect(cold_path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS rows ("
            "key INTEGER PRIMARY KEY, row BLOB, freq INTEGER, "
            "ts INTEGER, evict_seq INTEGER)"
        )
        self._lock = threading.Lock()
        self.dim = hot.dim
        self.row_floats = hot.dim * (1 + hot.num_slots)
        with self._lock:
            (mx,) = self._conn.execute(
                "SELECT COALESCE(MAX(evict_seq), 0) FROM rows"
            ).fetchone()
        self._evict_seq = mx
        self._exported_seq = 0  # cold rows > this are new to a delta

    # -- introspection --------------------------------------------------
    def hot_rows(self) -> int:
        return len(self.hot)

    def cold_rows(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM rows"
            ).fetchone()
        return n

    # -- fault-in -------------------------------------------------------
    def _fault_in(self, keys: np.ndarray) -> int:
        """Move any cold ``keys`` into the hot tier. Import-then-delete
        under the lock: a concurrent gather of the same key either waits
        here or finds the row already hot — never in neither tier."""
        f, _ = self.hot.meta(keys)  # reads only, no freq/ts bump
        missing = np.unique(keys[f < 0])
        if len(missing) == 0:
            return 0
        moved = 0
        with self._lock:
            for start in range(0, len(missing), _IN_CHUNK):
                chunk = [
                    int(k) for k in missing[start : start + _IN_CHUNK]
                ]
                qmarks = ",".join("?" * len(chunk))
                rows = self._conn.execute(
                    f"SELECT key, row, freq, ts FROM rows "
                    f"WHERE key IN ({qmarks})",
                    chunk,
                ).fetchall()
                if not rows:
                    continue
                k = np.array([r[0] for r in rows], np.int64)
                data = np.stack(
                    [np.frombuffer(r[1], np.float32) for r in rows]
                ).reshape(len(rows), self.row_floats)
                self.hot.import_state(
                    {
                        "keys": k,
                        "rows": data,
                        "freq": np.array([r[2] for r in rows], np.int64),
                        "ts": np.array([r[3] for r in rows], np.int64),
                    }
                )
                self._conn.execute(
                    f"DELETE FROM rows WHERE key IN "
                    f"({','.join('?' * len(rows))})",
                    [r[0] for r in rows],
                )
                moved += len(rows)
            self._conn.commit()
        return moved

    # -- public surface (hot-store API + fault-in) ---------------------
    def gather(self, keys, insert_missing: bool = True) -> np.ndarray:
        k = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        self._fault_in(k)
        return self.hot.gather(k, insert_missing)

    def __getattr__(self, name):
        # sparse_* updates / scatter pass through to the hot tier —
        # callers gather() first (which faults in), the same contract
        # the training loop already follows
        return getattr(self.hot, name)

    # -- checkpoint (both tiers!) ---------------------------------------
    def _cold_rows(self, min_seq: int = 0):
        with self._lock:
            return self._conn.execute(
                "SELECT key, row, freq, ts FROM rows WHERE evict_seq > ?",
                (min_seq,),
            ).fetchall()

    def export_state(
        self, since_versions: Optional[List[int]] = None
    ) -> Dict[str, np.ndarray]:
        """Hot export (full or delta) merged with the cold tier: full
        export carries every cold row; delta export carries cold rows
        evicted since the previous export — a checkpoint of a tiered
        store must never silently drop evicted rows."""
        state = self.hot.export_state(since_versions)
        min_seq = self._exported_seq if since_versions else 0
        cold = self._cold_rows(min_seq)
        self._exported_seq = self._evict_seq
        if cold:
            state = {
                "keys": np.concatenate(
                    [state["keys"], [r[0] for r in cold]]
                ).astype(np.int64),
                "rows": np.concatenate(
                    [
                        state["rows"].reshape(-1, self.row_floats),
                        np.stack(
                            [
                                np.frombuffer(r[1], np.float32)
                                for r in cold
                            ]
                        ),
                    ]
                ),
                "freq": np.concatenate(
                    [state["freq"], [r[2] for r in cold]]
                ).astype(np.int64),
                "ts": np.concatenate(
                    [state["ts"], [r[3] for r in cold]]
                ).astype(np.int64),
            }
        return state

    # -- eviction -------------------------------------------------------
    def evict_cold(self, ts_limit: int) -> int:
        """Move rows last touched before ``ts_limit`` to disk."""
        state = self.hot.export_state()
        cold = state["ts"] < ts_limit
        n = int(cold.sum())
        if n:
            self._evict_seq += 1
            with self._lock:
                self._conn.executemany(
                    "INSERT OR REPLACE INTO rows VALUES (?,?,?,?,?)",
                    [
                        (
                            int(state["keys"][i]),
                            state["rows"][i].tobytes(),
                            int(state["freq"][i]),
                            int(state["ts"][i]),
                            self._evict_seq,
                        )
                        for i in np.nonzero(cold)[0]
                    ],
                )
                self._conn.commit()
            for shard in self.hot.shards:
                shard.evict_older_than(ts_limit)
            logger.info(f"evicted {n} cold embedding rows to disk")
        return n

    def close(self):
        with self._lock:
            self._conn.close()
