// KvEmbeddingStore: native hash-table embedding store for elastic sparse
// training on TPU hosts.
//
// Parity: tfplus KvVariable (tfplus/tfplus/kv_variable/kernels/
// kv_variable_ops.cc:1164, kv_variable.h:1021, hashmap.h:1030) and its
// fused sparse optimizers (kernels/training_ops.cc). Re-designed for the
// TPU recommender shape: the table lives in HOST memory (TPU HBM holds
// the dense model; embedding rows are gathered host-side and fed to the
// chip per step), so the native layer is a plain shared library driven
// through ctypes — no TF op registry, no resource-variable machinery.
//
// Design:
// - NUM_BUCKETS internal shards, each its own mutex + open hash map:
//   concurrent gathers/updates from data-loader threads don't serialize.
// - A row = [value(dim) | slot_0(dim) | ... ]: optimizer slots
//   (Adagrad/Momentum accumulators) live beside the value, so a fused
//   sparse update touches one cache-resident row (the reference keeps
//   slots in separate KvVariables and pays two lookups).
// - Every row carries frequency, last-access timestamp and the global
//   mutation version at its last write: full export = export(since=0),
//   delta export = export(since=v) (parity: FullOrDeltaImport/Export
//   ops, kv_variable_ops.cc:733) — the primitive elastic resharding and
//   incremental checkpoints are built on.
// - Missing keys on gather are initialized from a splitmix64 hash of
//   (seed, key): deterministic across shards/restarts, no RNG state.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumBuckets = 64;

struct Row {
  std::vector<float> data;  // dim * (1 + num_slots)
  int64_t freq = 0;
  int64_t ts = 0;
  uint64_t version = 0;
};

struct Bucket {
  std::mutex mu;
  std::unordered_map<int64_t, Row> map;
};

struct Store {
  int64_t dim;
  int num_slots;
  uint64_t seed;
  float init_scale;
  Bucket buckets[kNumBuckets];
  std::mutex version_mu;
  uint64_t version = 0;  // global mutation counter

  uint64_t next_version() {
    std::lock_guard<std::mutex> g(version_mu);
    return ++version;
  }
  int64_t row_floats() const { return dim * (1 + num_slots); }
  Bucket& bucket(int64_t key) {
    // splitmix-style mix so sequential ids spread across buckets
    uint64_t h = static_cast<uint64_t>(key) * 0x9E3779B97F4A7C15ULL;
    return buckets[(h >> 32) % kNumBuckets];
  }
};

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

void init_row(const Store* s, int64_t key, float* out) {
  // deterministic pseudo-normal init (sum of uniforms), scaled
  uint64_t state = splitmix64(s->seed ^ static_cast<uint64_t>(key));
  for (int64_t i = 0; i < s->dim; ++i) {
    float acc = 0.f;
    for (int k = 0; k < 4; ++k) {
      state = splitmix64(state);
      acc += static_cast<float>(state >> 40) /
             static_cast<float>(1ULL << 24);  // [0,1)
    }
    out[i] = (acc - 2.0f) * 1.7320508f * s->init_scale;  // ~N(0, scale)
  }
  std::memset(out + s->dim, 0, sizeof(float) * s->dim * s->num_slots);
}

Row& find_or_create(Store* s, Bucket& b, int64_t key, int64_t now,
                    bool* created) {
  auto it = b.map.find(key);
  if (it == b.map.end()) {
    Row row;
    row.data.resize(s->row_floats());
    init_row(s, key, row.data.data());
    row.ts = now;
    row.version = s->next_version();
    it = b.map.emplace(key, std::move(row)).first;
    if (created) *created = true;
  } else if (created) {
    *created = false;
  }
  return it->second;
}

}  // namespace

extern "C" {

void* kv_create(int64_t dim, int num_slots, uint64_t seed,
                float init_scale) {
  Store* s = new Store();
  s->dim = dim;
  s->num_slots = num_slots;
  s->seed = seed;
  s->init_scale = init_scale;
  return s;
}

void kv_free(void* h) { delete static_cast<Store*>(h); }

int64_t kv_size(void* h) {
  Store* s = static_cast<Store*>(h);
  int64_t n = 0;
  for (auto& b : s->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    n += static_cast<int64_t>(b.map.size());
  }
  return n;
}

uint64_t kv_version(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->version_mu);
  return s->version;
}

// Gather values (NOT slots) for n keys into out[n*dim]. insert_missing:
// initialize absent keys (GatherOrInsert); otherwise absent keys read 0.
// Bumps freq and ts of every touched key.
void kv_gather(void* h, const int64_t* keys, int64_t n, float* out,
               int insert_missing, int64_t now) {
  Store* s = static_cast<Store*>(h);
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    if (insert_missing) {
      Row& row = find_or_create(s, b, keys[i], now, nullptr);
      row.freq++;
      row.ts = now;
      std::memcpy(out + i * s->dim, row.data.data(),
                  sizeof(float) * s->dim);
    } else {
      auto it = b.map.find(keys[i]);
      if (it == b.map.end()) {
        std::memset(out + i * s->dim, 0, sizeof(float) * s->dim);
      } else {
        it->second.freq++;
        it->second.ts = now;
        std::memcpy(out + i * s->dim, it->second.data.data(),
                    sizeof(float) * s->dim);
      }
    }
  }
}

// op: 0=update 1=add 2=sub 3=mul 4=div 5=min 6=max   (parity:
// KvVariableScatter{Update,Add,Sub,Mul,Div,Min,Max}V2)
void kv_scatter(void* h, const int64_t* keys, int64_t n,
                const float* vals, int op, int64_t now) {
  Store* s = static_cast<Store*>(h);
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* v = row.data.data();
    const float* u = vals + i * s->dim;
    for (int64_t d = 0; d < s->dim; ++d) {
      switch (op) {
        case 0: v[d] = u[d]; break;
        case 1: v[d] += u[d]; break;
        case 2: v[d] -= u[d]; break;
        case 3: v[d] *= u[d]; break;
        case 4: v[d] /= u[d]; break;
        case 5: v[d] = v[d] < u[d] ? v[d] : u[d]; break;
        case 6: v[d] = v[d] > u[d] ? v[d] : u[d]; break;
      }
    }
    row.ts = now;
    row.version = s->next_version();
  }
}

// Fused sparse Adagrad (parity: training_ops.cc KvSparseApplyAdagrad):
// slot0 += g^2 ; value -= lr * g / (sqrt(slot0) + eps). Requires
// num_slots >= 1. Duplicate keys in one batch accumulate sequentially
// (same as the reference's row-locked apply).
void kv_sparse_adagrad(void* h, const int64_t* keys, int64_t n,
                       const float* grads, float lr, float eps,
                       int64_t now) {
  Store* s = static_cast<Store*>(h);
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* v = row.data.data();
    float* acc = v + s->dim;
    const float* gr = grads + i * s->dim;
    for (int64_t d = 0; d < s->dim; ++d) {
      acc[d] += gr[d] * gr[d];
      v[d] -= lr * gr[d] / (__builtin_sqrtf(acc[d]) + eps);
    }
    row.ts = now;
    row.version = s->next_version();
  }
}

// Fused sparse momentum-SGD: slot0 = momentum*slot0 + g;
// value -= lr*slot0. Requires num_slots >= 1.
void kv_sparse_momentum(void* h, const int64_t* keys, int64_t n,
                        const float* grads, float lr, float momentum,
                        int64_t now) {
  Store* s = static_cast<Store*>(h);
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* v = row.data.data();
    float* m = v + s->dim;
    const float* gr = grads + i * s->dim;
    for (int64_t d = 0; d < s->dim; ++d) {
      m[d] = momentum * m[d] + gr[d];
      v[d] -= lr * m[d];
    }
    row.ts = now;
    row.version = s->next_version();
  }
}

// Fused sparse Adam (parity: training_ops.cc group/sparse Adam family):
// slot0 = m, slot1 = v; bias-corrected update using the caller's step
// count. Requires num_slots >= 2.
void kv_sparse_adam(void* h, const int64_t* keys, int64_t n,
                    const float* grads, float lr, float beta1,
                    float beta2, float eps, int64_t step, int64_t now) {
  Store* s = static_cast<Store*>(h);
  const float bc1 = 1.0f - __builtin_powf(beta1, (float)step);
  const float bc2 = 1.0f - __builtin_powf(beta2, (float)step);
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* w = row.data.data();
    float* m = w + s->dim;
    float* v = w + 2 * s->dim;
    const float* gr = grads + i * s->dim;
    for (int64_t d = 0; d < s->dim; ++d) {
      m[d] = beta1 * m[d] + (1.0f - beta1) * gr[d];
      v[d] = beta2 * v[d] + (1.0f - beta2) * gr[d] * gr[d];
      const float mhat = m[d] / bc1;
      const float vhat = v[d] / bc2;
      w[d] -= lr * mhat / (__builtin_sqrtf(vhat) + eps);
    }
    row.ts = now;
    row.version = s->next_version();
  }
}

// Fused sparse group-lasso FTRL (parity: the "Group Adam/Adagrad" paper
// ops in training_ops.cc / sparse_group_ftrl.py): per-coordinate FTRL
// accumulators (slot0 = n, slot1 = z) with an L2,1 group penalty that
// zeroes WHOLE embedding rows of rarely-useful keys — the sparsity the
// reference's recommender workloads rely on. Requires num_slots >= 2.
void kv_sparse_group_ftrl(void* h, const int64_t* keys, int64_t nkeys,
                          const float* grads, float alpha, float beta,
                          float l1, float l21, int64_t now) {
  Store* s = static_cast<Store*>(h);
  for (int64_t i = 0; i < nkeys; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* w = row.data.data();
    float* acc = w + s->dim;  // n accumulator
    float* z = w + 2 * s->dim;
    const float* gr = grads + i * s->dim;
    // First FTRL touch of a row created by gather (random init, zero
    // accumulators): seed z so the proximal solve reproduces the
    // initial weights (z = -w*(beta+sqrt(n))/alpha, TF Ftrl's init
    // convention; exact when l1=l21=0). Without this the random init
    // would leak into z as a permanent bias AND be discarded from w.
    {
      bool untouched = true;
      for (int64_t d = 0; d < s->dim && untouched; ++d)
        untouched = acc[d] == 0.0f && z[d] == 0.0f;
      if (untouched) {
        for (int64_t d = 0; d < s->dim; ++d) z[d] = -w[d] * beta / alpha;
      }
    }
    // accumulate, then solve the proximal step for the whole row
    for (int64_t d = 0; d < s->dim; ++d) {
      const float n_new = acc[d] + gr[d] * gr[d];
      const float sigma =
          (__builtin_sqrtf(n_new) - __builtin_sqrtf(acc[d])) / alpha;
      z[d] += gr[d] - sigma * w[d];
      acc[d] = n_new;
    }
    // per-coordinate soft threshold (l1), collect row norm of the
    // thresholded pseudo-weights
    float norm2 = 0.0f;
    for (int64_t d = 0; d < s->dim; ++d) {
      const float zd = z[d];
      const float sgn = zd > 0.f ? 1.f : (zd < 0.f ? -1.f : 0.f);
      const float mag = zd * sgn - l1;  // |z| - l1
      const float u = mag > 0.f ? sgn * mag : 0.f;
      w[d] = u;  // stash u; scaled below
      norm2 += u * u;
    }
    const float norm = __builtin_sqrtf(norm2);
    const float group = norm > l21 ? (1.0f - l21 / norm) : 0.0f;
    for (int64_t d = 0; d < s->dim; ++d) {
      const float denom = (beta + __builtin_sqrtf(acc[d])) / alpha;
      w[d] = -group * w[d] / denom;
    }
    row.ts = now;
    row.version = s->next_version();
  }
}

// Fused sparse Group Adam (parity: training_ops.cc
// KvVariableGroupSparseApplyAdamNewV2, python group_adam.py — the
// "Adaptive Optimizers with Sparse Group Lasso" construction): Adam
// moments drive an FTRL-style linear accumulator, and the weight is the
// CLOSED-FORM solution of the proximal problem with elementwise L1,
// ridge L2 and row-group L2,1 penalties — rarely-useful keys collapse to
// exact zero rows. Slots: 0=linear, 1=m, 2=v (num_slots >= 3).
void kv_sparse_group_adam(void* h, const int64_t* keys, int64_t nkeys,
                          const float* grads, float lr, float beta1,
                          float beta2, float eps, float l1, float l2,
                          float l21, int64_t step, int64_t now) {
  Store* s = static_cast<Store*>(h);
  const float b1p = __builtin_powf(beta1, (float)step);
  const float b2p = __builtin_powf(beta2, (float)step);
  const float alpha = __builtin_sqrtf(1.0f - b2p) / (1.0f - b1p);
  const float l21_norm =
      l21 * __builtin_sqrtf(static_cast<float>(s->dim));
  for (int64_t i = 0; i < nkeys; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* w = row.data.data();
    float* linear = w + s->dim;
    float* m = w + 2 * s->dim;
    float* v = w + 3 * s->dim;
    const float* gr = grads + i * s->dim;
    float norm2 = 0.0f;
    for (int64_t d = 0; d < s->dim; ++d) {
      m[d] = beta1 * m[d] + (1.0f - beta1) * gr[d];
      const float new_v =
          beta2 * v[d] + (1.0f - beta2) * gr[d] * gr[d];
      // the reference drops eps from the sigma term after step 1
      // (beta1 > beta1^t), keeping it only for the t=1 edge
      const float sigma =
          (__builtin_sqrtf(new_v) - __builtin_sqrtf(v[d]) +
           (beta1 > b1p ? 0.0f : eps)) /
          lr;
      linear[d] += alpha * m[d] - sigma * w[d];
      v[d] = new_v;
      const float clipped =
          linear[d] > l1 ? l1 : (linear[d] < -l1 ? -l1 : linear[d]);
      const float u = clipped - linear[d];  // soft-thresholded direction
      w[d] = u;  // stash; scaled (or zeroed) below
      norm2 += u * u;
    }
    const float norm = __builtin_sqrtf(norm2);
    if (norm > l21_norm) {
      const float scale = 1.0f - l21_norm / norm;
      for (int64_t d = 0; d < s->dim; ++d) {
        const float y =
            (__builtin_sqrtf(v[d]) + eps) / lr + 2.0f * l2;
        w[d] = w[d] * scale / y;
      }
    } else {
      // group lasso zeroes the whole row (the reference blacklists the
      // key; here the zero row IS the tombstone — eviction reclaims it)
      std::memset(w, 0, sizeof(float) * s->dim);
    }
    row.ts = now;
    row.version = s->next_version();
  }
}

// Fused sparse LAMB (parity: training_ops.cc sparse Lamb family /
// python lamb_optimizer.py): Adam direction with decoupled weight decay,
// rescaled per EMBEDDING ROW by the trust ratio ||w|| / ||update|| — the
// row is the natural "layer" of a kv table. Slots: 0=m, 1=v.
void kv_sparse_lamb(void* h, const int64_t* keys, int64_t nkeys,
                    const float* grads, float lr, float beta1,
                    float beta2, float eps, float weight_decay,
                    int64_t step, int64_t now) {
  Store* s = static_cast<Store*>(h);
  const float bc1 = 1.0f - __builtin_powf(beta1, (float)step);
  const float bc2 = 1.0f - __builtin_powf(beta2, (float)step);
  std::vector<float> r(s->dim);
  for (int64_t i = 0; i < nkeys; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* w = row.data.data();
    float* m = w + s->dim;
    float* v = w + 2 * s->dim;
    const float* gr = grads + i * s->dim;
    float wnorm2 = 0.0f, rnorm2 = 0.0f;
    for (int64_t d = 0; d < s->dim; ++d) {
      m[d] = beta1 * m[d] + (1.0f - beta1) * gr[d];
      v[d] = beta2 * v[d] + (1.0f - beta2) * gr[d] * gr[d];
      const float mhat = m[d] / bc1;
      const float vhat = v[d] / bc2;
      r[d] = mhat / (__builtin_sqrtf(vhat) + eps) + weight_decay * w[d];
      wnorm2 += w[d] * w[d];
      rnorm2 += r[d] * r[d];
    }
    const float wn = __builtin_sqrtf(wnorm2);
    const float rn = __builtin_sqrtf(rnorm2);
    const float ratio = (wn > 0.0f && rn > 0.0f) ? wn / rn : 1.0f;
    for (int64_t d = 0; d < s->dim; ++d) w[d] -= lr * ratio * r[d];
    row.ts = now;
    row.version = s->next_version();
  }
}

// Fused sparse AdaBelief (parity: atorch low-bit optim family's
// AdaBelief / tfplus adabelief): second moment tracks the variance of
// the gradient around its EMA — (g - m)^2 — so steps grow where the
// gradient is consistent and shrink where it is noisy.
// Slots: 0=m, 1=s.
void kv_sparse_adabelief(void* h, const int64_t* keys, int64_t nkeys,
                         const float* grads, float lr, float beta1,
                         float beta2, float eps, int64_t step,
                         int64_t now) {
  Store* s_ = static_cast<Store*>(h);
  const float bc1 = 1.0f - __builtin_powf(beta1, (float)step);
  const float bc2 = 1.0f - __builtin_powf(beta2, (float)step);
  for (int64_t i = 0; i < nkeys; ++i) {
    Bucket& b = s_->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s_, b, keys[i], now, nullptr);
    float* w = row.data.data();
    float* m = w + s_->dim;
    float* sv = w + 2 * s_->dim;
    const float* gr = grads + i * s_->dim;
    for (int64_t d = 0; d < s_->dim; ++d) {
      m[d] = beta1 * m[d] + (1.0f - beta1) * gr[d];
      const float diff = gr[d] - m[d];
      sv[d] = beta2 * sv[d] + (1.0f - beta2) * diff * diff + eps;
      const float mhat = m[d] / bc1;
      const float shat = sv[d] / bc2;
      w[d] -= lr * mhat / (__builtin_sqrtf(shat) + eps);
    }
    row.ts = now;
    row.version = s_->next_version();
  }
}

// Fused sparse AMSGrad (parity: tfplus adam family with amsgrad):
// Adam with a monotone max over the second moment, so the effective LR
// never grows back after a large gradient. Slots: 0=m, 1=v, 2=vmax
// (num_slots >= 3).
void kv_sparse_amsgrad(void* h, const int64_t* keys, int64_t nkeys,
                       const float* grads, float lr, float beta1,
                       float beta2, float eps, int64_t step,
                       int64_t now) {
  Store* s = static_cast<Store*>(h);
  const float bc1 = 1.0f - __builtin_powf(beta1, (float)step);
  const float bc2 = 1.0f - __builtin_powf(beta2, (float)step);
  for (int64_t i = 0; i < nkeys; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = find_or_create(s, b, keys[i], now, nullptr);
    float* w = row.data.data();
    float* m = w + s->dim;
    float* v = w + 2 * s->dim;
    float* vmax = w + 3 * s->dim;
    const float* gr = grads + i * s->dim;
    for (int64_t d = 0; d < s->dim; ++d) {
      m[d] = beta1 * m[d] + (1.0f - beta1) * gr[d];
      v[d] = beta2 * v[d] + (1.0f - beta2) * gr[d] * gr[d];
      if (v[d] > vmax[d]) vmax[d] = v[d];
      const float mhat = m[d] / bc1;
      const float vhat = vmax[d] / bc2;
      w[d] -= lr * mhat / (__builtin_sqrtf(vhat) + eps);
    }
    row.ts = now;
    row.version = s->next_version();
  }
}

// Export rows whose version > since (0 = full export). Two-phase: count,
// then fill caller-allocated buffers. Rows: full row incl. slots.
int64_t kv_export_count(void* h, uint64_t since) {
  Store* s = static_cast<Store*>(h);
  int64_t n = 0;
  for (auto& b : s->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    for (auto& kv : b.map)
      if (kv.second.version > since) ++n;
  }
  return n;
}

int64_t kv_export(void* h, uint64_t since, int64_t* keys_out,
                  float* rows_out, int64_t* freq_out, int64_t* ts_out,
                  int64_t capacity) {
  Store* s = static_cast<Store*>(h);
  int64_t rf = s->row_floats();
  int64_t n = 0;
  for (auto& b : s->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    for (auto& kv : b.map) {
      if (kv.second.version <= since) continue;
      if (n >= capacity) return -1;  // caller raced a writer; retry
      keys_out[n] = kv.first;
      std::memcpy(rows_out + n * rf, kv.second.data.data(),
                  sizeof(float) * rf);
      freq_out[n] = kv.second.freq;
      ts_out[n] = kv.second.ts;
      ++n;
    }
  }
  return n;
}

// Import rows (full row incl. slots). Overwrites existing keys.
void kv_import(void* h, const int64_t* keys, int64_t n,
               const float* rows, const int64_t* freq,
               const int64_t* ts) {
  Store* s = static_cast<Store*>(h);
  int64_t rf = s->row_floats();
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    Row& row = b.map[keys[i]];
    row.data.assign(rows + i * rf, rows + (i + 1) * rf);
    row.freq = freq ? freq[i] : 0;
    row.ts = ts ? ts[i] : 0;
    row.version = s->next_version();
  }
}

// List every live key (no values, no freq/ts bump): the cheap first
// pass of a warm reshard — 8 bytes per row instead of the full
// row_floats export, so ownership can be recomputed over millions of
// rows before any row data moves. Returns the count, or -1 when the
// caller's buffer raced a concurrent insert and is too small (retry
// with a fresh kv_size).
int64_t kv_export_keys(void* h, int64_t* keys_out, int64_t capacity) {
  Store* s = static_cast<Store*>(h);
  int64_t n = 0;
  for (auto& b : s->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    for (auto& kv : b.map) {
      if (n >= capacity) return -1;
      keys_out[n++] = kv.first;
    }
  }
  return n;
}

// Export full rows (values + slots + freq/ts) for exactly the given
// keys — the move leg of a warm reshard and the device hot tier's
// fault-in read. Absent keys zero their row and mark freq_out = -1;
// freq/ts are NOT bumped (this is a state read, not an access).
// Returns the number of keys found.
int64_t kv_export_rows(void* h, const int64_t* keys, int64_t n,
                       float* rows_out, int64_t* freq_out,
                       int64_t* ts_out) {
  Store* s = static_cast<Store*>(h);
  int64_t rf = s->row_floats();
  int64_t found = 0;
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    auto it = b.map.find(keys[i]);
    if (it == b.map.end()) {
      std::memset(rows_out + i * rf, 0, sizeof(float) * rf);
      freq_out[i] = -1;
      ts_out[i] = -1;
    } else {
      std::memcpy(rows_out + i * rf, it->second.data.data(),
                  sizeof(float) * rf);
      freq_out[i] = it->second.freq;
      ts_out[i] = it->second.ts;
      ++found;
    }
  }
  return found;
}

// Delete exactly the given keys (the hand-off leg of a warm reshard:
// rows exported to their new owner leave the old shard). Returns the
// number actually removed.
int64_t kv_delete_keys(void* h, const int64_t* keys, int64_t n) {
  Store* s = static_cast<Store*>(h);
  int64_t removed = 0;
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    removed += static_cast<int64_t>(b.map.erase(keys[i]));
  }
  return removed;
}

// Evict rows last touched before ts_limit (parity:
// KvVariableDeleteWithTimestamp). Returns evicted count.
int64_t kv_delete_before_timestamp(void* h, int64_t ts_limit) {
  Store* s = static_cast<Store*>(h);
  int64_t n = 0;
  for (auto& b : s->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    for (auto it = b.map.begin(); it != b.map.end();) {
      if (it->second.ts < ts_limit) {
        it = b.map.erase(it);
        ++n;
      } else {
        ++it;
      }
    }
  }
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native cold tier (hybrid embedding spill store).
//
// Parity: tfplus hybrid_embedding keeps the TIER MANAGER native
// (table_manager.h:547, storage_table.h:199): the hot->cold eviction and
// cold->hot fault-in move rows entirely inside C++ — one pass over the
// buckets, no per-row Python/sqlite marshaling — which is what makes
// recommender-scale gathers with faulting viable. The cold tier is an
// append-only spill log (fixed header + row floats; tombstones on
// fault-in) with an in-memory index rebuilt by a single scan at open, so
// it survives restarts and compacts naturally on rewrite.
//
// Concurrency contract: the embedding wrapper's tier lock (tiered.py
// _RWLock) serializes tier MOVES against gathers; within that contract
// the cold store needs only its own mutex for file/index access.
// ---------------------------------------------------------------------------

namespace {

struct ColdRecHeader {
  int64_t key;
  int64_t freq;
  int64_t ts;
  int64_t seq;
  int64_t kind;  // 1 = row payload follows, 0 = tombstone
};

struct ColdEnt {
  int64_t offset;  // file offset of the row payload
  int64_t freq;
  int64_t ts;
  int64_t seq;
};

struct ColdStore {
  std::mutex mu;
  std::FILE* f = nullptr;
  int64_t row_floats = 0;
  int64_t max_seq = 0;
  std::unordered_map<int64_t, ColdEnt> index;
};

bool cold_append(ColdStore* c, const ColdRecHeader& hdr,
                 const float* row) {
  std::fseek(c->f, 0, SEEK_END);
  if (std::fwrite(&hdr, sizeof(hdr), 1, c->f) != 1) return false;
  if (hdr.kind == 1) {
    int64_t payload = std::ftell(c->f);
    if (std::fwrite(row, sizeof(float),
                    static_cast<size_t>(c->row_floats),
                    c->f) != static_cast<size_t>(c->row_floats))
      return false;
    c->index[hdr.key] = ColdEnt{payload, hdr.freq, hdr.ts, hdr.seq};
  } else {
    c->index.erase(hdr.key);
  }
  if (hdr.seq > c->max_seq) c->max_seq = hdr.seq;
  return true;
}

}  // namespace

extern "C" {

// Open (creating if absent) a spill log; rebuilds the index by scan.
// Returns nullptr when the file cannot be opened or is malformed for
// this row size.
void* cold_open(const char* path, int64_t row_floats) {
  std::FILE* f = std::fopen(path, "r+b");
  if (!f) f = std::fopen(path, "w+b");
  if (!f) return nullptr;
  ColdStore* c = new ColdStore();
  c->f = f;
  c->row_floats = row_floats;
  std::fseek(f, 0, SEEK_END);
  const int64_t fsize = std::ftell(f);
  const int64_t row_bytes =
      static_cast<int64_t>(sizeof(float)) * row_floats;
  std::fseek(f, 0, SEEK_SET);
  int64_t off = 0;
  ColdRecHeader hdr;
  // crash recovery: a record torn mid-append (writer died between the
  // header and the payload landing) is the un-completed tail of the
  // log — drop it and every byte after it, keep everything before.
  // (fseek past EOF SUCCEEDS on binary streams, so truncation must be
  // detected against the byte count, not a seek failure.)
  while (off + static_cast<int64_t>(sizeof(hdr)) <= fsize) {
    if (std::fread(&hdr, sizeof(hdr), 1, f) != 1) break;
    off += static_cast<int64_t>(sizeof(hdr));
    if (hdr.kind == 1) {
      if (off + row_bytes > fsize) break;  // torn payload: drop tail
      c->index[hdr.key] = ColdEnt{off, hdr.freq, hdr.ts, hdr.seq};
      off += row_bytes;
      std::fseek(f, static_cast<long>(off), SEEK_SET);
    } else {
      c->index.erase(hdr.key);
    }
    if (hdr.seq > c->max_seq) c->max_seq = hdr.seq;
  }
  return c;
}

void cold_close(void* h) {
  ColdStore* c = static_cast<ColdStore*>(h);
  if (c->f) std::fclose(c->f);
  delete c;
}

int64_t cold_count(void* h) {
  ColdStore* c = static_cast<ColdStore*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return static_cast<int64_t>(c->index.size());
}

int64_t cold_max_seq(void* h) {
  ColdStore* c = static_cast<ColdStore*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return c->max_seq;
}

// Move every hot row last touched before ts_limit into the cold log,
// stamped with eviction sequence `seq`. Returns the number moved (or
// -1 on a write error; rows stay hot on failure).
int64_t kv_evict_to_cold(void* hot_h, void* cold_h, int64_t ts_limit,
                         int64_t seq) {
  Store* s = static_cast<Store*>(hot_h);
  ColdStore* c = static_cast<ColdStore*>(cold_h);
  int64_t moved = 0;
  std::lock_guard<std::mutex> cg(c->mu);
  for (auto& b : s->buckets) {
    std::lock_guard<std::mutex> g(b.mu);
    for (auto it = b.map.begin(); it != b.map.end();) {
      if (it->second.ts >= ts_limit) {
        ++it;
        continue;
      }
      ColdRecHeader hdr{it->first, it->second.freq, it->second.ts, seq,
                        1};
      if (!cold_append(c, hdr, it->second.data.data())) return -1;
      it = b.map.erase(it);
      ++moved;
    }
  }
  std::fflush(c->f);
  return moved;
}

// Fault keys present in the cold tier back into the hot store (values
// AND optimizer slots travel; freq/ts preserved), tombstoning them in
// the log. Keys not in the cold tier are ignored. Returns the number
// faulted in (or -1 on an IO error).
int64_t kv_fault_from_cold(void* hot_h, void* cold_h,
                           const int64_t* keys, int64_t n) {
  Store* s = static_cast<Store*>(hot_h);
  ColdStore* c = static_cast<ColdStore*>(cold_h);
  int64_t rf = s->row_floats();
  std::vector<float> row(static_cast<size_t>(rf));
  int64_t moved = 0;
  std::lock_guard<std::mutex> cg(c->mu);
  for (int64_t i = 0; i < n; ++i) {
    auto it = c->index.find(keys[i]);
    if (it == c->index.end()) continue;
    if (std::fseek(c->f, static_cast<long>(it->second.offset),
                   SEEK_SET) != 0)
      return -1;
    if (std::fread(row.data(), sizeof(float), static_cast<size_t>(rf),
                   c->f) != static_cast<size_t>(rf))
      return -1;
    {
      Bucket& b = s->bucket(keys[i]);
      std::lock_guard<std::mutex> g(b.mu);
      Row& r = b.map[keys[i]];
      r.data.assign(row.begin(), row.end());
      r.freq = it->second.freq;
      r.ts = it->second.ts;
      r.version = s->next_version();
    }
    ColdRecHeader tomb{keys[i], 0, 0, it->second.seq, 0};
    if (!cold_append(c, tomb, nullptr)) return -1;
    ++moved;
  }
  std::fflush(c->f);
  return moved;
}

// Export live cold rows with seq > since into caller buffers; returns
// the count, or -1 if capacity is too small, -2 on IO error.
int64_t cold_export(void* h, int64_t since, int64_t* keys_out,
                    float* rows_out, int64_t* freq_out, int64_t* ts_out,
                    int64_t capacity) {
  ColdStore* c = static_cast<ColdStore*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  int64_t n = 0;
  for (auto& kv : c->index) {
    if (kv.second.seq <= since) continue;
    if (n >= capacity) return -1;
    if (std::fseek(c->f, static_cast<long>(kv.second.offset),
                   SEEK_SET) != 0)
      return -2;
    if (std::fread(rows_out + n * c->row_floats, sizeof(float),
                   static_cast<size_t>(c->row_floats),
                   c->f) != static_cast<size_t>(c->row_floats))
      return -2;
    keys_out[n] = kv.first;
    freq_out[n] = kv.second.freq;
    ts_out[n] = kv.second.ts;
    ++n;
  }
  return n;
}

// Count of live cold rows with seq > since (delta-export sizing —
// mirrors kv_export_count for the hot tier).
int64_t cold_export_count(void* h, int64_t since) {
  ColdStore* c = static_cast<ColdStore*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  int64_t n = 0;
  for (auto& kv : c->index)
    if (kv.second.seq > since) ++n;
  return n;
}

// Read freq/ts metadata for keys (absent keys: -1).
void kv_meta(void* h, const int64_t* keys, int64_t n, int64_t* freq_out,
             int64_t* ts_out) {
  Store* s = static_cast<Store*>(h);
  for (int64_t i = 0; i < n; ++i) {
    Bucket& b = s->bucket(keys[i]);
    std::lock_guard<std::mutex> g(b.mu);
    auto it = b.map.find(keys[i]);
    if (it == b.map.end()) {
      freq_out[i] = -1;
      ts_out[i] = -1;
    } else {
      freq_out[i] = it->second.freq;
      ts_out[i] = it->second.ts;
    }
  }
}

}  // extern "C"
