"""ctypes binding + sharded wrapper for the native KvEmbeddingStore.

Parity: the python face of tfplus KvVariable
(tfplus/kv_variable/python/ops/kv_variable_ops.py) — gather/insert,
scatter math ops, fused sparse optimizers, frequency/timestamp metadata,
full/delta export-import — plus the elastic resharding the reference
builds from FullOrDeltaImport/Export. The shared library is compiled
from kv_store.cc on first use (g++ is in the image; no pybind11) and
cached beside the source keyed by the source hash.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "kv_store.cc")
_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None


# stable per-process fallback build dir when the cache dir is not
# writable (read-only site-packages / locked-down shared FS): one extra
# compile per process, not one per _build_library call
_FALLBACK_BUILD_DIR: Optional[str] = None


def _build_library() -> str:
    global _FALLBACK_BUILD_DIR
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.environ.get(
        "DLROVER_TPU_KV_CACHE", os.path.join(_HERE, "_build")
    )
    lib_name = f"libdlrover_kv_{digest}.so"
    candidates = [cache_dir]
    if _FALLBACK_BUILD_DIR is not None:
        candidates.append(_FALLBACK_BUILD_DIR)
    for d in candidates:
        cached = os.path.join(d, lib_name)
        if os.path.exists(cached):
            return cached
    # the try covers ONLY the writability probe: a failing COMPILE
    # (missing g++, source error) must propagate untouched instead of
    # being misreported as "cache dir not writable" and pointlessly
    # retried in a tmpdir
    try:
        os.makedirs(cache_dir, exist_ok=True)
        # probe writability up front: a read-only dir would otherwise
        # surface as an opaque g++ "cannot open output file" error
        probe = os.path.join(cache_dir, f".probe.{os.getpid()}")
        with open(probe, "w"):
            pass
        os.remove(probe)
    except OSError as e:
        # read-only cache dir (read-only install, locked-down shared
        # FS): fall back to a process-stable tmpdir instead of
        # crashing at import time — the PR-6 topology-cache
        # read-only-fs tolerance, applied to the build cache
        if _FALLBACK_BUILD_DIR is None:
            import tempfile

            _FALLBACK_BUILD_DIR = tempfile.mkdtemp(
                prefix="dlrover_kv_build_"
            )
        logger.warning(
            f"kv build cache {cache_dir} is not writable ({e}); "
            f"building into {_FALLBACK_BUILD_DIR} instead (set "
            f"DLROVER_TPU_KV_CACHE to a writable dir to cache builds)"
        )
        return _compile_into(_FALLBACK_BUILD_DIR, lib_name)
    return _compile_into(cache_dir, lib_name)


def _compile_into(cache_dir: str, lib_name: str) -> str:
    lib_path = os.path.join(cache_dir, lib_name)
    if os.path.exists(lib_path):
        return lib_path
    tmp = f"{lib_path}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, _SRC,
    ]
    logger.info(f"building kv embedding library: {' '.join(cmd)}")
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, lib_path)
    return lib_path


def _load_library() -> ctypes.CDLL:
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        lib = ctypes.CDLL(_build_library())
        i64, u64, f32 = ctypes.c_int64, ctypes.c_uint64, ctypes.c_float
        p = ctypes.c_void_p
        I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        F32P = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.kv_create.restype = p
        lib.kv_create.argtypes = [i64, ctypes.c_int, u64, f32]
        lib.kv_free.argtypes = [p]
        lib.kv_size.restype = i64
        lib.kv_size.argtypes = [p]
        lib.kv_version.restype = u64
        lib.kv_version.argtypes = [p]
        lib.kv_gather.argtypes = [p, I64P, i64, F32P, ctypes.c_int, i64]
        lib.kv_scatter.argtypes = [p, I64P, i64, F32P, ctypes.c_int, i64]
        lib.kv_sparse_adagrad.argtypes = [p, I64P, i64, F32P, f32, f32, i64]
        lib.kv_sparse_momentum.argtypes = [p, I64P, i64, F32P, f32, f32, i64]
        lib.kv_sparse_adam.argtypes = [
            p, I64P, i64, F32P, f32, f32, f32, f32, i64, i64,
        ]
        lib.kv_sparse_group_ftrl.argtypes = [
            p, I64P, i64, F32P, f32, f32, f32, f32, i64,
        ]
        lib.kv_sparse_group_adam.argtypes = [
            p, I64P, i64, F32P, f32, f32, f32, f32, f32, f32, f32,
            i64, i64,
        ]
        lib.kv_sparse_lamb.argtypes = [
            p, I64P, i64, F32P, f32, f32, f32, f32, f32, i64, i64,
        ]
        lib.kv_sparse_adabelief.argtypes = [
            p, I64P, i64, F32P, f32, f32, f32, f32, i64, i64,
        ]
        lib.kv_sparse_amsgrad.argtypes = [
            p, I64P, i64, F32P, f32, f32, f32, f32, i64, i64,
        ]
        lib.kv_export_count.restype = i64
        lib.kv_export_count.argtypes = [p, u64]
        lib.kv_export.restype = i64
        lib.kv_export.argtypes = [p, u64, I64P, F32P, I64P, I64P, i64]
        lib.kv_import.argtypes = [p, I64P, i64, F32P, I64P, I64P]
        lib.kv_delete_before_timestamp.restype = i64
        lib.kv_delete_before_timestamp.argtypes = [p, i64]
        # warm-reshard / device-tier primitives
        lib.kv_export_keys.restype = i64
        lib.kv_export_keys.argtypes = [p, I64P, i64]
        lib.kv_export_rows.restype = i64
        lib.kv_export_rows.argtypes = [p, I64P, i64, F32P, I64P, I64P]
        lib.kv_delete_keys.restype = i64
        lib.kv_delete_keys.argtypes = [p, I64P, i64]
        lib.kv_meta.argtypes = [p, I64P, i64, I64P, I64P]
        # native cold tier (hybrid embedding spill store)
        lib.cold_open.restype = p
        lib.cold_open.argtypes = [ctypes.c_char_p, i64]
        lib.cold_close.argtypes = [p]
        lib.cold_count.restype = i64
        lib.cold_count.argtypes = [p]
        lib.cold_max_seq.restype = i64
        lib.cold_max_seq.argtypes = [p]
        lib.kv_evict_to_cold.restype = i64
        lib.kv_evict_to_cold.argtypes = [p, p, i64, i64]
        lib.kv_fault_from_cold.restype = i64
        lib.kv_fault_from_cold.argtypes = [p, p, I64P, i64]
        lib.cold_export.restype = i64
        lib.cold_export.argtypes = [p, i64, I64P, F32P, I64P, I64P, i64]
        lib.cold_export_count.restype = i64
        lib.cold_export_count.argtypes = [p, i64]
        _LIB = lib
        return lib


_SCATTER_OPS = {
    "update": 0, "add": 1, "sub": 2, "mul": 3, "div": 4,
    "min": 5, "max": 6,
}


@dataclass
class WarmReshardReport:
    """What a warm reshard moved (mirrors ckpt.reshard.ReshardReport:
    the per-axis story for embedding shards is old→new shard count and
    the mover fraction)."""

    old_shards: int
    new_shards: int
    total_rows: int
    moved_rows: int
    bytes_moved: int
    elapsed_s: float

    @property
    def moved_fraction(self) -> float:
        return self.moved_rows / self.total_rows if self.total_rows else 0.0

    def describe(self) -> str:
        return (
            f"shards {self.old_shards}->{self.new_shards}: "
            f"{self.moved_rows}/{self.total_rows} rows moved "
            f"({100.0 * self.moved_fraction:.1f}%, "
            f"{self.bytes_moved / 1e6:.2f} MB) in "
            f"{self.elapsed_s * 1e3:.1f} ms"
        )


def _now() -> int:
    return int(time.time())


class KvEmbeddingStore:
    """One native hash-table shard: key (int64) → row
    [value(dim) | slots(num_slots × dim)]."""

    def __init__(
        self,
        dim: int,
        num_slots: int = 1,
        seed: int = 0,
        init_scale: float = 0.05,
    ):
        self.dim = dim
        self.num_slots = num_slots
        self.seed = seed
        self.init_scale = init_scale
        self._lib = _load_library()
        self._h = self._lib.kv_create(dim, num_slots, seed, init_scale)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.kv_free(h)

    # -- core ----------------------------------------------------------
    def __len__(self) -> int:
        return self._lib.kv_size(self._h)

    @property
    def version(self) -> int:
        return self._lib.kv_version(self._h)

    @property
    def row_floats(self) -> int:
        return self.dim * (1 + self.num_slots)

    @staticmethod
    def _keys(keys) -> np.ndarray:
        return np.ascontiguousarray(keys, dtype=np.int64).ravel()

    def gather(self, keys, insert_missing: bool = True) -> np.ndarray:
        """Lookup rows' values [n, dim]; missing keys are initialized
        (GatherOrInsert) or read as zeros. Bumps freq/timestamp."""
        k = self._keys(keys)
        out = np.empty((len(k), self.dim), np.float32)
        self._lib.kv_gather(
            self._h, k, len(k), out, int(insert_missing), _now()
        )
        return out

    def scatter(self, keys, values, op: str = "update"):
        k = self._keys(keys)
        self._lib.kv_scatter(
            self._h, k, len(k), self._grads(k, values),
            _SCATTER_OPS[op], _now(),
        )

    def sparse_adagrad(self, keys, grads, lr: float, eps: float = 1e-8):
        k = self._keys(keys)
        self._lib.kv_sparse_adagrad(
            self._h, k, len(k), self._grads(k, grads), lr, eps, _now()
        )

    def sparse_momentum(self, keys, grads, lr: float, momentum: float = 0.9):
        k = self._keys(keys)
        self._lib.kv_sparse_momentum(
            self._h, k, len(k), self._grads(k, grads), lr, momentum, _now()
        )

    def sparse_adam(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
    ):
        """Fused sparse Adam (slots: m, v; needs num_slots >= 2).
        ``step`` is the 1-based update count for bias correction."""
        if self.num_slots < 2:
            raise ValueError("sparse_adam needs num_slots >= 2 (m, v)")
        self._check_step(step)
        k = self._keys(keys)
        self._lib.kv_sparse_adam(
            self._h, k, len(k), self._grads(k, grads), lr, beta1,
            beta2, eps, step, _now(),
        )

    def sparse_group_ftrl(
        self,
        keys,
        grads,
        alpha: float = 0.05,
        beta: float = 1.0,
        l1: float = 0.0,
        l21: float = 0.0,
    ):
        """Fused group-lasso FTRL (slots: n, z; needs num_slots >= 2).
        ``l21`` zeroes whole rows whose thresholded signal is weak —
        the group sparsity of the reference's recommender optimizers."""
        if self.num_slots < 2:
            raise ValueError("sparse_group_ftrl needs num_slots >= 2")
        k = self._keys(keys)
        self._lib.kv_sparse_group_ftrl(
            self._h, k, len(k), self._grads(k, grads), alpha, beta,
            l1, l21, _now(),
        )

    def _grads(self, k, grads) -> np.ndarray:
        return np.ascontiguousarray(grads, dtype=np.float32).reshape(
            len(k), self.dim
        )

    @staticmethod
    def _check_step(step: int):
        if step < 1:
            raise ValueError(f"step must be >= 1 (got {step})")

    def sparse_group_adam(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
        l1: float = 0.0,
        l2: float = 0.0,
        l21: float = 0.0,
    ):
        """Fused Group Adam (slots: linear, m, v; needs num_slots >= 3)
        — Adam moments feeding an FTRL-style linear accumulator with a
        closed-form L1/L2/L2,1 proximal solve; ``l21 > 0`` zeroes whole
        rows (parity: training_ops.cc GroupSparseApplyAdamNewV2,
        group_adam.py:272)."""
        if self.num_slots < 3:
            raise ValueError(
                "sparse_group_adam needs num_slots >= 3 (linear, m, v)"
            )
        self._check_step(step)
        k = self._keys(keys)
        self._lib.kv_sparse_group_adam(
            self._h, k, len(k), self._grads(k, grads), lr, beta1,
            beta2, eps, l1, l2, l21, step, _now(),
        )

    def sparse_lamb(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-6,
        weight_decay: float = 0.0,
    ):
        """Fused sparse LAMB (slots: m, v; needs num_slots >= 2): Adam
        direction + decoupled decay, rescaled per embedding row by the
        trust ratio ||w||/||update||."""
        if self.num_slots < 2:
            raise ValueError("sparse_lamb needs num_slots >= 2 (m, v)")
        self._check_step(step)
        k = self._keys(keys)
        self._lib.kv_sparse_lamb(
            self._h, k, len(k), self._grads(k, grads), lr, beta1,
            beta2, eps, weight_decay, step, _now(),
        )

    def sparse_adabelief(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-12,
    ):
        """Fused sparse AdaBelief (slots: m, s; needs num_slots >= 2):
        the second moment tracks (g - m)^2 — gradient variance around
        its EMA — instead of g^2."""
        if self.num_slots < 2:
            raise ValueError("sparse_adabelief needs num_slots >= 2")
        self._check_step(step)
        k = self._keys(keys)
        self._lib.kv_sparse_adabelief(
            self._h, k, len(k), self._grads(k, grads), lr, beta1,
            beta2, eps, step, _now(),
        )

    def sparse_amsgrad(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
    ):
        """Fused sparse AMSGrad (slots: m, v, vmax; needs
        num_slots >= 3): Adam with a monotone max on the second moment."""
        if self.num_slots < 3:
            raise ValueError(
                "sparse_amsgrad needs num_slots >= 3 (m, v, vmax)"
            )
        self._check_step(step)
        k = self._keys(keys)
        self._lib.kv_sparse_amsgrad(
            self._h, k, len(k), self._grads(k, grads), lr, beta1,
            beta2, eps, step, _now(),
        )

    def meta(self, keys) -> Tuple[np.ndarray, np.ndarray]:
        """(frequency, last_access_ts) per key; -1 for absent keys."""
        k = self._keys(keys)
        freq = np.empty(len(k), np.int64)
        ts = np.empty(len(k), np.int64)
        self._lib.kv_meta(self._h, k, len(k), freq, ts)
        return freq, ts

    def export_keys(self) -> np.ndarray:
        """Every live key — 8 bytes per row, no values, no freq/ts
        bump: the cheap ownership pass of a warm reshard."""
        while True:
            cap = len(self) + 64  # headroom vs concurrent inserts
            keys = np.empty(cap, np.int64)
            n = self._lib.kv_export_keys(self._h, keys, cap)
            if n >= 0:  # -1 = an insert raced the sizing; retry
                return keys[:n]

    def export_rows(
        self, keys
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Full rows (values + slots), freq, ts and a presence mask for
        exactly ``keys``. Unlike gather this is a STATE read: absent
        keys are NOT created, freq/ts are NOT bumped, and optimizer
        slots travel — the move leg of a warm reshard and the device
        hot tier's fault-in."""
        k = self._keys(keys)
        rows = np.empty((len(k), self.row_floats), np.float32)
        freq = np.empty(len(k), np.int64)
        ts = np.empty(len(k), np.int64)
        self._lib.kv_export_rows(self._h, k, len(k), rows, freq, ts)
        return rows, freq, ts, freq >= 0

    def delete_keys(self, keys) -> int:
        """Remove exactly ``keys``; returns the number removed."""
        k = self._keys(keys)
        return self._lib.kv_delete_keys(self._h, k, len(k))

    def evict_older_than(self, ts_limit: int) -> int:
        return self._lib.kv_delete_before_timestamp(self._h, ts_limit)

    # -- export / import (elastic resharding + incremental ckpt) -------
    def export(self, since_version: int = 0):
        """(keys, rows[n, row_floats], freq, ts) for rows modified after
        ``since_version`` (0 = everything)."""
        while True:
            cap = self._lib.kv_export_count(self._h, since_version)
            keys = np.empty(cap, np.int64)
            rows = np.empty((cap, self.row_floats), np.float32)
            freq = np.empty(cap, np.int64)
            ts = np.empty(cap, np.int64)
            n = self._lib.kv_export(
                self._h, since_version, keys, rows, freq, ts, cap
            )
            if n >= 0:  # -1 = writer raced the count; retry
                return keys[:n], rows[:n], freq[:n], ts[:n]

    def import_rows(self, keys, rows, freq=None, ts=None):
        k = self._keys(keys)
        r = np.ascontiguousarray(rows, dtype=np.float32).reshape(
            len(k), self.row_floats
        )
        f = (
            np.ascontiguousarray(freq, dtype=np.int64)
            if freq is not None
            else np.zeros(len(k), np.int64)
        )
        t = (
            np.ascontiguousarray(ts, dtype=np.int64)
            if ts is not None
            else np.zeros(len(k), np.int64)
        )
        self._lib.kv_import(self._h, k, len(k), r, f, t)


class ShardedKvEmbedding:
    """Key-hash-routed shard set with elastic resharding.

    Parity: the reference reshards PS embedding tables through
    KvVariable full/delta export-import driven by cluster-version bumps
    (elastic_ps.py + checkpoint_manager.py). ``reshard(new_num)``
    re-routes every row to its new home with no loss/duplication; an
    ``ElasticPsService``-compatible ``version_service`` is bumped on
    every reshard so trainers can detect the topology change.
    """

    def __init__(
        self,
        num_shards: int,
        dim: int,
        num_slots: int = 1,
        seed: int = 0,
        init_scale: float = 0.05,
        version_service=None,
    ):
        self.dim = dim
        self.num_slots = num_slots
        self.seed = seed
        self.init_scale = init_scale
        self._version_service = version_service
        self.shards: List[KvEmbeddingStore] = [
            KvEmbeddingStore(dim, num_slots, seed, init_scale)
            for _ in range(num_shards)
        ]

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    def _route(self, keys: np.ndarray) -> np.ndarray:
        return self._route_n(keys, self.num_shards)

    @staticmethod
    def _route_n(keys: np.ndarray, num_shards: int) -> np.ndarray:
        # same mix as the native bucket router, mod num_shards
        h = keys.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        return ((h >> np.uint64(17)) % np.uint64(num_shards)).astype(
            np.int64
        )

    def gather(self, keys, insert_missing: bool = True) -> np.ndarray:
        k = KvEmbeddingStore._keys(keys)
        out = np.empty((len(k), self.dim), np.float32)
        route = self._route(k)
        for sid in range(self.num_shards):
            mask = route == sid
            if mask.any():
                out[mask] = self.shards[sid].gather(
                    k[mask], insert_missing
                )
        return out

    def _per_shard(self, fn_name: str, keys, values, *args):
        k = KvEmbeddingStore._keys(keys)
        v = np.ascontiguousarray(values, dtype=np.float32).reshape(
            len(k), self.dim
        )
        route = self._route(k)
        for sid in range(self.num_shards):
            mask = route == sid
            if mask.any():
                getattr(self.shards[sid], fn_name)(k[mask], v[mask], *args)

    def scatter(self, keys, values, op: str = "update"):
        self._per_shard("scatter", keys, values, op)

    def sparse_adagrad(self, keys, grads, lr: float, eps: float = 1e-8):
        self._per_shard("sparse_adagrad", keys, grads, lr, eps)

    def sparse_momentum(self, keys, grads, lr: float, momentum: float = 0.9):
        self._per_shard("sparse_momentum", keys, grads, lr, momentum)

    def sparse_adam(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
    ):
        self._per_shard(
            "sparse_adam", keys, grads, lr, step, beta1, beta2, eps
        )

    def sparse_group_ftrl(
        self,
        keys,
        grads,
        alpha: float = 0.05,
        beta: float = 1.0,
        l1: float = 0.0,
        l21: float = 0.0,
    ):
        self._per_shard(
            "sparse_group_ftrl", keys, grads, alpha, beta, l1, l21
        )

    def sparse_group_adam(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
        l1: float = 0.0,
        l2: float = 0.0,
        l21: float = 0.0,
    ):
        self._per_shard(
            "sparse_group_adam", keys, grads, lr, step, beta1, beta2,
            eps, l1, l2, l21,
        )

    def sparse_lamb(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-6,
        weight_decay: float = 0.0,
    ):
        self._per_shard(
            "sparse_lamb", keys, grads, lr, step, beta1, beta2, eps,
            weight_decay,
        )

    def sparse_adabelief(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-12,
    ):
        self._per_shard(
            "sparse_adabelief", keys, grads, lr, step, beta1, beta2, eps
        )

    def sparse_amsgrad(
        self,
        keys,
        grads,
        lr: float,
        step: int,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
    ):
        self._per_shard(
            "sparse_amsgrad", keys, grads, lr, step, beta1, beta2, eps
        )

    def meta(self, keys) -> Tuple[np.ndarray, np.ndarray]:
        """(frequency, last_access_ts) per key; -1 for absent keys.
        Reads only — never bumps freq/ts."""
        k = KvEmbeddingStore._keys(keys)
        freqs = np.empty(len(k), np.int64)
        tss = np.empty(len(k), np.int64)
        route = self._route(k)
        for sid in range(self.num_shards):
            mask = route == sid
            if mask.any():
                f, t = self.shards[sid].meta(k[mask])
                freqs[mask] = f
                tss[mask] = t
        return freqs, tss

    def export_keys(self) -> np.ndarray:
        """Every live key across all shards (no values, no bumps)."""
        parts = [s.export_keys() for s in self.shards]
        return (
            np.concatenate(parts) if parts else np.empty(0, np.int64)
        )

    def import_rows(self, keys, rows, freq=None, ts=None):
        """Route-and-import full rows (values + slots) — the write leg
        of device-tier spills and warm-reshard moves."""
        k = KvEmbeddingStore._keys(keys)
        if len(k) == 0:
            return
        r = np.ascontiguousarray(rows, dtype=np.float32).reshape(
            len(k), self.dim * (1 + self.num_slots)
        )
        f = (
            np.ascontiguousarray(freq, dtype=np.int64)
            if freq is not None
            else np.zeros(len(k), np.int64)
        )
        t = (
            np.ascontiguousarray(ts, dtype=np.int64)
            if ts is not None
            else np.zeros(len(k), np.int64)
        )
        route = self._route(k)
        for sid in range(self.num_shards):
            mask = route == sid
            if mask.any():
                self.shards[sid].import_rows(
                    k[mask], r[mask], f[mask], t[mask]
                )

    def export_rows(
        self, keys
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Full rows/freq/ts/presence for exactly ``keys`` (state
        read: nothing created, freq/ts untouched, slots travel)."""
        k = KvEmbeddingStore._keys(keys)
        rows = np.zeros((len(k), self.dim * (1 + self.num_slots)), np.float32)
        freq = np.full(len(k), -1, np.int64)
        ts = np.full(len(k), -1, np.int64)
        present = np.zeros(len(k), bool)
        route = self._route(k)
        for sid in range(self.num_shards):
            mask = route == sid
            if mask.any():
                r, f, t, p = self.shards[sid].export_rows(k[mask])
                rows[mask], freq[mask], ts[mask] = r, f, t
                present[mask] = p
        return rows, freq, ts, present

    def delete_keys(self, keys) -> int:
        k = KvEmbeddingStore._keys(keys)
        route = self._route(k)
        removed = 0
        for sid in range(self.num_shards):
            mask = route == sid
            if mask.any():
                removed += self.shards[sid].delete_keys(k[mask])
        return removed

    # -- elastic resharding --------------------------------------------
    def warm_reshard(self, new_num_shards: int) -> "WarmReshardReport":
        """N → M shards moving ONLY rows whose route changes.

        The cold :meth:`reshard` exports every row once and re-imports
        the whole table into fresh stores; under a resize that is the
        embedding analogue of a full checkpoint restore. The warm path
        is the ElasWave-style per-dimension reconfiguration: existing
        shard objects with index < M are kept in place, each old shard
        lists its keys (8 bytes/row), recomputes ownership under M, and
        exports/deletes only the movers — rows whose home is unchanged
        never leave their store. Bumps the PS cluster version exactly
        like :meth:`reshard` so consumers detect the topology change.
        """
        old_n = self.num_shards
        t0 = time.perf_counter()
        total = len(self)
        moved = 0
        bytes_moved = 0
        rf = self.dim * (1 + self.num_slots)
        if new_num_shards == old_n:
            return WarmReshardReport(
                old_shards=old_n, new_shards=new_num_shards,
                total_rows=total, moved_rows=0, bytes_moved=0,
                elapsed_s=time.perf_counter() - t0,
            )
        for _ in range(old_n, new_num_shards):
            self.shards.append(
                KvEmbeddingStore(
                    self.dim, self.num_slots, self.seed, self.init_scale
                )
            )
        # movers are computed against the OLD shard list: shards past M
        # dissolve entirely, kept shards surrender only re-routed keys
        for sid in range(old_n):
            shard = self.shards[sid]
            keys = shard.export_keys()
            if len(keys) == 0:
                continue
            dest = self._route_n(keys, new_num_shards)
            mover_mask = dest != sid
            movers = keys[mover_mask]
            if len(movers) == 0:
                continue
            rows, freq, ts, _present = shard.export_rows(movers)
            mover_dest = dest[mover_mask]
            for did in np.unique(mover_dest):
                m = mover_dest == did
                self.shards[int(did)].import_rows(
                    movers[m], rows[m], freq[m], ts[m]
                )
            shard.delete_keys(movers)
            moved += len(movers)
            bytes_moved += len(movers) * (rf * 4 + 3 * 8)
        if new_num_shards < old_n:
            self.shards = self.shards[:new_num_shards]
        if self._version_service is not None:
            self._version_service.inc_global_version()
        report = WarmReshardReport(
            old_shards=old_n, new_shards=new_num_shards,
            total_rows=total, moved_rows=moved,
            bytes_moved=bytes_moved,
            elapsed_s=time.perf_counter() - t0,
        )
        logger.info(f"warm embedding reshard: {report.describe()}")
        return report

    def reshard(self, new_num_shards: int) -> None:
        """N → M shards: export every row once, re-route, import. Bumps
        the PS cluster version so consumers refresh their topology."""
        old = self.shards
        self.shards = [
            KvEmbeddingStore(
                self.dim, self.num_slots, self.seed, self.init_scale
            )
            for _ in range(new_num_shards)
        ]
        for shard in old:
            keys, rows, freq, ts = shard.export()
            if len(keys) == 0:
                continue
            route = self._route(keys)
            for sid in range(new_num_shards):
                mask = route == sid
                if mask.any():
                    self.shards[sid].import_rows(
                        keys[mask], rows[mask], freq[mask], ts[mask]
                    )
        if self._version_service is not None:
            self._version_service.inc_global_version()
        logger.info(
            f"resharded kv embedding {len(old)} -> {new_num_shards} "
            f"shards ({len(self)} rows)"
        )

    # -- checkpoint ----------------------------------------------------
    def export_state(
        self, since_versions: Optional[List[int]] = None
    ) -> Dict[str, np.ndarray]:
        """Full export, or a delta (rows newer than the per-shard
        versions) when ``since_versions`` is given."""
        since = since_versions or [0] * len(self.shards)
        parts = [
            s.export(since_version=v)
            for s, v in zip(self.shards, since)
        ]
        return {
            "keys": np.concatenate([p[0] for p in parts]),
            "rows": np.concatenate([p[1] for p in parts]),
            "freq": np.concatenate([p[2] for p in parts]),
            "ts": np.concatenate([p[3] for p in parts]),
        }

    def shard_versions(self) -> List[int]:
        return [s.version for s in self.shards]

    def import_state(self, state: Dict[str, np.ndarray]) -> None:
        keys = state["keys"]
        if len(keys) == 0:
            return
        route = self._route(np.asarray(keys, np.int64))
        for sid in range(self.num_shards):
            mask = route == sid
            if mask.any():
                self.shards[sid].import_rows(
                    keys[mask],
                    state["rows"][mask],
                    state["freq"][mask],
                    state["ts"][mask],
                )
