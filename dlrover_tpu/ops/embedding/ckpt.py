"""Incremental embedding checkpoints: full snapshots + version deltas.

Parity: TFPlus's incremental checkpoint manager
(tfplus/kv_variable/python/training/checkpoint_manager.py:333) built on
KvVariable FullOrDeltaExport — recommender embedding tables are huge but
churn slowly, so persisting only rows touched since the last save cuts
checkpoint cost by orders of magnitude. Here the native store's
per-row mutation versions drive it: a full snapshot every
``full_every`` saves, deltas (rows with version > last saved version,
per shard) in between; restore = latest full + deltas in order (delta
rows carry full values+slots, so import order is the only invariant).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.embedding.store import ShardedKvEmbedding


class IncrementalCheckpointManager:
    def __init__(
        self,
        store: ShardedKvEmbedding,
        directory: str,
        full_every: int = 10,
        keep_history: int = 2,
    ):
        self._store = store
        self._dir = directory
        self._full_every = max(1, full_every)
        self._keep_history = max(1, keep_history)
        # per-shard version at the last save; len mismatch (resharded
        # store) forces the next save to be full
        self._last_versions: List[int] = []
        # deltas written since this manager's last full (None = none yet)
        self._saves_since_full: Optional[int] = None
        os.makedirs(directory, exist_ok=True)
        # file indices must be unique against whatever already lives in
        # the directory (restore trims the manifest; len(entries) would
        # collide with surviving higher-numbered files and a later GC
        # would delete a live checkpoint)
        self._save_count = self._next_index()

    def _next_index(self) -> int:
        indices = [
            int(e["file"].rsplit("_", 1)[1].split(".")[0])
            for e in self._read_manifest()
        ]
        return max(indices) + 1 if indices else 0

    # -- manifest -------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self._dir, "manifest.json")

    def _read_manifest(self) -> List[dict]:
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return []

    def _write_manifest(self, entries: List[dict]):
        tmp = f"{self._manifest_path()}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(entries, f)
        os.replace(tmp, self._manifest_path())

    # -- save -----------------------------------------------------------
    def save(self, step: int = 0) -> str:
        """Write one checkpoint; returns the file path. Full when due
        (cadence, first save, or the store was resharded), else delta."""
        shards = self._store.shards
        force_full = (
            self._saves_since_full is None
            or self._saves_since_full >= self._full_every
            or len(self._last_versions) != len(shards)
        )
        state = self._store.export_state(
            since_versions=None if force_full else self._last_versions
        )
        keys = state["keys"]
        kind = "full" if force_full else "delta"
        name = f"{kind}_{self._save_count:06d}.npz"
        path = os.path.join(self._dir, name)
        tmp = path.replace(".npz", f".tmp{os.getpid()}.npz")
        np.savez(tmp, step=step, **state)
        os.replace(tmp, path)

        entries = self._read_manifest()
        entries.append(
            {"file": name, "kind": kind, "step": step, "rows": len(keys)}
        )
        self._write_manifest(entries)
        self._last_versions = self._store.shard_versions()
        self._save_count += 1
        self._saves_since_full = (
            0 if force_full else self._saves_since_full + 1
        )
        logger.info(
            f"embedding ckpt {name}: {len(keys)} rows ({kind})"
        )
        self._gc(entries)
        return path

    def _gc(self, entries: List[dict]):
        """Keep the last ``keep_history`` full chains; drop older files."""
        full_idx = [
            i for i, e in enumerate(entries) if e["kind"] == "full"
        ]
        if len(full_idx) <= self._keep_history:
            return
        cut = full_idx[-self._keep_history]
        dead, live = entries[:cut], entries[cut:]
        for e in dead:
            try:
                os.remove(os.path.join(self._dir, e["file"]))
            except OSError:
                pass
        self._write_manifest(live)

    # -- restore --------------------------------------------------------
    def restore(self) -> Optional[int]:
        """Latest full + subsequent deltas, in order. Returns the last
        saved training step, or None when nothing is restorable."""
        entries = self._read_manifest()
        full_idx = [
            i for i, e in enumerate(entries) if e["kind"] == "full"
        ]
        if not full_idx:
            return None
        chain = entries[full_idx[-1] :]
        step = 0
        for e in chain:
            path = os.path.join(self._dir, e["file"])
            data = dict(np.load(path))
            step = int(data.pop("step", 0))
            self._store.import_state(data)
        logger.info(
            f"restored embedding from {len(chain)} files "
            f"(1 full + {len(chain) - 1} deltas), step {step}"
        )
        # future deltas must be relative to what is now in the store;
        # the restored chain counts as a fresh full for cadence purposes
        self._last_versions = self._store.shard_versions()
        self._save_count = self._next_index()
        self._saves_since_full = len(chain) - 1
        return step
