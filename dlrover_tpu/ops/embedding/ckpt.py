"""Incremental embedding checkpoints: full snapshots + version deltas,
crc-verified with rollback, and a chunked budgeted stager.

Parity: TFPlus's incremental checkpoint manager
(tfplus/kv_variable/python/training/checkpoint_manager.py:333) built on
KvVariable FullOrDeltaExport — recommender embedding tables are huge but
churn slowly, so persisting only rows touched since the last save cuts
checkpoint cost by orders of magnitude. Here the native store's
per-row mutation versions drive it: a full snapshot every
``full_every`` saves, deltas (rows with version > last saved version,
per shard) in between; restore = latest full + deltas in order (delta
rows carry full values+slots, so import order is the only invariant).

PR-12 integrity (the PR-5 dense-shard rules applied to embeddings):

- every file's whole-blob crc32 + nbytes land in the manifest, computed
  by the WRITER before the bytes can be corrupted in flight (the
  ``embedding.export`` fault site corrupts after);
- ``restore`` verifies each chain file (``embedding.import`` fault
  site on the read leg); a corrupt file is quarantined to
  ``*.corrupt`` and the restore rolls back — a bad delta truncates the
  chain at the last good prefix (an earlier consistent state), a bad
  full falls back to the previous full chain. A torn export can no
  longer restore silently;
- ``begin_chunked_save`` returns an :class:`EmbeddingDeltaStager`
  mirroring the dense ``ChunkedStager`` surface: the delta export is
  snapshotted up front, then ``advance(budget_s)`` writes fixed-size
  chunks between train steps (bounded critical-path cost, incremental
  crc folded chunk-by-chunk so the published crc equals the whole-blob
  crc), and ``commit()`` is the only barrier — it publishes the
  manifest entry, so a crash mid-drain leaves the previous chain
  intact and restorable.
"""

from __future__ import annotations

import io
import json
import os
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common import faults
from dlrover_tpu.common.storage import durable_replace, fsync_dir
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.embedding.store import ShardedKvEmbedding
from dlrover_tpu.parallel import wire_format as wire_fmt

_DEF_CHUNK_BYTES = 4 << 20

# npz key prefixes carrying the int8 wire's sidecar data: per-chunk
# scales and the original dtype of each quantized array
_WIRE_SCALES = "__wire_scales__"
_WIRE_DTYPE = "__wire_dtype__"


def _serialize_state(step: int, state: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, step=np.int64(step), **state)
    return buf.getvalue()


def _encode_wire(state: Dict[str, np.ndarray], wire: str):
    """Apply the opt-in wire format to an export. Returns
    ``(wire_state, decoded_crc32)`` — the crc is the digest of what a
    reader will hold AFTER decoding (``wire_format.decoded_crc32``), so
    restore gates bitwise on the decoded payload even though the int8
    wire itself is lossy. ``("none")`` passes through with no crc (the
    whole-blob crc already covers a bitwise file)."""
    if wire != "int8":
        return state, None
    out: Dict[str, np.ndarray] = {}
    decoded: Dict[str, np.ndarray] = {}
    for k, v in state.items():
        arr = np.asarray(v)
        if wire_fmt.quantizable(arr):
            q, scales = wire_fmt.encode_int8(arr)
            out[k] = q
            out[_WIRE_SCALES + k] = scales
            out[_WIRE_DTYPE + k] = np.array(arr.dtype.str)
            decoded[k] = wire_fmt.decode_int8(q, scales, arr.dtype)
        else:
            # ints/bools (keys, versions) stay bitwise on the wire
            out[k] = arr
            decoded[k] = arr
    return out, wire_fmt.decoded_crc32(decoded)


def _decode_wire(data: Dict[str, np.ndarray]):
    """Inverse of :func:`_encode_wire` on a loaded npz dict. Returns
    ``(state, decoded_crc32)``; the crc is None when the file carries
    no wire sidecar keys (a bitwise checkpoint). ``step`` is excluded
    from the digest — the writer computed it over the export alone."""
    if not any(k.startswith(_WIRE_SCALES) for k in data):
        return data, None
    out: Dict[str, np.ndarray] = {}
    for k, v in data.items():
        if k.startswith(_WIRE_SCALES) or k.startswith(_WIRE_DTYPE):
            continue
        if _WIRE_SCALES + k in data:
            out[k] = wire_fmt.decode_int8(
                v,
                data[_WIRE_SCALES + k],
                np.dtype(str(data[_WIRE_DTYPE + k])),
            )
        else:
            out[k] = v
    crc = wire_fmt.decoded_crc32(
        {k: v for k, v in out.items() if k != "step"}
    )
    return out, crc


class EmbeddingDeltaStager:
    """Budgeted chunked writer of one (already exported) checkpoint.

    The export snapshot happens at construction — the delta is a
    consistent point-in-time view however long the drain takes. Until
    ``commit()`` publishes the manifest entry the file is a ``.staging``
    temp invisible to restore (the ChunkedStager crash-safe ordering).
    """

    def __init__(
        self,
        manager: "IncrementalCheckpointManager",
        step: int,
        kind: str,
        name: str,
        blob: bytes,
        chunk_bytes: int = _DEF_CHUNK_BYTES,
    ):
        self._manager = manager
        self.step = step
        self.kind = kind
        self.name = name
        self._blob = blob
        self._chunk_bytes = max(int(chunk_bytes), 1 << 10)
        self.total_bytes = len(blob)
        self._offset = 0
        self._crc = 0
        self.chunks_written = 0
        self._finished = False
        self._failed = False
        self._tmp = os.path.join(
            manager._dir, f"{name}.staging.{os.getpid()}"
        )
        self._f = open(self._tmp, "wb")

    @property
    def backlog_bytes(self) -> int:
        return self.total_bytes - self._offset

    @property
    def done(self) -> bool:
        return self._offset >= self.total_bytes

    @property
    def finished(self) -> bool:
        return self._finished

    def advance(self, budget_s: Optional[float] = None) -> int:
        """Write chunks until ``budget_s`` of wall time is spent (None
        = drain everything). Bounded overshoot: at most one chunk past
        the budget. Returns bytes written by this call."""
        if self._finished:
            return 0
        t0 = time.perf_counter()
        written = 0
        try:
            while not self.done:
                chunk = self._blob[
                    self._offset : self._offset + self._chunk_bytes
                ]
                # fold BEFORE the fault site corrupts: the published
                # crc is the writer's truth, a torn chunk is detected
                self._crc = zlib.crc32(chunk, self._crc)
                self._offset += len(chunk)
                corrupted = faults.corrupt("embedding.export", chunk)
                self._f.write(corrupted)
                written += len(chunk)
                self.chunks_written += 1
                if (
                    budget_s is not None
                    and time.perf_counter() - t0 >= budget_s
                ):
                    break
        except BaseException:
            self.abort()
            raise
        return written

    def commit(self) -> str:
        """Drain the backlog, fsync-rename the file into place, publish
        the manifest entry. Returns the final path."""
        if self._finished:
            return os.path.join(self._manager._dir, self.name)
        try:
            self.advance(budget_s=None)
            self._f.flush()
            os.fsync(self._f.fileno())  # post-commit means DURABLE
            self._f.close()
            path = os.path.join(self._manager._dir, self.name)
            os.replace(self._tmp, path)
            # post-commit means DURABLE for the rename too: the dir
            # entry must survive the crash, not just the bytes
            fsync_dir(self._manager._dir)
        except BaseException:
            self.abort()
            raise
        self._finished = True
        self._manager._publish(
            self.step, self.kind, self.name, self._crc,
            self.total_bytes, getattr(self, "rows", None),
            wire=getattr(self, "wire", "none"),
            decoded_crc32=getattr(self, "decoded_crc32", None),
        )
        self._blob = b""
        return path

    def abort(self):
        if self._finished:
            return
        self._finished = True
        self._failed = True
        self._blob = b""
        try:
            self._f.close()
        except OSError:
            pass
        try:
            os.remove(self._tmp)
        except OSError:
            pass
        self._manager._staging_aborted(self)


class IncrementalCheckpointManager:
    def __init__(
        self,
        store: ShardedKvEmbedding,
        directory: str,
        full_every: int = 10,
        keep_history: int = 2,
        wire_format: str = "none",
    ):
        if wire_format not in wire_fmt.WIRE_FORMATS:
            raise ValueError(
                f"unknown wire_format {wire_format!r}; "
                f"one of {wire_fmt.WIRE_FORMATS}"
            )
        self._store = store
        self._dir = directory
        # opt-in int8 wire for the slow-rail bulk leg: float arrays are
        # quantized per chunk in the npz; the manifest then carries the
        # decoded-payload crc32 and restore gates on it (the whole-blob
        # crc keeps covering the wire bytes themselves)
        self._wire_format = wire_format
        self._full_every = max(1, full_every)
        self._keep_history = max(1, keep_history)
        # per-shard version at the last save; len mismatch (resharded
        # store) forces the next save to be full
        self._last_versions: List[int] = []
        # version snapshot taken when a chunked save exported (becomes
        # _last_versions only at publish — an aborted stager must not
        # swallow its rows from the next delta)
        self._pending_versions: Optional[List[int]] = None
        # deltas written since this manager's last full (None = none yet)
        self._saves_since_full: Optional[int] = None
        # at most ONE stager in flight: a second would reuse the same
        # file index (it only advances at publish) and clobber the
        # pending version cursor
        self._active_stager: Optional[EmbeddingDeltaStager] = None
        os.makedirs(directory, exist_ok=True)
        # file indices must be unique against whatever already lives in
        # the directory (restore trims the manifest; len(entries) would
        # collide with surviving higher-numbered files and a later GC
        # would delete a live checkpoint)
        self._save_count = self._next_index()

    def _next_index(self) -> int:
        indices = [
            int(e["file"].rsplit("_", 1)[1].split(".")[0])
            for e in self._read_manifest()
        ]
        return max(indices) + 1 if indices else 0

    # -- manifest -------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self._dir, "manifest.json")

    def _read_manifest(self) -> List[dict]:
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return []

    def _write_manifest(self, entries: List[dict]):
        durable_replace(
            self._manifest_path(), lambda f: json.dump(entries, f)
        )

    # -- save -----------------------------------------------------------
    def _next_save_kind(self) -> str:
        shards = self._store.shards
        force_full = (
            self._saves_since_full is None
            or self._saves_since_full >= self._full_every
            or len(self._last_versions) != len(shards)
        )
        return "full" if force_full else "delta"

    def _export(self, kind: str) -> Dict[str, np.ndarray]:
        return self._store.export_state(
            since_versions=None
            if kind == "full"
            else self._last_versions
        )

    def save(self, step: int = 0) -> str:
        """Write one checkpoint synchronously; returns the file path.
        Full when due (cadence, first save, or the store was
        resharded), else delta."""
        stager = self.begin_chunked_save(step)
        return stager.commit()

    def begin_chunked_save(
        self, step: int = 0, chunk_bytes: int = _DEF_CHUNK_BYTES
    ) -> EmbeddingDeltaStager:
        """Snapshot the export now, drain it in budgeted chunks later:
        the trainer calls ``advance(budget_s)`` once per step and
        ``commit()`` at checkpoint cadence. Dirty-row deltas ride the
        same versions machinery as :meth:`save`."""
        if (
            self._active_stager is not None
            and not self._active_stager.finished
        ):
            raise RuntimeError(
                "a chunked embedding save is already in flight — "
                "commit() or abort() it before beginning another "
                "(both would target the same file index)"
            )
        kind = self._next_save_kind()
        state = self._export(kind)
        rows = len(state["keys"])
        name = f"{kind}_{self._save_count:06d}.npz"
        wire_state, decoded_crc = _encode_wire(
            state, self._wire_format
        )
        blob = _serialize_state(step, wire_state)
        self._pending_versions = self._store.shard_versions()
        stager = EmbeddingDeltaStager(
            self, step, kind, name, blob, chunk_bytes=chunk_bytes
        )
        stager.rows = rows
        stager.wire = self._wire_format
        stager.decoded_crc32 = decoded_crc
        self._active_stager = stager
        return stager

    def _publish(
        self,
        step: int,
        kind: str,
        name: str,
        crc: int,
        nbytes: int,
        rows: Optional[int] = None,
        wire: str = "none",
        decoded_crc32: Optional[int] = None,
    ):
        entries = self._read_manifest()
        entry = {
            "file": name,
            "kind": kind,
            "step": step,
            "rows": rows,
            "crc32": crc,
            "nbytes": nbytes,
        }
        if wire != "none":
            entry["wire"] = wire
            entry["decoded_crc32"] = decoded_crc32
        entries.append(entry)
        self._write_manifest(entries)
        self._last_versions = (
            self._pending_versions
            if self._pending_versions is not None
            else self._store.shard_versions()
        )
        self._pending_versions = None
        self._active_stager = None
        self._save_count += 1
        self._saves_since_full = (
            0
            if kind == "full"
            else (self._saves_since_full or 0) + 1
        )
        logger.info(
            f"embedding ckpt {name}: {nbytes} bytes ({kind}, "
            f"crc {crc:08x})"
        )
        self._gc(entries)

    def _staging_aborted(self, stager: EmbeddingDeltaStager):
        # the exported rows were NOT published: the next delta must
        # still carry them, so the version cursor does not advance.
        # Guarded on identity so a stale stager's late abort cannot
        # clobber a newer save's pending cursor
        if self._active_stager is stager:
            self._pending_versions = None
            self._active_stager = None

    def _gc(self, entries: List[dict]):
        """Keep the last ``keep_history`` full chains; drop older files."""
        full_idx = [
            i for i, e in enumerate(entries) if e["kind"] == "full"
        ]
        if len(full_idx) <= self._keep_history:
            return
        cut = full_idx[-self._keep_history]
        dead, live = entries[:cut], entries[cut:]
        for e in dead:
            try:
                os.remove(os.path.join(self._dir, e["file"]))
            except OSError:
                pass
        self._write_manifest(live)

    # -- restore --------------------------------------------------------
    def _load_entry(self, e: dict) -> Dict[str, np.ndarray]:
        """Read + verify one chain file; raises ValueError on any
        corruption (length, crc, unreadable zip)."""
        path = os.path.join(self._dir, e["file"])
        faults.fire("embedding.import")
        with open(path, "rb") as f:
            blob = f.read()
        if "crc32" in e:
            if len(blob) != e.get("nbytes", len(blob)) or (
                zlib.crc32(blob) != e["crc32"]
            ):
                raise ValueError(
                    f"embedding ckpt {e['file']} fails crc/length "
                    f"verification"
                )
        try:
            data = dict(np.load(io.BytesIO(blob)))
        except Exception as err:
            raise ValueError(
                f"embedding ckpt {e['file']} unreadable: {err!r}"
            )
        state, dec_crc = _decode_wire(data)
        if e.get("wire") == "int8":
            # the decoded payload is what the store will import: gate
            # on ITS digest, not just the wire bytes'
            if dec_crc is None or dec_crc != e.get("decoded_crc32"):
                raise ValueError(
                    f"embedding ckpt {e['file']} fails decoded-payload "
                    f"crc verification"
                )
        return state

    def _quarantine(self, e: dict):
        path = os.path.join(self._dir, e["file"])
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass
        logger.error(
            f"embedding ckpt {e['file']} quarantined (corrupt)"
        )

    def restore(self) -> Optional[int]:
        """Latest VERIFIED full + subsequent verified deltas, in order.

        Corruption rolls back instead of restoring silently: a corrupt
        delta truncates the chain at the last good prefix (an earlier
        consistent state); a corrupt full drops the whole chain and the
        previous full chain is tried. Quarantined files are renamed
        ``*.corrupt`` and trimmed from the manifest. Returns the last
        restored training step, or None when nothing verifiable
        remains."""
        entries = self._read_manifest()
        while True:
            full_idx = [
                i for i, e in enumerate(entries) if e["kind"] == "full"
            ]
            if not full_idx:
                return None
            chain = entries[full_idx[-1] :]
            loaded = []
            bad_at: Optional[int] = None
            for j, e in enumerate(chain):
                try:
                    loaded.append((e, self._load_entry(e)))
                except ValueError as err:
                    logger.error(str(err))
                    self._quarantine(e)
                    bad_at = j
                    break
            if bad_at == 0:
                # the full itself is bad: drop this chain entirely and
                # fall back to the previous full chain
                entries = entries[: full_idx[-1]]
                self._write_manifest(entries)
                continue
            if bad_at is not None:
                # truncate at the last good prefix; later files (even
                # if healthy) can't apply over the missing delta
                entries = entries[: full_idx[-1] + bad_at]
                self._write_manifest(entries)
                chain = chain[:bad_at]
            step = 0
            for e, data in loaded:
                step = int(data.pop("step", 0))
                self._store.import_state(data)
            logger.info(
                f"restored embedding from {len(loaded)} files "
                f"(1 full + {len(loaded) - 1} deltas), step {step}"
                + (" [rolled back past corruption]" if bad_at else "")
            )
            # future deltas must be relative to what is now in the
            # store; the restored chain counts as a fresh full for
            # cadence purposes
            self._last_versions = self._store.shard_versions()
            self._save_count = self._next_index()
            self._saves_since_full = len(loaded) - 1
            return step
