"""Device-resident hot tier: HBM-pinned embedding rows with Pallas
gather/scatter, over any host-side KvEmbedding store.

Parity target: TFPlus ``KvVariable`` serves recommender gathers from
wherever the row lives; this repo's port kept every row host-side, so
``SparseTrainer`` paid a synchronous host gather → device step → host
scatter cycle every step. Zipfian access means a small hot set absorbs
almost all traffic: this module pins that hot set in HBM and serves it
with Pallas kernels, leaving the host store (``ShardedKvEmbedding`` /
``TieredKvEmbedding`` / ``NativeTieredKvEmbedding``) as the warm tier
of a three-tier hierarchy::

    HBM hot tier (this module)  --spill/fault-->  host C++ store
    host C++ store              --evict/fault-->  disk cold tier

Design:

- The tier is ONE device table ``[capacity, row_floats]`` (values +
  optimizer slots — update state travels with the row, the same fused
  layout the C++ store uses). ``capacity`` comes from an HBM byte
  budget, the knob that bounds the tier (docs/sparse-embeddings.md).
- Gather/scatter are Pallas kernels over **sorted unique ids**: the
  id→slot map lives host-side (cheap numpy hash ops on deduped ids),
  the kernels move one row per grid step via scalar-prefetched slot
  indices (``PrefetchScalarGridSpec``) — compiled on TPU, and run
  under the Pallas interpreter on CPU via
  ``jax_compat.pallas_interpret_mode`` so tier-1 runs everywhere.
  ``DLROVER_TPU_EMB_KERNEL=jnp`` selects a pure ``jnp.take``/``.at[]``
  fallback (also the automatic fallback if a kernel fails to trace).
- Missing rows FAULT IN from the host store (full rows incl. slots via
  ``export_rows`` — a state read, no freq/ts bump); LRU victims spill
  back with an **async D2H**: the evicted rows are handed to a drain
  thread as device arrays with ``copy_to_host_async`` already issued,
  so the step never blocks on the host link. Both directions are
  priced through the PR-6 ``LinkModel`` host leg
  (``topology.price_host_transfer``).
- The sparse optimizer update runs ON DEVICE (adagrad / momentum /
  adam over the gathered rows, duplicate ids segment-summed), then a
  Pallas scatter writes the new rows back into the table in place
  (``input_output_aliases`` — no table-sized copy per step).

Coherency contract: while a row is device-resident its device copy is
authoritative and the host copy is stale; ``flush()`` (checkpoint
cadence) and spills write it back. ``export_state`` flushes first so a
checkpoint can never lose device-only training.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.jax_compat import pallas_interpret_mode
from dlrover_tpu.common.log import default_logger as logger

_DEF_HBM_BUDGET = 64 << 20  # 64 MiB of rows unless the caller budgets


def _bucket(n: int, floor: int = 64) -> int:
    """Next power of two ≥ n (≥ floor): the shape buckets that keep
    kernel/jit compiles amortized across variable unique-id counts."""
    b = floor
    while b < n:
        b <<= 1
    return b


# -- kernels -----------------------------------------------------------------


class _Kernels:
    """Pallas gather/scatter over a ``[capacity, row_floats]`` table,
    one row per grid step, slots scalar-prefetched so the index map can
    address HBM before the body runs. Falls back to jnp take/at ops on
    any trace failure (logged once) — same numerics, no kernel.

    Mode resolution (``DLROVER_TPU_EMB_KERNEL`` overrides): ``auto``
    compiles the Pallas kernels on TPU and uses the jnp path on CPU —
    the interpreter executes the grid one id at a time in Python
    (seconds per 4k-id batch), correct but only useful as a numerics
    check, which is exactly what ``pallas`` forces in the tests."""

    def __init__(self, mode: Optional[str] = None):
        import os

        mode = mode or os.getenv("DLROVER_TPU_EMB_KERNEL", "auto")
        if mode == "auto":
            mode = "jnp" if pallas_interpret_mode() else "pallas"
        self.mode = mode
        self._gather_calls: Dict[Tuple[int, int, int], Any] = {}
        self._scatter_calls: Dict[Tuple[int, int, int], Any] = {}

    # jnp fallback path (also the reference the tests check against):
    # jitted per shape bucket, with the table DONATED to the scatter so
    # the update happens in place — the jnp twin of the pallas kernel's
    # input_output_aliases (an eager .at[].set would copy the whole
    # table every step)
    def _gather_jnp(self, table, slots):
        import jax
        import jax.numpy as jnp

        key = ("gj", len(slots)) + table.shape
        fn = self._gather_calls.get(key)
        if fn is None:
            fn = jax.jit(lambda t, s: jnp.take(t, s, axis=0))
            self._gather_calls[key] = fn
        return fn(table, jnp.asarray(slots, jnp.int32))

    def _scatter_jnp(self, table, slots, rows):
        import jax
        import jax.numpy as jnp

        key = ("sj", len(slots)) + table.shape
        fn = self._scatter_calls.get(key)
        if fn is None:
            fn = jax.jit(
                lambda t, s, r: t.at[s].set(r), donate_argnums=(0,)
            )
            self._scatter_calls[key] = fn
        return fn(table, jnp.asarray(slots, jnp.int32), rows)

    def _fall_back(self, why: Exception):
        logger.warning(
            f"embedding pallas kernels unavailable on this backend "
            f"({why!r}); falling back to jnp gather/scatter"
        )
        self.mode = "jnp"

    def _build_gather(self, n: int, capacity: int, row_floats: int):
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(_slots_ref, table_ref, out_ref):
            out_ref[...] = table_ref[...]

        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, row_floats), lambda i, s: (s[i], 0))
            ],
            out_specs=pl.BlockSpec((1, row_floats), lambda i, s: (i, 0)),
        )
        return pl.pallas_call(
            kernel,
            grid_spec=gs,
            out_shape=jax.ShapeDtypeStruct((n, row_floats), np.float32),
            interpret=pallas_interpret_mode(),
        )

    def _build_scatter(self, n: int, capacity: int, row_floats: int):
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(_slots_ref, rows_ref, _table_ref, out_ref):
            out_ref[...] = rows_ref[...]

        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, row_floats), lambda i, s: (i, 0)),
                pl.BlockSpec((1, row_floats), lambda i, s: (s[i], 0)),
            ],
            out_specs=pl.BlockSpec((1, row_floats), lambda i, s: (s[i], 0)),
        )
        # the table (input 2, counting the scalar-prefetch arg) aliases
        # the output: untouched rows persist, addressed rows are
        # overwritten in place — no table-sized copy per step
        return pl.pallas_call(
            kernel,
            grid_spec=gs,
            out_shape=jax.ShapeDtypeStruct(
                (capacity, row_floats), np.float32
            ),
            input_output_aliases={2: 0},
            interpret=pallas_interpret_mode(),
        )

    def gather(self, table, slots_np: np.ndarray):
        """rows[i] = table[slots[i]] — slots are sorted unique device
        slot ids (host side guarantees uniqueness/sortedness)."""
        import jax.numpy as jnp

        if self.mode == "jnp":
            return self._gather_jnp(table, jnp.asarray(slots_np))
        key = (len(slots_np),) + table.shape
        call = self._gather_calls.get(key)
        if call is None:
            try:
                call = self._build_gather(
                    len(slots_np), table.shape[0], table.shape[1]
                )
            except Exception as e:  # jaxlib without pallas support
                self._fall_back(e)
                return self._gather_jnp(table, jnp.asarray(slots_np))
            self._gather_calls[key] = call
        try:
            return call(jnp.asarray(slots_np, jnp.int32), table)
        except Exception as e:
            self._fall_back(e)
            return self._gather_jnp(table, jnp.asarray(slots_np))

    def scatter(self, table, slots_np: np.ndarray, rows):
        """table[slots[i]] = rows[i], in place (aliased); returns the
        new table array. Slots MUST be unique (duplicate writes would
        race in the grid) — the callers pass deduped ids only."""
        import jax.numpy as jnp

        if self.mode == "jnp":
            return self._scatter_jnp(table, jnp.asarray(slots_np), rows)
        key = (len(slots_np),) + table.shape
        call = self._scatter_calls.get(key)
        if call is None:
            try:
                call = self._build_scatter(
                    len(slots_np), table.shape[0], table.shape[1]
                )
            except Exception as e:
                self._fall_back(e)
                return self._scatter_jnp(
                    table, jnp.asarray(slots_np), rows
                )
            self._scatter_calls[key] = call
        try:
            return call(jnp.asarray(slots_np, jnp.int32), rows, table)
        except Exception as e:
            self._fall_back(e)
            return self._scatter_jnp(table, jnp.asarray(slots_np), rows)


# -- stats -------------------------------------------------------------------


@dataclass
class EmbeddingTierStats:
    """Per-table hot-tier telemetry; ``export_metrics`` publishes it as
    ``dlrover_embedding_*`` gauges (docs/observability.md) and the
    trainer forwards the same scalars to the master / Brain
    ``job_metrics`` through its train-metrics report."""

    gathers: int = 0
    unique_ids: int = 0
    hits: int = 0  # unique ids already device-resident
    faults: int = 0  # unique ids faulted in from the host tier
    fault_bytes: int = 0  # H2D row traffic
    spill_rows: int = 0
    spill_bytes: int = 0  # D2H row traffic
    scatter_lag_s: float = 0.0  # enqueue→host-import latency (sum)
    scatter_drains: int = 0
    host_leg_s: float = 0.0  # LinkModel-priced host-link seconds

    @property
    def hit_pct(self) -> float:
        total = self.hits + self.faults
        return 100.0 * self.hits / total if total else 0.0

    @property
    def scatter_lag_ms(self) -> float:
        if not self.scatter_drains:
            return 0.0
        return 1e3 * self.scatter_lag_s / self.scatter_drains

    def as_dict(self) -> Dict[str, float]:
        return {
            "emb_gather_hit_pct": round(self.hit_pct, 3),
            "emb_faults": float(self.faults),
            "emb_fault_bytes": float(self.fault_bytes),
            "emb_spill_rows": float(self.spill_rows),
            "emb_spill_bytes": float(self.spill_bytes),
            "emb_scatter_lag_ms": round(self.scatter_lag_ms, 3),
            "emb_host_leg_ms": round(1e3 * self.host_leg_s, 3),
        }


# -- hot tier ----------------------------------------------------------------


class DeviceHotTier:
    """The HBM row cache: device table + host-side id→slot map + LRU.

    Not thread-safe by itself — :class:`DeviceSparseEmbedding` owns the
    lock that serializes table mutations (the pipeline's fault-in
    thread vs the train thread's grad scatter)."""

    def __init__(
        self,
        dim: int,
        num_slots: int = 1,
        hbm_budget_bytes: int = _DEF_HBM_BUDGET,
        capacity: Optional[int] = None,
        kernels: Optional[_Kernels] = None,
    ):
        import jax.numpy as jnp

        self.dim = dim
        self.num_slots = num_slots
        self.row_floats = dim * (1 + num_slots)
        row_bytes = self.row_floats * 4
        self.capacity = int(
            capacity
            if capacity is not None
            else max(64, hbm_budget_bytes // row_bytes)
        )
        self.hbm_bytes = self.capacity * row_bytes
        # one extra SCRATCH row at index ``capacity``: batches pad
        # their unique-id slot lists up to a power-of-two bucket with
        # it, so every kernel/jit shape is reused instead of
        # recompiling per step (unique counts vary batch to batch).
        # Padding entries carry zero gradients, so the scratch row's
        # update is the identity and concurrent identical writes to it
        # are benign.
        self.scratch_slot = self.capacity
        self.table = jnp.zeros(
            (self.capacity + 1, self.row_floats), jnp.float32
        )
        self._kernels = kernels or _Kernels()
        self._slot_of: Dict[int, int] = {}
        # bookkeeping arrays include the scratch slot so padded slot
        # lists can index them; the scratch entry never binds an id, so
        # occupancy/dirty scans (keyed on _id_of >= 0) exclude it
        self._id_of = np.full(self.capacity + 1, -1, np.int64)
        self._dirty = np.zeros(self.capacity + 1, bool)
        self._last_used = np.zeros(self.capacity + 1, np.int64)
        # pin refcounts: slots referenced by an outstanding
        # PreparedBatch must not be LRU victims — the pipeline thread's
        # fault-in for step N+1 would otherwise evict rows step N is
        # about to update, silently reusing the slot for another id
        self._pins = np.zeros(self.capacity + 1, np.int32)
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._tick = 0

    def __len__(self) -> int:
        return len(self._slot_of)

    @property
    def kernel_mode(self) -> str:
        return self._kernels.mode

    def lookup(self, unique_ids: np.ndarray) -> np.ndarray:
        """slots for ``unique_ids`` (-1 = not resident). Read-only."""
        slots = np.empty(len(unique_ids), np.int64)
        get = self._slot_of.get
        for i, k in enumerate(unique_ids):
            slots[i] = get(int(k), -1)
        return slots

    def touch(self, slots: np.ndarray):
        self._tick += 1
        self._last_used[slots] = self._tick

    def pin(self, slots: np.ndarray):
        self._pins[slots] += 1

    def unpin(self, slots: np.ndarray):
        self._pins[slots] = np.maximum(self._pins[slots] - 1, 0)

    def recency_snapshot(self) -> Dict[str, Any]:
        """Copy of the residency/LRU/pin bookkeeping. The serving-path
        guarantee is stated against this: a read-only probe
        (``gather(insert_missing=False)``) must leave two snapshots
        bit-identical — no admissions, no recency touches, no pin
        drift — so serving traffic can never evict or age what
        training needs resident."""
        return {
            "tick": self._tick,
            "resident": dict(self._slot_of),
            "last_used": self._last_used.copy(),
            "pins": self._pins.copy(),
        }

    def _allocate(
        self, n: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """n free slots, evicting coldest UNPINNED residents if needed.
        Returns (slots, victim_slots, victim_ids) — victim ids are
        captured BEFORE the unbind, and the victims' rows must be read
        out by the caller before anything scatters over them."""
        n_free = len(self._free)
        victims = np.empty(0, np.int64)
        victim_ids = np.empty(0, np.int64)
        if n > n_free:
            need = n - n_free
            occupied = np.nonzero(
                (self._id_of >= 0) & (self._pins == 0)
            )[0]
            order = np.argsort(self._last_used[occupied], kind="stable")
            victims = occupied[order[:need]]
            if len(victims) < need:
                raise ValueError(
                    f"hot tier capacity {self.capacity} cannot hold "
                    f"{n} new rows ({int((self._pins > 0).sum())} "
                    f"pinned by in-flight steps) — raise the HBM "
                    f"budget or lower the pipeline depth"
                )
            victim_ids = self._id_of[victims].copy()
            for s in victims:
                del self._slot_of[int(self._id_of[s])]
                self._id_of[s] = -1
                self._free.append(int(s))
        slots = np.array(
            [self._free.pop() for _ in range(n)], np.int64
        )
        return slots, victims, victim_ids

    def gather_rows(self, slots: np.ndarray):
        """Full rows (values + slots) at device ``slots``. Exact
        power-of-two slot lists (the PreparedBatch hot path) return a
        device array straight from the kernel; ragged lists (spill /
        flush) are padded to a bucket against the scratch slot and
        materialized to a host numpy slice — slicing a device array at
        a per-call-unique length would trigger an XLA compile per
        shape, and these callers want host bytes anyway."""
        n = len(slots)
        padded_len = _bucket(n)
        s = np.asarray(slots, np.int32)
        if padded_len != n:
            p = np.full(padded_len, self.scratch_slot, np.int32)
            p[:n] = s
            return np.asarray(self._kernels.gather(self.table, p))[:n]
        return self._kernels.gather(self.table, s)

    def scatter_rows(self, slots: np.ndarray, rows, dirty: bool = True):
        """Overwrite rows at unique device ``slots`` in place (padding
        writes land on the scratch row, whose content is immaterial).
        Ragged numpy inputs are padded HOST-side so the device only
        ever sees bucket shapes — no per-step eager-op compiles."""
        import jax.numpy as jnp

        n = len(slots)
        padded_len = _bucket(n)
        s = np.asarray(slots, np.int32)
        if padded_len != n:
            p = np.full(padded_len, self.scratch_slot, np.int32)
            p[:n] = s
            np_rows = np.asarray(rows, np.float32).reshape(
                n, self.row_floats
            )
            padded = np.zeros(
                (padded_len, self.row_floats), np.float32
            )
            padded[:n] = np_rows
            rows = padded
            s = p
        self.table = self._kernels.scatter(
            self.table, s, jnp.asarray(rows)
        )
        if dirty:
            self._dirty[slots] = True

    def bind(self, ids: np.ndarray, slots: np.ndarray):
        for k, s in zip(ids, slots):
            self._slot_of[int(k)] = int(s)
            self._id_of[s] = k
        self.touch(slots)

    def dirty_slots(self) -> np.ndarray:
        # padded scatters may mark the scratch slot dirty; only bound
        # slots carry rows that need a write-back
        return np.nonzero(self._dirty & (self._id_of >= 0))[0]

    def clear_dirty(self, slots: np.ndarray):
        self._dirty[slots] = False

    def drop(self, slots: np.ndarray):
        """Unbind slots (rows must already be safe host-side)."""
        for s in slots:
            k = int(self._id_of[s])
            if k >= 0:
                del self._slot_of[k]
            self._id_of[s] = -1
            self._dirty[s] = False
            self._pins[s] = 0
            self._free.append(int(s))


# -- prepared step -----------------------------------------------------------


@dataclass
class PreparedBatch:
    """Everything the train step needs for one batch of ids, built by
    ``prepare`` (possibly on the pipeline thread one step ahead):
    sorted unique ids, their device slots, and the inverse map back to
    the per-occurrence order."""

    ids: np.ndarray
    unique_ids: np.ndarray
    inverse: np.ndarray
    slots: np.ndarray  # padded to a power-of-two bucket (scratch slot)
    n_unique: int = 0  # real entries in ``slots`` before padding
    generation: int = 0
    released: bool = False  # pins returned (apply_grads or release)


# -- the three-tier facade ---------------------------------------------------


class DeviceSparseEmbedding:
    """HBM hot tier over a host KvEmbedding store, with the sparse
    optimizer running on device.

    The train cycle becomes::

        prep = emb.prepare(ids)          # pipeline thread, step N+1
        rows = emb.gather_for(prep)      # device gather, step N
        ... dense step produces row_grads ...
        emb.apply_grads(prep, row_grads) # on-device update + scatter

    ``sparse_optimizer`` ∈ {adagrad, momentum, adam} — the on-device
    subset of the host store's fused family (rows carry the same
    [value | slot…] layout, so a row can move tiers mid-training and
    keep its optimizer state).
    """

    SUPPORTED_OPTS = ("adagrad", "momentum", "adam")

    def __init__(
        self,
        host,
        hbm_budget_bytes: int = _DEF_HBM_BUDGET,
        capacity: Optional[int] = None,
        sparse_optimizer: str = "adagrad",
        lr: float = 0.05,
        eps: float = 1e-8,
        momentum: float = 0.9,
        beta1: float = 0.9,
        beta2: float = 0.999,
        table_name: str = "t0",
        kernel_mode: Optional[str] = None,
        async_spill: bool = True,
        spill_stripe_min_bytes: Optional[int] = None,
    ):
        if sparse_optimizer not in self.SUPPORTED_OPTS:
            raise ValueError(
                f"device tier supports {self.SUPPORTED_OPTS}, got "
                f"{sparse_optimizer!r} (use the host-path SparseTrainer "
                f"cycle for the full fused family)"
            )
        need_slots = {"adagrad": 1, "momentum": 1, "adam": 2}[
            sparse_optimizer
        ]
        if host.num_slots < need_slots:
            raise ValueError(
                f"{sparse_optimizer} needs num_slots >= {need_slots}"
            )
        self.host = host
        self.table_name = table_name
        self.hot = DeviceHotTier(
            host.dim,
            host.num_slots,
            hbm_budget_bytes=hbm_budget_bytes,
            capacity=capacity,
            kernels=_Kernels(kernel_mode),
        )
        self._opt = sparse_optimizer
        self._lr = float(lr)
        self._eps = float(eps)
        self._momentum = float(momentum)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self.stats = EmbeddingTierStats()
        # host-link arbitration (parallel/transfer_sched.py): the
        # fault-in H2D leg and the spill D2H leg register as streams so
        # they interleave with checkpoint staging by priority instead
        # of queueing blindly. Grants wrap whole transfers — ordering
        # changes, contents never do. Acquired OUTSIDE self._lock
        # always (the arbiter is a leaf lock).
        from dlrover_tpu.parallel import transfer_sched

        self._fault_stream = transfer_sched.get_arbiter().register(
            f"emb_fault:{table_name}",
            transfer_sched.Priority.BACKPRESSURE,
            direction="h2d",
        )
        self._spill_stream = transfer_sched.get_arbiter().register(
            f"emb_spill:{table_name}",
            transfer_sched.Priority.BACKGROUND,
            direction="d2h",
        )
        # multi-rail spill striping: a spill whose staging D2H is at
        # least this large splits its row ranges across every admitted
        # rail (the striper's per-range grants replace the single
        # stream grant). Only the device→host copy stripes — the host
        # import stays single-threaded (ShardedKvEmbedding.import_rows
        # is not thread-safe).
        self._spill_stripe_min_bytes = (
            transfer_sched.DEFAULT_STRIPE_MIN_BYTES
            if spill_stripe_min_bytes is None
            else max(int(spill_stripe_min_bytes), 1)
        )
        self._spill_striper = transfer_sched.StripedTransfer(
            self._spill_stream.arbiter,
            name=f"emb_spill:{table_name}",
            direction="d2h",
            priority=transfer_sched.Priority.BACKGROUND,
            ignore_window=True,
        )
        # one lock serializes every table mutation: the pipeline
        # thread's fault-in scatter vs the train thread's grad scatter
        # (jax arrays are immutable — the hazard is lost updates via
        # interleaved read-modify-swap, not torn reads)
        self._lock = threading.RLock()
        self._gen = 0
        self._update_fns: Dict[Tuple[int, int], Any] = {}
        # async spill drain: victims leave _allocate as device arrays
        # with copy_to_host_async issued; this thread materializes and
        # imports them so the step never blocks on the D2H
        self._spill_q: "queue.Queue" = queue.Queue()
        self._spill_err: Optional[BaseException] = None
        # spill lifetime tracking (both under self._lock): ids whose
        # dirty rows are queued/in-flight to the host — a fault-in for
        # one of them must wait, or it would read the PRE-spill host
        # value and silently lose the victim's training; and an
        # explicit in-flight count, because Queue.empty() flips False
        # the moment the drain DEQUEUES an item, not when its import
        # lands — join_spills on empty() could let a checkpoint export
        # race the last import
        self._pending_spill_ids: set = set()
        self._spills_inflight = 0
        self._async_spill = async_spill
        self._spill_thread: Optional[threading.Thread] = None
        if async_spill:
            self._spill_thread = threading.Thread(
                target=self._drain_spills,
                daemon=True,
                name=f"emb-spill-{table_name}",
            )
            self._spill_thread.start()

    # -- spill drain ---------------------------------------------------
    def _drain_spills(self):
        while True:
            item = self._spill_q.get()
            if item is None:
                return
            try:
                self._import_spill(*item)
            except BaseException as e:  # surfaced on next flush()
                self._spill_err = e
                logger.error(f"embedding spill drain failed: {e!r}")
                with self._lock:
                    self._spills_inflight -= 1
                    self._pending_spill_ids.difference_update(
                        int(k) for k in item[1]
                    )

    def _import_spill(
        self, t_enq: float, ids, dev_rows, n: int, arbitrate: bool = True
    ):
        from contextlib import nullcontext

        from dlrover_tpu.parallel import transfer_sched

        # link-grant ordering is ALWAYS link → emb/host locks: the
        # drain thread holds no lock here, so it arbitrates; the
        # synchronous (async_spill=False) path runs INLINE under
        # self._lock from _allocate and must NOT wait on the link — a
        # grant-holding fault-in briefly takes self._lock inside
        # _host_rows, and emb→link here would be the ABBA half of a
        # deadlock
        prio = transfer_sched.Priority.BACKGROUND
        if arbitrate:
            # backlog escalates priority: a deep spill queue is about
            # to stall _allocate (the step path), so it outranks
            # background checkpoint staging
            if self._spill_q.qsize() >= 2:
                prio = transfer_sched.Priority.BACKPRESSURE
        nbytes = n * self.host.dim * 4
        stripes = (
            arbitrate
            and nbytes >= self._spill_stripe_min_bytes
            and len(self._spill_striper.rails()) >= 2
        )
        if stripes:
            # stripe ONLY the D2H staging: per-rail workers land row
            # ranges into a preallocated host buffer (disjoint slices,
            # so concurrent writes never overlap) under the striper's
            # per-range grants — no outer stream grant, or the striper
            # would deadlock against its own stream's held rail. The
            # host import below runs single-threaded after the join.
            # row width comes from the device gather (dim plus the
            # optimizer slot columns), not host.dim
            rows = np.empty(
                (n,) + tuple(dev_rows.shape[1:]),
                np.dtype(dev_rows.dtype),
            )
            rowb = max(1, rows.nbytes // max(n, 1))
            step = max(1, self._spill_striper.chunk_bytes // rowb)
            ranges = []
            lo = 0
            while lo < n:
                hi = min(lo + step, n)
                ranges.append(((lo, hi), (hi - lo) * rowb))
                lo = hi

            def _stage(rail, rng):
                rlo, rhi = rng
                rows[rlo:rhi] = np.asarray(dev_rows[rlo:rhi])

            self._spill_striper.run_items(
                ranges, _stage, priority=prio
            )
            self.host.import_rows(ids, rows)
        else:
            grant = (
                self._spill_stream.transfer(nbytes, priority=prio)
                if arbitrate
                else nullcontext()
            )
            # lands the (already async) D2H; the device array is
            # bucket-padded, the tail rows are scratch filler
            with grant:
                rows = np.asarray(dev_rows)[:n]
                self.host.import_rows(ids, rows)
        self.stats.spill_rows += len(ids)
        self.stats.spill_bytes += rows.nbytes
        self.stats.scatter_lag_s += time.perf_counter() - t_enq
        self.stats.scatter_drains += 1
        self.stats.host_leg_s += self._price(rows.nbytes, h2d=False)
        with self._lock:
            self._spills_inflight -= 1
            self._pending_spill_ids.difference_update(
                int(k) for k in ids
            )

    @staticmethod
    def _price(nbytes: int, h2d: bool) -> float:
        try:
            from dlrover_tpu.parallel.topology import price_host_transfer

            return price_host_transfer(nbytes, h2d=h2d)
        except Exception:
            return 0.0

    def _spill(
        self,
        victim_slots: np.ndarray,
        victim_ids: Optional[np.ndarray] = None,
    ):
        """Read victims' rows and hand them to the drain (async D2H).
        ``victim_ids`` must be passed when the caller already unbound
        the slots (the ``_allocate`` path clears ``_id_of`` first)."""
        if len(victim_slots) == 0:
            return
        ids = (
            victim_ids
            if victim_ids is not None
            else self.hot._id_of[victim_slots].copy()
        )
        # only dirty victims need the write-back; clean ones are
        # byte-identical host-side already
        dirty = self.hot._dirty[victim_slots]
        if dirty.any():
            d_slots = victim_slots[dirty]
            # bucket-padded DEVICE gather (not gather_rows, whose
            # ragged path materializes to host synchronously): the
            # array stays on device with its D2H dispatched async, and
            # the drain thread slices the real rows off once it lands
            n = len(d_slots)
            padded = np.full(
                _bucket(n), self.hot.scratch_slot, np.int32
            )
            padded[:n] = d_slots
            dev_rows = self.hot._kernels.gather(
                self.hot.table, padded
            )
            try:
                dev_rows.copy_to_host_async()
            except Exception:
                pass
            item = (time.perf_counter(), ids[dirty], dev_rows, n)
            # bookkeeping BEFORE dispatch (callers hold self._lock):
            # _import_spill decrements/clears on completion either way
            self._spills_inflight += 1
            self._pending_spill_ids.update(int(k) for k in ids[dirty])
            if self._async_spill:
                self._spill_q.put(item)
            else:
                # inline under self._lock: no link arbitration (see
                # _import_spill's ordering note)
                self._import_spill(*item, arbitrate=False)
        self.hot.clear_dirty(victim_slots)

    # -- prepare / gather / update -------------------------------------
    def prepare(self, ids) -> PreparedBatch:
        """Dedup ``ids`` (sorted unique) and make every unique id
        device-resident, faulting missing rows in from the host tier.
        Safe to call from the pipeline thread one step ahead of the
        compute that will consume it."""
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        unique, inverse = np.unique(ids, return_inverse=True)
        while True:
            with self._lock:
                gen0 = self._gen
                slots = self.hot.lookup(unique)
                missing_mask = slots < 0
                missing = unique[missing_mask]
                self.stats.gathers += 1
                self.stats.unique_ids += len(unique)
                self.stats.hits += int((~missing_mask).sum())
            if not len(missing):
                with self._lock:
                    if self._gen != gen0:
                        continue  # resident set changed under us
                    self.hot.touch(slots)
                    self.hot.pin(slots)
                    gen = gen0
                break
            # host legs OUTSIDE the lock: the C++ gather/export and the
            # H2D dispatch are the slow part and must overlap the train
            # thread's compute, not serialize against its scatter.
            # Rows stay numpy until the (bucket-padded) scatter so no
            # ragged-shape eager op ever reaches the device. The link
            # grant (BACKPRESSURE: a consumer may be waiting on this
            # prep) orders the leg against spills/staging.
            if self._spills_racing(missing):
                # one of these ids was just evicted and its spill has
                # not landed host-side: reading now would fault the
                # PRE-spill value back in and lose the victim's
                # training. Join BEFORE taking the link grant — the
                # drain needs the link to land its import, and joining
                # while HOLDING the grant deadlocks against it (the
                # arbiter's forced-grant backstop outlasts the join
                # timeout; graftlint lock-discipline.grant, found as a
                # flaky 30 s wedge in the spill-lifetime test)
                self.join_spills()
            with self._fault_stream.transfer(
                len(missing) * self.host.dim * 4
            ):
                racing = self._spills_racing(missing)
                rows_np = None if racing else self._host_rows(missing)
            if racing:
                # re-armed between the join and the export (a
                # concurrent prepare faulted one of these ids in and
                # evicted it again): the grant is released now, so
                # join and retry from the top
                self.join_spills()
                continue
            with self._lock:
                if self._gen != gen0:
                    # an import_state/evict resharded the world while
                    # the rows were in flight: binding them now would
                    # install PRE-restore values under the new
                    # generation and defeat the staleness check —
                    # discard and re-read the (new) host state
                    continue
                # re-check residency: a concurrent prepare may have
                # faulted some of these in meanwhile
                cur = self.hot.lookup(missing)
                still = cur < 0
                if still.any():
                    new_ids = missing[still]
                    new_slots, victims, victim_ids = self.hot._allocate(
                        int(still.sum())
                    )
                    self._spill(victims, victim_ids)
                    self.hot.scatter_rows(
                        new_slots, rows_np[still], dirty=False
                    )
                    self.hot.bind(new_ids, new_slots)
                self.stats.faults += len(missing)
                self.stats.fault_bytes += rows_np.nbytes
                self.stats.host_leg_s += self._price(
                    rows_np.nbytes, h2d=True
                )
                slots = self.hot.lookup(unique)
                self.hot.touch(slots)
                self.hot.pin(slots)
                gen = gen0
            break
        # pad the slot list to a power-of-two bucket with the scratch
        # slot: kernel/jit shapes recur across steps instead of
        # recompiling for every distinct unique-id count
        padded_len = _bucket(len(unique))
        padded = np.full(padded_len, self.hot.scratch_slot, np.int64)
        padded[: len(unique)] = slots
        return PreparedBatch(
            ids=ids,
            unique_ids=unique,
            inverse=inverse.astype(np.int32),
            slots=padded,
            n_unique=len(unique),
            generation=gen,
        )

    def _spills_racing(self, ids: np.ndarray) -> bool:
        """True if any of ``ids`` has an in-flight spill whose import
        has not landed host-side yet (reading it now would return the
        pre-spill value)."""
        with self._lock:
            return bool(
                self._pending_spill_ids.intersection(
                    int(k) for k in ids
                )
            )

    def _host_rows(self, missing: np.ndarray) -> np.ndarray:
        """Full rows for ``missing`` from the host tier; keys the host
        has never seen are created there first (deterministic C++ init)
        so both tiers agree on the row's birth value. Callers must have
        joined any racing spill of these ids FIRST — and before taking
        the link grant: the drain needs the link to land its import,
        so a grant-holding join deadlocks (prepare does this)."""
        rows, _f, _t, present = self.host.export_rows(missing)
        absent = missing[~present]
        if len(absent):
            # gather(insert_missing=True) creates + inits; rows (incl.
            # zero slots) then export with the authoritative values.
            # TieredKvEmbedding.gather also faults disk-cold rows hot
            # first, so all three tiers compose here.
            self.host.gather(absent, insert_missing=True)
            rows2, _f2, _t2, present2 = self.host.export_rows(missing)
            rows[~present] = rows2[~present]
        return rows

    def _check_gen(self, prep: PreparedBatch):
        if prep.generation != self._gen:
            raise RuntimeError(
                "PreparedBatch is stale: the embedding was flushed/"
                "resharded after prepare() — re-prepare this batch"
            )

    def gather_for(self, prep: PreparedBatch):
        """Values for every occurrence in ``prep.ids`` as a device
        array ``[len(ids), dim]`` (what the dense step consumes)."""
        with self._lock:
            self._check_gen(prep)
            rows = self.hot.gather_rows(prep.slots)
        return self._project_fn(len(prep.slots), len(prep.inverse))(
            rows, prep.inverse
        )

    def _project_fn(self, n_padded: int, n_ids: int):
        """Jitted (padded rows, inverse) -> per-occurrence values."""
        key = ("proj", n_padded, n_ids)
        fn = self._update_fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            dim = self.host.dim

            def project(rows, inverse):
                return jnp.take(rows[:, :dim], inverse, axis=0)

            fn = jax.jit(project)
            self._update_fns[key] = fn
        return fn

    def gather(self, ids, insert_missing: bool = True):
        """One-call gather (prepare inline): host-store-compatible
        surface for code that does not pipeline.

        ``insert_missing=False`` is the read-only probe the host
        stores honor, so it must not create keys OR promote rows into
        the device tier: resident rows read from HBM, the rest read
        through the host path (which faults disk-cold rows but never
        invents keys), absent keys read zeros."""
        if insert_missing:
            prep = self.prepare(ids)
            try:
                return self.gather_for(prep)
            finally:
                self.release(prep)
        import jax.numpy as jnp

        ids = np.ascontiguousarray(ids, np.int64).ravel()
        unique, inverse = np.unique(ids, return_inverse=True)
        dim = self.host.dim
        vals = np.zeros((len(unique), dim), np.float32)
        with self._lock:
            slots = self.hot.lookup(unique)
            resident = slots >= 0
            if resident.any():
                rows = np.asarray(
                    self.hot.gather_rows(slots[resident])
                )
                vals[resident] = rows[:, :dim]
        missing = unique[~resident]
        if len(missing):
            if self._spills_racing(missing):
                self.join_spills()
            vals[~resident] = self.host.gather(
                missing, insert_missing=False
            )
        return jnp.asarray(vals[inverse])

    def release(self, prep: PreparedBatch):
        """Return the pins a ``prepare`` took. ``apply_grads`` does
        this implicitly; gather-only consumers (eval) call it once the
        step no longer needs the rows resident. Idempotent."""
        with self._lock:
            if prep.released:
                return
            prep.released = True
            if prep.generation == self._gen:
                self.hot.unpin(prep.slots[: prep.n_unique])

    def _update_fn(self, n_padded: int, n_ids: int):
        """Jitted (padded rows, per-occurrence grads, inverse, step) ->
        new padded rows for this optimizer (cached per shape bucket).
        Duplicate occurrences are segment-summed inside the jit; padded
        rows receive zero gradient, so their update is the identity."""
        key = (n_padded, n_ids, self.host.num_slots)
        fn = self._update_fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        dim = self.host.dim
        opt = self._opt
        lr, eps = self._lr, self._eps
        mom, b1, b2 = self._momentum, self._beta1, self._beta2

        def update(rows, grads_occ, inverse, step):
            grads = jax.ops.segment_sum(
                grads_occ, inverse, num_segments=n_padded
            )
            w = rows[:, :dim]
            if opt == "adagrad":
                acc = rows[:, dim : 2 * dim] + grads * grads
                w = w - lr * grads / (jnp.sqrt(acc) + eps)
                rows = rows.at[:, dim : 2 * dim].set(acc)
            elif opt == "momentum":
                m = mom * rows[:, dim : 2 * dim] + grads
                w = w - lr * m
                rows = rows.at[:, dim : 2 * dim].set(m)
            else:  # adam
                m = b1 * rows[:, dim : 2 * dim] + (1.0 - b1) * grads
                v = b2 * rows[:, 2 * dim : 3 * dim] + (
                    1.0 - b2
                ) * grads * grads
                bc1 = 1.0 - b1 ** step.astype(jnp.float32)
                bc2 = 1.0 - b2 ** step.astype(jnp.float32)
                w = w - lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
                rows = rows.at[:, dim : 2 * dim].set(m)
                rows = rows.at[:, 2 * dim : 3 * dim].set(v)
            return rows.at[:, :dim].set(w)

        fn = jax.jit(update)
        self._update_fns[key] = fn
        return fn

    def apply_grads(self, prep: PreparedBatch, row_grads, step: int = 1):
        """On-device sparse update: segment-sum duplicate occurrences
        onto the unique rows, run the optimizer math, scatter the new
        rows back into the HBM table. Never touches the host link."""
        import jax.numpy as jnp

        grads = jnp.asarray(row_grads, jnp.float32).reshape(
            len(prep.ids), self.host.dim
        )
        fn = self._update_fn(len(prep.slots), len(prep.ids))
        with self._lock:
            self._check_gen(prep)
            rows = self.hot.gather_rows(prep.slots)
            new_rows = fn(
                rows,
                grads,
                prep.inverse,
                jnp.asarray(max(1, int(step)), jnp.int32),
            )
            self.hot.scatter_rows(prep.slots, new_rows, dirty=True)
            if not prep.released:
                prep.released = True
                self.hot.unpin(prep.slots[: prep.n_unique])

    # -- spill / flush / checkpoint ------------------------------------
    def evict_to_host(self, keep_rows: Optional[int] = None) -> int:
        """Spill coldest resident rows until at most ``keep_rows``
        remain (default: half the capacity) — the HBM→host analogue of
        ``TieredKvEmbedding.evict_cold``, run at checkpoint cadence."""
        with self._lock:
            keep = (
                self.hot.capacity // 2 if keep_rows is None else keep_rows
            )
            occupied = np.nonzero(
                (self.hot._id_of >= 0) & (self.hot._pins == 0)
            )[0]
            excess = len(occupied) - max(0, keep)
            if excess <= 0:
                return 0
            order = np.argsort(
                self.hot._last_used[occupied], kind="stable"
            )
            victims = occupied[order[:excess]]
            self._spill(victims)
            self.hot.drop(victims)
            self._bump_gen()
        return int(excess)

    def _bump_gen(self):
        """Invalidate every outstanding PreparedBatch (they must
        re-prepare) and reset ALL pins with them: a stale prep's
        release() is a no-op by design, so leaving its pins in place
        would leak one batch of un-evictable slots per bump."""
        self._gen += 1
        self.hot._pins[:] = 0

    def flush(self) -> int:
        """Write every dirty resident row back to the host store and
        wait for the spill drain: after flush the host tiers hold the
        complete, current state (the checkpoint precondition). Rows
        STAY resident (and clean)."""
        with self._lock:
            dirty = self.hot.dirty_slots()
            if len(dirty):
                ids = self.hot._id_of[dirty].copy()
                rows = np.asarray(self.hot.gather_rows(dirty))
                self.host.import_rows(ids, rows)
                self.stats.spill_rows += len(ids)
                self.stats.spill_bytes += rows.nbytes
                self.stats.host_leg_s += self._price(
                    rows.nbytes, h2d=False
                )
                self.hot.clear_dirty(dirty)
        self.join_spills()
        return int(len(dirty))

    def join_spills(self, timeout: float = 30.0):
        """Barrier on the async spill drain (checkpoint/teardown).
        Waits on the in-flight COUNT, not the queue: the queue empties
        the moment the drain dequeues, while the import of that last
        item may still be running — returning then would let a
        checkpoint export race it."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                if self._spills_inflight == 0:
                    break
            if time.monotonic() > deadline:
                raise TimeoutError("embedding spill drain wedged")
            time.sleep(0.002)
        if self._spill_err is not None:
            err, self._spill_err = self._spill_err, None
            raise err

    def close(self):
        if self._spill_thread is not None:
            self._spill_q.put(None)
            self._spill_thread.join(timeout=5.0)
            self._spill_thread = None

    # -- host-store passthrough (checkpoint / reshard surface) ---------
    def export_state(self, since_versions=None):
        """Flush-then-export: the host store's merged view IS the
        checkpoint (device-resident training included)."""
        self.flush()
        return self.host.export_state(since_versions)

    def shard_versions(self):
        return self.host.shard_versions()

    def import_state(self, state):
        """Restore into the host tier and invalidate the device tier:
        resident rows may now be stale, so they are dropped (clean —
        the import is authoritative) and will fault back in."""
        with self._lock:
            occupied = np.nonzero(self.hot._id_of >= 0)[0]
            self.hot.drop(occupied)
            self._bump_gen()
        self.host.import_state(state)

    def warm_reshard(self, new_num_shards: int):
        """Flush, then warm-reshard the host store (move-only): the
        device tier keeps serving — residency survives a reshard
        because the id→slot map is independent of host routing."""
        self.flush()
        return self.host.warm_reshard(new_num_shards)

    def __len__(self) -> int:
        return len(self.host)

    @property
    def dim(self) -> int:
        return self.host.dim

    @property
    def num_slots(self) -> int:
        return self.host.num_slots

    # -- telemetry -----------------------------------------------------
    def export_metrics(self, registry=None) -> Dict[str, float]:
        """Publish per-table gauges; returns the scalar dict the
        trainer forwards to the master (→ Brain job_metrics)."""
        if registry is None:
            from dlrover_tpu.obs.metrics import default_registry

            registry = default_registry()
        scalars = self.stats.as_dict()
        scalars["emb_hot_rows"] = float(len(self.hot))
        scalars["emb_hbm_bytes"] = float(self.hot.hbm_bytes)
        # refresh the arbiter's standing-demand hints (the dry-runner
        # prices aggregate host traffic from these): average bytes per
        # gather cycle so far
        gathers = max(self.stats.gathers, 1)
        self._fault_stream.demand_bytes_per_step = (
            self.stats.fault_bytes // gathers
        )
        self._spill_stream.demand_bytes_per_step = (
            self.stats.spill_bytes // gathers
        )
        for name, value in scalars.items():
            registry.gauge(
                f"dlrover_embedding_{name[4:]}",
                f"embedding hot tier: {name[4:]}",
                labelnames=("table",),
            ).labels(self.table_name).set(value)
        return scalars
