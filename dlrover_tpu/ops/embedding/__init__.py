"""Native (C++) hash-table embedding store for elastic sparse training.

Parity: TFPlus KvVariable stack (SURVEY §2.4) — see store.py and
kv_store.cc for the component mapping.
"""

from dlrover_tpu.ops.embedding.store import (  # noqa: F401
    KvEmbeddingStore,
    ShardedKvEmbedding,
    WarmReshardReport,
)
from dlrover_tpu.ops.embedding.ckpt import (  # noqa: F401
    IncrementalCheckpointManager,
)
from dlrover_tpu.ops.embedding.tiered import (  # noqa: F401
    NativeTieredKvEmbedding,
    TieredKvEmbedding,
    three_tier_embedding,
)
from dlrover_tpu.ops.embedding.device_tier import (  # noqa: F401
    DeviceHotTier,
    DeviceSparseEmbedding,
    EmbeddingTierStats,
    PreparedBatch,
)
