"""Int8 quantized matmul for training (AQT-style).

Parity: atorch's FP8 optimization entry (auto/opt_lib
optimization_library.py:39-58 lists "fp8"; module-replace pairs layers
with TransformerEngine fp8 kernels). TPUs have no fp8 MXU mode — the
low-precision compute path is **int8** (v5e: 394 int8 TOPS vs 197 bf16
TFLOPs), so the TPU-native equivalent is dynamic-range int8 quantized
matmul, the AQT recipe (public google/aqt):

- per-contraction-slice scales: A[M,K] rows and B[K,N] columns each get
  ``max|.|/127``, so the int8 dot accumulates in int32 on the MXU and
  rescales once per output element;
- **straight-through estimator** backward: gradients flow as if the
  matmul were exact (quantization noise is treated as additive), in the
  activation dtype — the standard quantized-training trade that keeps
  the backward stable;
- drop-in: ``TransformerConfig.int8_mlp`` routes the MLP projections
  (the dominant matmuls) through this op; everything else (norms,
  attention softmax, residuals) stays in bf16/fp32.

When it pays — measured on v5e-lite (2026-07, chained in-jit loops so
tunnel dispatch overhead cannot pollute the timing; an earlier
unchained measurement had wrongly concluded bf16 wins):

    M=8192 tokens          bf16 TF   int8 TF   speedup
    K=768,  N=3072  (124M)   14.7      24.6     1.67x
    K=1600, N=6400  (1.5B)   49.2      82.3     1.67x
    K=4096, N=11008 (7B)    115.9     182.7     1.58x
    K=8192, N=8192          131.1     203.7     1.55x

int8 wins at EVERY training-relevant MLP shape once the token batch is
MXU-sized (M >= ~8k): the dynamic-quantize pass costs one extra read of
each operand, repaid by the 2x int8 MXU rate. ``int8_mlp`` remains
default-off only because quantization noise is a per-model accuracy
decision, not a performance one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_int8(x: jnp.ndarray, axis: int):
    """Symmetric per-slice int8 quantization along ``axis`` (the
    contraction axis): returns (codes int8, scale f32 with ``axis``
    reduced to 1)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    codes = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return codes, scale


def _int8_matmul_fwd_impl(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a [..., M, K] @ b [K, N] with both sides int8-quantized."""
    qa, sa = quantize_int8(a, axis=-1)  # scales [..., M, 1]
    qb, sb = quantize_int8(b, axis=0)  # scales [1, N]
    acc = jax.lax.dot_general(
        qa,
        qb,
        (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * sa * sb
    return out.astype(a.dtype)


@jax.custom_vjp
def int8_matmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _int8_matmul_fwd_impl(a, b)


def _fwd(a, b):
    return _int8_matmul_fwd_impl(a, b), (a, b)


def _bwd(res, g):
    a, b = res
    # straight-through: exact-matmul cotangents in the activation dtype
    da = jnp.einsum("...mn,kn->...mk", g, b.astype(g.dtype))
    db = jnp.einsum(
        "...mk,...mn->kn", a.astype(g.dtype), g
    ).astype(b.dtype)
    return da.astype(a.dtype), db


int8_matmul.defvjp(_fwd, _bwd)


def int8_einsum_btd_df(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """``btd,df->btf`` through the int8 path (the MLP projection shape)."""
    B, T, D = x.shape
    out = int8_matmul(x.reshape(B * T, D), w)
    return out.reshape(B, T, w.shape[1])
