"""TPU kernels + optimizer math.

Replaces the reference's native op layer: ATorch CUDA quantization kernels
(atorch/atorch/ops/csrc/*.cu), flash-attention glue
(modules/transformer/layers.py:54-1168), and the AGD/WSAM optimizers
(optimizers/agd.py:18, wsam.py:11) — as Pallas kernels and optax
transforms.
"""

from dlrover_tpu.ops.flash_attention import flash_attention  # noqa: F401
from dlrover_tpu.ops.int8_matmul import (  # noqa: F401
    int8_einsum_btd_df,
    int8_matmul,
    quantize_int8,
)
from dlrover_tpu.ops.optimizers import agd, make_wsam_grad_fn  # noqa: F401
from dlrover_tpu.ops.quantized_optim import (  # noqa: F401
    adamw_4bit,
    adamw_8bit,
    adamw_8bit_flat,
    dequantize_4bit,
    dequantize_8bit,
    quantize_4bit,
    quantize_8bit,
)
