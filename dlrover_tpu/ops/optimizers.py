"""AGD and WSAM optimizers as JAX/optax transforms.

Parity: ATorch ``AGD`` (atorch/atorch/optimizers/agd.py:18, NeurIPS'23
"Auto-switchable optimizer with stepwise gradient difference
preconditioning") and ``WeightedSAM`` (atorch/atorch/optimizers/wsam.py:11,
KDD'23 "Weighted Sharpness as a Regularization Term"). The reference
implements both as in-place torch optimizers; here they are pure
functional transforms — AGD is an ``optax.GradientTransformation`` that
composes with the rest of the optax chain, and WSAM (which needs a second
gradient evaluation at perturbed params) is a gradient-function wrapper,
the functional analog of the reference's closure-based ``step``.

All state updates are elementwise pytree maps — XLA fuses them into a
handful of HBM-bandwidth-bound loops, which is exactly what the
reference's fused CUDA "multi-tensor apply" achieves by hand.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class AGDState(NamedTuple):
    count: jnp.ndarray  # int32 step counter
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    max_exp_avg_sq: Optional[optax.Updates]


def agd(
    learning_rate: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
    clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """AGD: Adam-shaped update whose second moment tracks the *stepwise
    difference* of bias-corrected first moments instead of the raw
    gradient (the auto-switch between gradient-descent-like and
    Newton-like behavior in the paper). Decoupled weight decay.

    Matches the reference step math (agd.py:118-156): with
    ``m_t = b1*m_{t-1} + (1-b1)*g``,
    ``u_t = m_t/bc1_t - m_{t-1}/bc1_{t-1}`` (just ``m_1/bc1_1`` at t=1),
    ``v_t = b2*v_{t-1} + (1-b2)*u_t^2``,
    update = ``m_t / max(sqrt(v_t), delta*sqrt(bc2_t)) * lr*sqrt(bc2_t)/bc1_t``.
    """

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return AGDState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=jax.tree.map(jnp.zeros_like, params),
            max_exp_avg_sq=(
                jax.tree.map(jnp.zeros_like, params) if amsgrad else None
            ),
        )

    def update_fn(grads, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        bc1_old = 1.0 - b1 ** (cf - 1.0)  # 0 at t=1
        bc1 = 1.0 - b1**cf
        bc2 = 1.0 - b2**cf

        m_new = jax.tree.map(
            lambda m, g: b1 * m + (1.0 - b1) * g, state.exp_avg, grads
        )
        # stepwise first-moment difference; at t=1 bc1_old=0 and the
        # reference special-cases to m_1/bc1_1 — jnp.where keeps it traced
        def _diff(m, m_old):
            first = m / bc1
            later = m / bc1 - m_old / jnp.maximum(bc1_old, 1e-38)
            return jnp.where(count == 1, first, later)

        diffs = jax.tree.map(_diff, m_new, state.exp_avg)
        v_new = jax.tree.map(
            lambda v, d: b2 * v + (1.0 - b2) * d * d,
            state.exp_avg_sq,
            diffs,
        )
        if amsgrad:
            v_hat = jax.tree.map(
                jnp.maximum, state.max_exp_avg_sq, v_new
            )
        else:
            v_hat = v_new

        denom_floor = delta * jnp.sqrt(bc2)
        lr_adjust = learning_rate * jnp.sqrt(bc2) / bc1

        def _step(m, v):
            u = m / jnp.maximum(jnp.sqrt(v), denom_floor)
            if clip is not None:
                u = jnp.clip(u, -clip, clip)
            return -lr_adjust * u

        updates = jax.tree.map(_step, m_new, v_hat)
        if weight_decay and params is not None:
            updates = jax.tree.map(
                lambda u, p: u - learning_rate * weight_decay * p,
                updates,
                params,
            )
        return updates, AGDState(
            count=count,
            exp_avg=m_new,
            exp_avg_sq=v_new,
            max_exp_avg_sq=v_hat if amsgrad else None,
        )

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# WSAM
# ---------------------------------------------------------------------------
def _global_norm(tree):
    return jnp.sqrt(
        sum(jnp.sum(x.astype(jnp.float32) ** 2) for x in jax.tree.leaves(tree))
    )


def make_wsam_grad_fn(
    grad_fn: Callable,
    *,
    rho: float = 0.05,
    gamma: float = 0.9,
    sam_eps: float = 1e-12,
    adaptive: bool = False,
    decouple: bool = True,
    grad_reduce: Optional[Callable] = None,
):
    """Wrap ``grad_fn(params, *args) -> (loss, grads)`` into a WSAM
    gradient function.

    Functional analog of the reference's first_step/second_step closure
    protocol (wsam.py:51-108): perturb to the local maximum
    ``w + rho*g/||g||``, take the gradient there, and either blend
    (``decouple=False``: ``alpha*g2 + (1-alpha)*g1`` fed to the base
    optimizer) or decouple the sharpness term (``decouple=True``: base
    optimizer sees ``g1``; the caller applies the returned ``sharpness``
    tree as an extra ``-lr*sharpness`` step, mirroring
    ``p.add_(sharpness, alpha=-lr*alpha)``).

    ``grad_reduce`` (e.g. a ``jax.lax.pmean`` closure) is applied to both
    gradient evaluations, the analog of the DDP all_reduce in first/second
    step. Returns ``wsam_grad(params, *args) -> (loss, grads, sharpness)``
    where ``sharpness`` is a zero tree when ``decouple=False``.
    """
    alpha = gamma / (1.0 - gamma)

    def wsam_grad(params, *args):
        loss, g1 = grad_fn(params, *args)
        if grad_reduce is not None:
            g1 = grad_reduce(g1)
        if adaptive:
            weighted = jax.tree.map(lambda p, g: p * p * g, params, g1)
            norm = _global_norm(weighted)
        else:
            norm = _global_norm(g1)
        scale = rho / (norm + sam_eps)

        def _perturb(p, g):
            e_w = (p * p if adaptive else 1.0) * g * scale
            return p + e_w

        perturbed = jax.tree.map(_perturb, params, g1)
        _, g2 = grad_fn(perturbed, *args)
        if grad_reduce is not None:
            g2 = grad_reduce(g2)

        if decouple:
            sharpness = jax.tree.map(
                lambda a, b: alpha * (a - b), g2, g1
            )
            return loss, g1, sharpness
        blended = jax.tree.map(
            lambda a, b: alpha * a + (1.0 - alpha) * b, g2, g1
        )
        zeros = jax.tree.map(jnp.zeros_like, g1)
        return loss, blended, zeros

    return wsam_grad


def apply_wsam_sharpness(updates, sharpness, learning_rate: float):
    """Fold the decoupled sharpness term into optimizer updates:
    ``updates - lr*sharpness`` (reference wsam.py:104-108)."""
    return jax.tree.map(
        lambda u, s: u - learning_rate * s, updates, sharpness
    )
