"""Deterministic fault injection: named fault points in production code.

The only failure testing the repo had was the random-SIGKILL chaos soak —
process death, nothing else, and nothing reproducible. This module gives
the storage/RPC failure scenarios a deterministic harness: production
code declares *fault points* (named sites like ``ckpt.shard_write``),
and a test/bench/operator arms them with spec strings::

    site:kind:prob[:seed]
    site:kind:@N[:seed]

    ckpt.shard_write:torn_write:1.0        # every shard write is torn
    ckpt.persist:enospc:0.5:42             # seeded coin per persist
    rpc.send:delay:0.2;prefetch.pull:io_error:0.1
    node.preempt:kill:@7                   # die at exactly the 7th step

``@N`` is the chaos-harness trigger form: the spec fires on EXACTLY the
Nth evaluation of its site (and never again) — "SIGKILL the worker at
its 7th step boundary" is a scripted, replayable event rather than a
seeded coin.

activated programmatically (``configure``) or via the
``DLROVER_TPU_FAULTS`` env var (read once at first use; tests call
``reload_from_env``). Multiple specs separate with ``;`` or ``,``.

Determinism: each armed spec owns a ``random.Random`` seeded with its
``seed`` field (or a stable hash of the spec string), so the *sequence*
of trigger decisions is reproducible for a fixed call order —
"the 3rd shard write fails" replays exactly.

Fault kinds:

- ``enospc``  — raise ``OSError(ENOSPC)`` at the site (disk full);
- ``io_error`` — raise ``OSError(EIO)`` (generic storage/RPC failure);
- ``delay``   — sleep ``DELAY_S`` (straggling storage/RPC);
- ``torn_write`` — truncate the payload to a seeded fraction (a write
  that landed partially despite the journaled rename — FS lying about
  durability); at fixed-size sites (shm) the tail is zeroed instead;
- ``bit_flip`` — flip one seeded bit of the payload (bit rot / DMA
  corruption);
- ``scale`` — multiply a deterministic slice of a *numeric* payload by
  ``SCALE_FACTOR`` (silent data corruption: a chip computing
  wrong-but-FINITE numbers — a bit flip on f32 usually yields NaN,
  which a cheap finite fence catches trivially; finite-but-wrong is the
  case the SDC detector must earn). Only meaningful at
  :func:`corrupt_array` sites; :func:`corrupt` on raw bytes ignores it
  (no dtype to scale);
- ``kill`` — hard process death (``os._exit(137)``, no atexit, no
  flushes): a SIGKILL/OOM-killer/hard-preemption stand-in the chaos
  harness (``tools/chaos.py``) scripts at sites like ``node.preempt``.

Control kinds (``enospc``/``io_error``/``delay``/``kill``) fire at any
site through :func:`fire`; data kinds only act at sites that pass their
payload through :func:`corrupt`/:func:`corrupt_array`.

Every triggered fault counts into the PR-4 metrics registry
(``dlrover_faults_triggered_total{site,kind}``) and a cheap local
tally (:func:`triggered`, :func:`triggered_total`) for asserts.

The inactive fast path is one module-global bool check — production
code pays nothing when no fault is armed.
"""

from __future__ import annotations

import errno
import os
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

ENV_VAR = "DLROVER_TPU_FAULTS"

# seconds slept by the ``delay`` kind (kept small: the point is to widen
# race windows deterministically, not to stall test suites)
DELAY_S = 0.05

KINDS = (
    "enospc",
    "io_error",
    "delay",
    "torn_write",
    "bit_flip",
    "scale",
    "kill",
)
_DATA_KINDS = ("torn_write", "bit_flip", "scale")

# the ``scale`` kind's corruption factor: large enough that a robust
# z-score over replica peers saturates, small enough to stay finite
# through a full fp32 backward pass (the point of the kind)
SCALE_FACTOR = 32.0

# the registered sites — arming a typo'd site is a hard error, so a
# chaos matrix can never silently test nothing. Production code may
# fire sites not in this set (they just can't be armed until added).
FAULT_SITES = frozenset(
    {
        "ckpt.shard_write",  # shard payload bytes → storage
        "ckpt.done_write",  # per-shard done file → storage
        "ckpt.tracker_write",  # commit tracker / history publish
        "ckpt.persist",  # whole persist pass (saver or sync engine)
        "ckpt.shm_stage",  # device/host bytes → shm segment
        "rpc.send",  # MasterClient._call request leg
        "rpc.recv",  # MasterClient._call response leg
        "rendezvous.join",  # agent's join-rendezvous report
        "reshard.gather",  # on-device resize state remap
        "prefetch.pull",  # prefetch producer's source pull
        "node.preempt",  # trainer step boundary (preemption arrival)
        "embedding.export",  # embedding ckpt bytes → storage (data
        # kinds corrupt the serialized npz/delta payload)
        "embedding.import",  # embedding ckpt read leg (restore)
        "transfer.stripe",  # one striped chunk move on a rail (the
        # multi-rail scheduler's per-chunk grant + mover)
        "serve.subscribe",  # subscriber's poll of the shm publication
        "serve.swap",  # serving engine adopting a newer weight frame
        "serve.stale_read",  # between zero-copy map and the seqlock
        # generation re-check (a delay here widens the torn-frame
        # race window deterministically)
        "device.sdc",  # one device silently computing wrong numbers
        # (``scale`` corrupts that lane's local gradient; the SDC
        # detector/audit chain must convict exactly that device)
    }
)


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault: parsed form of ``site:kind:prob[:seed]`` or the
    scripted ``site:kind:@N[:seed]`` form (``nth`` > 0 ⇒ fire on
    exactly the Nth evaluation, never again)."""

    site: str
    kind: str
    prob: float
    seed: int
    nth: int = 0

    @classmethod
    def parse(cls, raw: str) -> "FaultSpec":
        parts = [p.strip() for p in raw.strip().split(":")]
        if len(parts) not in (3, 4):
            raise ValueError(
                f"fault spec {raw!r}: want site:kind:prob[:seed] "
                f"or site:kind:@N[:seed]"
            )
        site, kind = parts[0], parts[1]
        if site != "*" and site not in FAULT_SITES:
            raise ValueError(
                f"fault spec {raw!r}: unknown site {site!r} "
                f"(known: {sorted(FAULT_SITES)})"
            )
        if kind not in KINDS:
            raise ValueError(
                f"fault spec {raw!r}: unknown kind {kind!r} "
                f"(known: {list(KINDS)})"
            )
        nth = 0
        if parts[2].startswith("@"):
            # scripted trigger: exactly the Nth evaluation of the site
            try:
                nth = int(parts[2][1:])
            except ValueError:
                raise ValueError(f"fault spec {raw!r}: bad @N trigger")
            if nth <= 0:
                raise ValueError(
                    f"fault spec {raw!r}: @N trigger must be >= 1"
                )
            prob = 1.0
        else:
            try:
                prob = float(parts[2])
            except ValueError:
                raise ValueError(f"fault spec {raw!r}: bad probability")
            if not 0.0 <= prob <= 1.0:
                raise ValueError(
                    f"fault spec {raw!r}: probability must be in [0, 1]"
                )
        if len(parts) == 4:
            seed = int(parts[3])
        else:
            # no explicit seed: still deterministic — derive from the
            # spec text so the same spec string replays the same run
            seed = zlib.crc32(raw.strip().encode())
        return cls(site=site, kind=kind, prob=prob, seed=seed, nth=nth)


class _Armed:
    """A spec plus its private RNG (the determinism unit)."""

    __slots__ = ("spec", "_rng", "_lock", "_visits")

    def __init__(self, spec: FaultSpec):
        import random

        self.spec = spec
        self._rng = random.Random(spec.seed)
        self._lock = threading.Lock()
        self._visits = 0  # evaluations of the site (@N scripting)

    def draw(self) -> bool:
        with self._lock:
            if self.spec.nth:
                # scripted: exactly the Nth evaluation, never again
                self._visits += 1
                if self._visits != self.spec.nth:
                    return False
                # consume a draw for downstream seeded decisions
                self._rng.random()
                return True
            if self.spec.prob >= 1.0:
                # still consume a draw so downstream decisions (torn
                # fraction, flipped bit) stay on the seeded sequence
                self._rng.random()
                return True
            return self._rng.random() < self.spec.prob

    def uniform(self) -> float:
        with self._lock:
            return self._rng.random()


class FaultInjector:
    """Process-wide registry of armed fault specs."""

    def __init__(self):
        self._by_site: Dict[str, List[_Armed]] = {}
        self._wildcards: List[_Armed] = []
        self._counts: Dict[Tuple[str, str], int] = {}
        self._lock = threading.Lock()

    # -- arming --------------------------------------------------------
    def configure(self, spec_str: str):
        """Arm every spec in ``spec_str`` (``;``/``,`` separated),
        replacing the current configuration."""
        self.clear()
        for raw in spec_str.replace(",", ";").split(";"):
            if raw.strip():
                self.arm(FaultSpec.parse(raw))

    def arm(self, spec: FaultSpec):
        global _active
        armed = _Armed(spec)
        with self._lock:
            if spec.site == "*":
                self._wildcards.append(armed)
            else:
                self._by_site.setdefault(spec.site, []).append(armed)
        _active = True
        logger.info(
            f"fault armed: {spec.site}:{spec.kind}:{spec.prob}"
            f" (seed={spec.seed})"
        )

    def clear(self):
        global _active
        with self._lock:
            self._by_site.clear()
            self._wildcards.clear()
        _active = False

    def active(self) -> bool:
        return bool(self._by_site or self._wildcards)

    def specs(self) -> List[FaultSpec]:
        with self._lock:
            out = [a.spec for a in self._wildcards]
            for lst in self._by_site.values():
                out.extend(a.spec for a in lst)
            return out

    # -- accounting ----------------------------------------------------
    def _count(self, site: str, kind: str):
        with self._lock:
            key = (site, kind)
            self._counts[key] = self._counts.get(key, 0) + 1
        try:
            from dlrover_tpu.obs.metrics import default_registry

            default_registry().counter(
                "dlrover_faults_triggered_total",
                "injected faults that fired, by site and kind",
                labelnames=("site", "kind"),
            ).labels(site, kind).inc()
        except Exception:  # metrics must never break the fault itself
            pass
        logger.warning(f"fault injected: {site}:{kind}")

    def triggered(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._counts)

    def reset_counts(self):
        with self._lock:
            self._counts.clear()

    def triggered_total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    # -- firing --------------------------------------------------------
    def _armed_for(self, site: str) -> List[_Armed]:
        with self._lock:
            return list(self._by_site.get(site, ())) + list(
                self._wildcards
            )

    def _raise_or_delay(self, site: str, armed: _Armed):
        kind = armed.spec.kind
        self._count(site, kind)
        if kind == "enospc":
            raise OSError(
                errno.ENOSPC,
                f"injected ENOSPC at {site}",
            )
        if kind == "io_error":
            raise OSError(errno.EIO, f"injected I/O error at {site}")
        if kind == "delay":
            time.sleep(DELAY_S)
        if kind == "kill":
            # hard process death: no atexit, no finally, no flushes —
            # the closest in-process stand-in for SIGKILL / OOM-killer /
            # hard preemption (the chaos harness asserts recovery)
            logger.warning(f"fault kill: hard exit(137) at {site}")
            os._exit(137)

    def fire(self, site: str):
        """Evaluate the control-kind specs armed for ``site``: raise
        OSError (enospc/io_error) or sleep (delay). Data kinds are
        ignored here — they only act where a payload flows through
        ``corrupt``/``corrupt_array``."""
        for armed in self._armed_for(site):
            if armed.spec.kind in _DATA_KINDS:
                continue
            if armed.draw():
                self._raise_or_delay(site, armed)

    def corrupt(self, site: str, blob: bytes) -> bytes:
        """Pass write-path payload bytes through the armed specs:
        control kinds raise/sleep, ``torn_write`` truncates to a seeded
        fraction, ``bit_flip`` flips one seeded bit. Returns the
        (possibly corrupted) payload."""
        for armed in self._armed_for(site):
            kind = armed.spec.kind
            if kind not in _DATA_KINDS:
                if armed.draw():
                    self._raise_or_delay(site, armed)
                continue
            if kind == "scale":
                # raw bytes carry no dtype to scale — the kind only
                # acts at corrupt_array sites
                continue
            if not armed.draw():
                continue
            self._count(site, kind)
            if kind == "torn_write":
                # keep at least one byte and strictly fewer than all:
                # both extremes would be a different failure class
                frac = 0.1 + 0.8 * armed.uniform()
                cut = max(1, min(len(blob) - 1, int(len(blob) * frac)))
                blob = blob[:cut]
            elif kind == "bit_flip" and blob:
                pos = int(armed.uniform() * len(blob)) % len(blob)
                bit = int(armed.uniform() * 8) % 8
                b = bytearray(blob)
                b[pos] ^= 1 << bit
                blob = bytes(b)
        return blob

    def corrupt_array(self, site: str, arr: np.ndarray) -> np.ndarray:
        """Array flavor of :meth:`corrupt` for fixed-size destinations
        (shm chunks): ``bit_flip`` flips one seeded bit in a copy,
        ``torn_write`` zeroes the tail half (a partial memcpy),
        ``scale`` multiplies a deterministic slice of a numeric array
        by ``SCALE_FACTOR`` (finite-but-wrong values, the shape a
        silently-bad chip produces) — the byte length never changes."""
        for armed in self._armed_for(site):
            kind = armed.spec.kind
            if kind not in _DATA_KINDS:
                if armed.draw():
                    self._raise_or_delay(site, armed)
                continue
            if not armed.draw():
                continue
            self._count(site, kind)
            if kind == "scale":
                # operate on the TYPED values, not the byte view: the
                # corruption must stay finite and dtype-shaped
                typed = np.ascontiguousarray(arr).reshape(-1).copy()
                if typed.size == 0 or not np.issubdtype(
                    typed.dtype, np.number
                ):
                    continue
                span = max(1, typed.size // 8)
                start = int(
                    armed.uniform() * max(1, typed.size - span)
                ) % typed.size
                typed[start:start + span] = (
                    typed[start:start + span]
                    * typed.dtype.type(SCALE_FACTOR)
                )
                arr = typed
                continue
            flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
            flat = flat.copy()
            if flat.size == 0:
                continue
            if kind == "torn_write":
                flat[flat.size // 2:] = 0
            else:  # bit_flip
                pos = int(armed.uniform() * flat.size) % flat.size
                flat[pos] ^= np.uint8(
                    1 << (int(armed.uniform() * 8) % 8)
                )
            arr = flat
        return arr


# -- process-wide singleton --------------------------------------------------

_injector = FaultInjector()
_active = False  # mirrors _injector.active(); the zero-cost gate
_env_loaded = False


def injector() -> FaultInjector:
    _load_env_once()
    return _injector


def _load_env_once():
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    raw = os.getenv(ENV_VAR, "")
    if raw:
        try:
            _injector.configure(raw)
        except ValueError as e:
            # a typo'd env spec must fail loudly, not silently test
            # nothing — but not crash an unrelated import path
            logger.error(f"bad {ENV_VAR}: {e}")
            raise


def reload_from_env():
    """Re-read ``DLROVER_TPU_FAULTS`` (tests that monkeypatch env)."""
    global _env_loaded
    _env_loaded = False
    _injector.clear()
    _load_env_once()


def configure(spec_str: str):
    injector().configure(spec_str)


def reset():
    """Disarm everything and zero the tallies (test teardown)."""
    global _env_loaded
    _env_loaded = True  # an explicit reset wins over the env
    _injector.clear()
    _injector.reset_counts()


def active() -> bool:
    return _active


def fire(site: str):
    """Production call site: no-op unless a fault is armed (the first
    call pays one env read; every later inactive call is one bool)."""
    if _env_loaded and not _active:
        return
    _load_env_once()
    if _active:
        _injector.fire(site)


def corrupt(site: str, blob: bytes) -> bytes:
    if _env_loaded and not _active:
        return blob
    _load_env_once()
    return _injector.corrupt(site, blob) if _active else blob


def corrupt_array(site: str, arr: np.ndarray) -> np.ndarray:
    if _env_loaded and not _active:
        return arr
    _load_env_once()
    return _injector.corrupt_array(site, arr) if _active else arr


def triggered() -> Dict[Tuple[str, str], int]:
    return _injector.triggered()


def triggered_total() -> int:
    return _injector.triggered_total()
