"""Version-compat shims for the JAX API surface this repo uses.

The codebase targets the modern names; older jaxlibs in some
deployment images (0.4.x) keep the same functionality under the
pre-promotion paths. Import the symbols from here so every call site
stays version-agnostic:

- ``shard_map``: promoted to ``jax.shard_map`` in 0.5; lives in
  ``jax.experimental.shard_map`` before that.
- ``pallas_tpu_compiler_params``: ``pltpu.CompilerParams`` was named
  ``TPUCompilerParams`` on 0.4.x.
"""

from __future__ import annotations

import os

try:  # jax >= 0.5
    from jax import shard_map as _shard_map

    _MODERN = True
except ImportError:  # 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _MODERN = False


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    axis_names=None,
    check_vma=None,
    **kwargs,
):
    """``jax.shard_map`` with the modern keyword surface on any version.

    On 0.4.x the same knobs exist under pre-promotion names with
    inverted semantics: ``axis_names`` (manual over THESE axes) maps to
    ``auto`` (its complement — axes left to GSPMD), and ``check_vma``
    was called ``check_rep``."""
    if _MODERN:
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
    else:
        if axis_names is not None:
            kwargs["auto"] = frozenset(mesh.axis_names) - set(
                axis_names
            )
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


# first jax release expected to stabilize residual shardings across
# steps on partial-manual (auto-axis) shard_map regions — the blocker
# that forces grad compression off on dp x tp/sp/ep plans (ROADMAP
# item 4's "once a newer jaxlib" clause, as code). Bump when an actual
# release lands it; until then the probe answers False everywhere and
# the gate in grad_sync._plan_for_mode stays closed.
_AUTO_AXIS_RESIDUAL_MIN_VERSION = (0, 9)


def supports_auto_axis_residual_shardings() -> bool:
    """Capability probe: can the error-feedback residual live across
    steps on a plan whose sync region leaves model axes to GSPMD
    ("auto" axes)? On every jaxlib shipped so far the answer is no —
    the residual's sharding is re-derived per step and AOT executables
    are invalidated — so int8 is forced off on tp/ep meshes. The probe
    turns that comment into code: when a jaxlib at or past
    ``_AUTO_AXIS_RESIDUAL_MIN_VERSION`` lands, int8-on-tp auto-enables
    without a code change here beyond the version bump.

    ``DLROVER_TPU_AUTO_AXIS_RESIDUAL=1`` (or ``0``) overrides for
    testing the enabled path on any version."""
    forced = os.getenv("DLROVER_TPU_AUTO_AXIS_RESIDUAL", "")
    if forced in ("1", "true"):
        return True
    if forced in ("0", "false"):
        return False
    import jax

    try:
        ver = tuple(
            int(p) for p in jax.__version__.split(".")[:2]
        )
    except ValueError:
        return False
    return ver >= _AUTO_AXIS_RESIDUAL_MIN_VERSION


def pcast(x, axis_names, to="varying"):
    """``lax.pcast`` (the VMA replicated→varying marker, jax >= 0.7).

    Older jaxlibs have no varying-manual-axes tracking: inside a
    ``shard_map`` built with ``check_vma=False`` (which this shim maps
    to ``check_rep=False``) replication is simply not checked, so the
    cast is a semantic no-op there."""
    from jax import lax

    fn = getattr(lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axis_names, to=to)


def set_cpu_device_count(n: int) -> None:
    """Force an ``n``-device virtual CPU backend on any jax version.

    Modern jax has the ``jax_num_cpu_devices`` config option; 0.4.x
    only honors the XLA flag, which works as long as the backend has
    not been created yet (creation is lazy even when jax was imported
    at interpreter start by sitecustomize)."""
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", int(n))
        return
    except AttributeError:
        pass
    flag = f"--xla_force_host_platform_device_count={int(n)}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


def set_cpu_collectives(impl: str = "gloo") -> None:
    """Best-effort CPU collectives selection: newer jaxlibs accept the
    config; older single-process ones reject gloo without a distributed
    client — fall back to plain (in-process collectives don't need it).
    """
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except AttributeError:
        return
    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_cpu_collectives_implementation", "none")


def cost_analysis_dict(compiled) -> dict:
    """``compiled.cost_analysis()`` as one flat dict on any version
    (0.4.x returned a list with one per-program dict)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def pallas_interpret_mode() -> bool:
    """True when Pallas kernels must run under the interpreter on this
    backend (anything without a Mosaic TPU compiler). The embedding
    hot-tier gather/scatter kernels pass this to ``pallas_call`` so
    tier-1 runs everywhere: compiled on TPU, interpreted on the CPU
    backend — same kernel, same numerics. ``DLROVER_TPU_PALLAS``
    overrides (``compile``/``interpret``) for debugging."""
    forced = os.getenv("DLROVER_TPU_PALLAS", "")
    if forced == "interpret":
        return True
    if forced == "compile":
        return False
    import jax

    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` under either name."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return cls(**kwargs)


def enable_persistent_compilation_cache(
    cache_dir: str,
    min_compile_secs: float = 0.5,
    min_entry_bytes: int = 0,
) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` on any
    version that has one. Returns False on jaxlibs without the cache
    (the caller falls back to in-process caching only). The two
    threshold knobs arrived later than the cache itself, so each is
    guarded independently."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except AttributeError:
        return False
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", min_compile_secs),
        ("jax_persistent_cache_min_entry_size_bytes", min_entry_bytes),
    ):
        try:
            jax.config.update(opt, val)
        except AttributeError:
            pass
    return True


def serialize_compiled(compiled) -> "bytes | None":
    """Pickle an AOT ``jax.stages.Compiled`` for the on-disk executable
    cache. None when this jaxlib cannot serialize executables or the
    program contains something unpicklable (custom pytree nodes in the
    in/out trees) — callers degrade to memory-only caching."""
    try:
        import pickle

        from jax.experimental import serialize_executable as se

        return pickle.dumps(se.serialize(compiled))
    except Exception:
        return None


def deserialize_compiled(blob: bytes):
    """Inverse of ``serialize_compiled``; None on any failure (version
    skew, device-assignment mismatch, truncated file) — a stale disk
    entry must read as a miss, never an error."""
    try:
        import pickle

        from jax.experimental import serialize_executable as se

        return se.deserialize_and_load(*pickle.loads(blob))
    except Exception:
        return None
