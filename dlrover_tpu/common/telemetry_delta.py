"""Delta-encoded telemetry snapshots for the agent→master batch channel.

The control plane's steady-state wire traffic is dominated by scalar
telemetry dictionaries (registry scalars, goodput categories, pipeline
stats) that barely change between ticks: a 10k-worker fleet re-sending
~100 float keys per node per tick pushes megabytes of identical strings
through the master's deserializer every second. This module is the
codec both ends of `comm.AgentReportBatch` share:

- ``DeltaEncoder`` (agent side) tracks the last snapshot the master
  ACKED per training process and emits only changed keys and removed
  keys. Unchanged scalar keys — and therefore unchanged label sets,
  since labels are inline in the key (``...{category="x"}``) — are not
  re-sent.
- ``DeltaDecoder`` (master side) reconstructs the full per-process
  scalar dict from its stored snapshot plus the delta, and detects when
  it cannot: an unknown node (master restart), an epoch it has never
  seen (agent restart or forced resync) or a sequence gap. In every
  such case ``apply`` returns None and the caller must answer
  ``resync`` — the agent's next batch is a full snapshot.

Protocol invariants:

- A **full** batch (``full=True``) is a snapshot: it unconditionally
  replaces the decoder's node state, whatever epoch/seq it carries.
- A **delta** with ``seq == last_seq + 1`` under the stored epoch
  applies normally.
- A **delta replay** (``seq == last_seq``, same epoch) re-applies
  idempotently: deltas are key assignments and removals, so applying
  the same delta twice converges to the same snapshot (decoder-side
  tolerance for duplicated requests on the wire).
- A **transport failure** makes the client's next batch a full
  snapshot (``rollback``): whether or not the master applied the lost
  batch, a snapshot converges — re-encoding a delta for the same seq
  could silently diverge when a key reverted between send and resend.
- Anything else (epoch mismatch, gap, unknown node) → resync. The
  agent bumps its epoch, re-sends everything, and no scalar is ever
  silently dropped — at worst one tick of latency.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

# per-proc delta payload: (changed keys, removed keys)
ProcSnapshot = Dict[str, float]
ProcDeltaPayload = Tuple[Dict[str, float], List[str]]

_epoch_counter = itertools.count(1)


def _fresh_epoch() -> int:
    """Epoch = client incarnation + resync stream id. Derived from wall
    time so two incarnations of the same node (restart) practically
    never collide, OR-ed with a process-local counter so two encoders
    built in the same millisecond (tests, multi-table) still differ."""
    return ((int(time.time() * 1000) & 0x3FFFFFFF) << 8) | (
        next(_epoch_counter) & 0xFF
    )


class DeltaEncoder:
    """Agent-side delta state for one node's report stream.

    Usage per tick::

        full, seq, deltas = enc.encode({proc_id: scalars, ...})
        ... send; on success response: enc.ack(seq)
        ... on send failure:           enc.rollback(seq)
        ... on resync response:        enc.force_resync()

    Deltas are always computed against the last **acked** snapshot, so
    an unacked change is re-sent next tick and can never be dropped by
    a lost request.
    """

    def __init__(self, epoch: Optional[int] = None):
        self._epoch = int(epoch) if epoch is not None else _fresh_epoch()
        self._seq = 0
        self._acked: Dict[int, ProcSnapshot] = {}
        self._pending: Optional[Tuple[int, Dict[int, ProcSnapshot]]] = None
        self._need_full = True

    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def seq(self) -> int:
        return self._seq

    @property
    def sending_full(self) -> bool:
        """The next ``encode`` will emit a full snapshot."""
        return self._need_full

    def encode(
        self, snapshots: Dict[int, ProcSnapshot]
    ) -> Tuple[bool, int, Dict[int, ProcDeltaPayload]]:
        """Returns ``(full, seq, {proc_id: (changed, removed)})``.

        ``full=True`` means ``changed`` is the complete snapshot per
        proc (``removed`` empty). A proc present in the acked state but
        absent from ``snapshots`` emits an all-keys-removed entry, so
        the master never keeps ghost scalars of a departed process."""
        snapshots = {int(p): dict(s) for p, s in snapshots.items()}
        self._seq += 1
        out: Dict[int, ProcDeltaPayload] = {}
        if self._need_full:
            for p, s in snapshots.items():
                out[p] = (dict(s), [])
        else:
            for p, cur in snapshots.items():
                prev = self._acked.get(p, {})
                changed = {
                    k: v for k, v in cur.items() if prev.get(k) != v
                }
                removed = [k for k in prev if k not in cur]
                if changed or removed:
                    out[p] = (changed, removed)
            for p, prev in self._acked.items():
                if p not in snapshots and prev:
                    out[p] = ({}, list(prev))
        self._pending = (self._seq, snapshots)
        return self._need_full, self._seq, out

    def ack(self, seq: int) -> None:
        """The master applied batch ``seq``: its snapshot becomes the
        delta base for the next encode."""
        if self._pending is not None and self._pending[0] == seq:
            self._acked = self._pending[1]
            self._pending = None
            self._need_full = False

    def rollback(self, seq: int) -> None:
        """The send for ``seq`` failed (transport error, no response).
        The master may or may not have applied it — and the next tick's
        scalars may differ from what was sent, so RE-ENCODING a delta
        for the same seq could diverge: a key that changed in the sent
        delta and reverted before the resend would be omitted (it again
        equals the acked base) while the master keeps the applied
        value. The only recovery that converges regardless of what the
        master saw is a snapshot: the next batch is FULL (same epoch,
        next seq — a full batch replaces decoder state
        unconditionally). Transport failures are rare; one full payload
        is cheap insurance against a silent divergence."""
        self._pending = None
        self._need_full = True

    def force_resync(self) -> None:
        """The master asked for a resync (it cannot reconstruct): next
        encode is a full snapshot under a fresh epoch, so stale
        in-flight deltas of the old stream can never interleave."""
        self._need_full = True
        self._epoch = _fresh_epoch()
        self._seq = 0
        self._acked = {}
        self._pending = None


class _NodeState:
    __slots__ = ("epoch", "seq", "procs")

    def __init__(self, epoch: int, seq: int):
        self.epoch = epoch
        self.seq = seq
        self.procs: Dict[int, ProcSnapshot] = {}


class DeltaDecoder:
    """Master-side reconstruction of per-node, per-proc scalar
    snapshots. Thread-safe (the servicer pool calls ``apply`` from
    many handler threads)."""

    def __init__(self):
        self._nodes: Dict[int, _NodeState] = {}
        self._lock = threading.Lock()
        self.resyncs = 0  # mismatches answered with resync
        self.replays = 0  # idempotent same-seq re-applies

    def apply(
        self,
        node_id: int,
        epoch: int,
        seq: int,
        full: bool,
        proc_deltas: Dict[int, ProcDeltaPayload],
    ) -> Optional[Dict[int, ProcSnapshot]]:
        """Apply one batch; returns the reconstructed FULL snapshots of
        every proc mentioned in ``proc_deltas`` (procs whose every key
        was removed reconstruct to ``{}``), or None when the decoder
        cannot reconstruct and the agent must resync."""
        with self._lock:
            st = self._nodes.get(node_id)
            if full:
                # a snapshot stands on its own: replace whatever we had
                st = _NodeState(epoch, seq)
                self._nodes[node_id] = st
                for p, (changed, _removed) in proc_deltas.items():
                    st.procs[int(p)] = dict(changed)
                return {
                    int(p): dict(st.procs[int(p)])
                    for p in proc_deltas
                }
            if st is None or st.epoch != epoch or seq > st.seq + 1 or (
                seq < st.seq
            ):
                self.resyncs += 1
                return None
            if seq == st.seq:
                self.replays += 1  # idempotent re-apply (lost response)
            st.seq = seq
            out: Dict[int, ProcSnapshot] = {}
            for p, (changed, removed) in proc_deltas.items():
                p = int(p)
                snap = st.procs.setdefault(p, {})
                snap.update(changed)
                for k in removed:
                    snap.pop(k, None)
                if not snap:
                    st.procs.pop(p, None)
                out[p] = dict(snap) if snap else {}
            return out

    def snapshot(self, node_id: int) -> Dict[int, ProcSnapshot]:
        """Current reconstruction for ``node_id`` (tests/diagnostics)."""
        with self._lock:
            st = self._nodes.get(node_id)
            if st is None:
                return {}
            return {p: dict(s) for p, s in st.procs.items()}

    def forget(self, node_id: int) -> None:
        """Drop a departed node's state (its next batch resyncs)."""
        with self._lock:
            self._nodes.pop(node_id, None)
