"""Cross-process IPC primitives: SharedLock / SharedQueue / SharedDict over
unix-domain sockets, and resource-tracker-free POSIX shared memory.

Parity: dlrover/python/common/multi_process.py:234,355,462,542. These are
the substrate of flash checkpoint: the training process and the agent
process exchange save events through a ``SharedQueue`` and hand gigabytes of
checkpoint bytes through ``SharedMemory`` segments that *survive the death
of the creating process* (Python's resource tracker would normally unlink
them — we unregister, like the reference does).

Design: every named primitive is hosted by the process that creates it with
``create=True`` (a daemon thread serves requests on a unix socket); any
process on the host attaches with ``create=False``. Requests are
length-prefixed pickled tuples ``(method, args)``.
"""

from __future__ import annotations

import os
import pickle
import queue
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional

from dlrover_tpu.common.log import default_logger as logger

SOCKET_DIR_ENV = "DLROVER_TPU_SOCKET_DIR"


def _socket_dir() -> str:
    # namespaced per job so two launchers on one host cannot clobber each
    # other's endpoints (the shm segments are namespaced the same way).
    # The env var overrides the BASE dir only — the job namespace always
    # applies (an as-is override once let a multi-node local cluster's
    # agents share un-namespaced endpoints and deadlock; chaos soak)
    job = os.getenv("DLROVER_TPU_JOB_NAME", "job")
    base = os.getenv(SOCKET_DIR_ENV, "/tmp/dlrover_tpu")
    d = os.path.join(base, job, "sockets")
    os.makedirs(d, exist_ok=True)
    return d


def _socket_path(name: str) -> str:
    return os.path.join(_socket_dir(), f"{name}.sock")


def server_exists(name: str) -> bool:
    """True when some process is *actually serving* the named IPC endpoint
    (a stale socket file left by a killed process probes as dead and is
    removed)."""
    path = _socket_path(name)
    if not os.path.exists(path):
        return False
    try:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(1.0)
            s.connect(path)
        return True
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass
        return False


def clear_sockets():
    d = _socket_dir()
    for f in os.listdir(d):
        if f.endswith(".sock"):
            try:
                os.unlink(os.path.join(d, f))
            except OSError:
                pass


def _send_msg(sock: socket.socket, obj: Any):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> Any:
    (length,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, length))


class LocalSocketComm:
    """Base for a named primitive shared between local processes."""

    def __init__(self, name: str, create: bool = False):
        self.name = name
        self._create = create
        self._path = _socket_path(name)
        self._server: Optional[socket.socket] = None
        self._stopped = False
        if create:
            self._start_server()

    # -- server side ---------------------------------------------------
    def _start_server(self):
        if os.path.exists(self._path):
            os.unlink(self._path)
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(self._path)
        self._server.listen(64)
        t = threading.Thread(
            target=self._serve, name=f"ipc-{self.name}", daemon=True
        )
        t.start()

    def _serve(self):
        while not self._stopped:
            try:
                conn, _ = self._server.accept()
            except OSError:
                break
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            ).start()

    def _handle_conn(self, conn: socket.socket):
        with conn:
            try:
                while True:
                    method, args = _recv_msg(conn)
                    try:
                        result = getattr(self, f"_do_{method}")(*args)
                        _send_msg(conn, (True, result))
                    except Exception as e:  # serve errors back to client
                        _send_msg(conn, (False, repr(e)))
            except (ConnectionError, EOFError):
                pass

    def close(self):
        self._stopped = True
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
            try:
                os.unlink(self._path)
            except OSError:
                pass

    # -- client side ---------------------------------------------------
    def _call(self, method: str, *args, timeout: float = 60.0):
        if self._create:
            # host process short-circuits straight to the implementation
            return getattr(self, f"_do_{method}")(*args)
        deadline = time.time() + timeout
        last_err: Optional[Exception] = None
        while time.time() < deadline:
            try:
                with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                    s.settimeout(max(1.0, deadline - time.time()))
                    s.connect(self._path)
                    _send_msg(s, (method, args))
                    ok, result = _recv_msg(s)
                if not ok:
                    raise RuntimeError(result)
                return result
            except (ConnectionError, FileNotFoundError, socket.timeout) as e:
                last_err = e
                time.sleep(0.1)
        raise TimeoutError(
            f"IPC call {self.name}.{method} failed: {last_err!r}"
        )


class SharedLock(LocalSocketComm):
    """Named lock usable across processes (parity: multi_process.py:234)."""

    def __init__(self, name: str, create: bool = False):
        self._lock = threading.Lock() if create else None
        self._owner: Optional[str] = None
        super().__init__(name, create)

    def _do_acquire(self, blocking: bool, owner: str) -> bool:
        got = self._lock.acquire(blocking=blocking, timeout=30 if blocking else -1)
        if got:
            self._owner = owner
        return got

    def _do_release(self, owner: str) -> bool:
        if self._owner == owner and self._lock.locked():
            self._owner = None
            self._lock.release()
            return True
        return False

    def _do_locked(self) -> bool:
        return self._lock.locked()

    def _do_force_release(self) -> bool:
        if self._lock.locked():
            self._owner = None
            self._lock.release()
            return True
        return False

    def acquire(self, blocking: bool = True) -> bool:
        return self._call("acquire", blocking, self._owner_id())

    def release(self) -> bool:
        return self._call("release", self._owner_id())

    def force_release(self) -> bool:
        """Release regardless of owner — for lock-handoff protocols where a
        different process (or a dead owner's supervisor) must unlock."""
        return self._call("force_release")

    def locked(self) -> bool:
        return self._call("locked")

    def _owner_id(self) -> str:
        return f"{os.getpid()}-{threading.get_ident()}"


class SharedQueue(LocalSocketComm):
    """Named FIFO queue across processes (parity: multi_process.py:355)."""

    def __init__(self, name: str, create: bool = False, maxsize: int = 0):
        self._queue: Optional[queue.Queue] = (
            queue.Queue(maxsize) if create else None
        )
        super().__init__(name, create)

    def _do_put(self, obj, timeout: float):
        self._queue.put(obj, timeout=timeout)

    def _do_get(self, timeout: float):
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return _EMPTY

    def _do_qsize(self) -> int:
        return self._queue.qsize()

    def _do_empty(self) -> bool:
        return self._queue.empty()

    def put(self, obj, timeout: float = 60.0):
        self._call("put", obj, timeout)

    def get(self, timeout: float = 60.0):
        result = self._call("get", timeout, timeout=timeout + 10)
        if isinstance(result, _Empty):
            raise queue.Empty
        return result

    def qsize(self) -> int:
        return self._call("qsize")

    def empty(self) -> bool:
        return self._call("empty")


class _Empty:
    """Sentinel marking an empty-queue response."""

    def __eq__(self, other):
        return isinstance(other, _Empty)


_EMPTY = _Empty()


class SharedDict(LocalSocketComm):
    """Named dict across processes (parity: multi_process.py:462)."""

    def __init__(self, name: str, create: bool = False):
        self._dict: Optional[Dict] = {} if create else None
        self._dict_lock = threading.Lock() if create else None
        super().__init__(name, create)

    def _do_set(self, key, value):
        with self._dict_lock:
            self._dict[key] = value

    def _do_update(self, other: Dict):
        with self._dict_lock:
            self._dict.update(other)

    def _do_get(self, key, default):
        with self._dict_lock:
            return self._dict.get(key, default)

    def _do_dict(self) -> Dict:
        with self._dict_lock:
            return dict(self._dict)

    def _do_pop(self, key, default):
        with self._dict_lock:
            return self._dict.pop(key, default)

    def set(self, key, value):
        self._call("set", key, value)

    def update(self, other: Dict):
        self._call("update", other)

    def get(self, key, default=None):
        return self._call("get", key, default)

    def pop(self, key, default=None):
        return self._call("pop", key, default)

    def as_dict(self) -> Dict:
        return self._call("dict")


# ---------------------------------------------------------------------------
# resource-tracker-free POSIX shared memory
# ---------------------------------------------------------------------------

from multiprocessing import resource_tracker, shared_memory  # noqa: E402


class SharedMemory(shared_memory.SharedMemory):
    """POSIX shm whose lifetime is *not* tied to the creating process.

    Parity: multi_process.py:542 — the reference re-implements
    ``SharedMemory`` so the resource tracker does not unlink the segment
    when the training process dies; the checkpoint bytes must outlive it so
    the agent can persist them ("save at breakpoint"). We create through the
    stdlib then immediately unregister from the tracker, and make
    ``unlink()`` explicit-only.
    """

    def __init__(self, name: str, create: bool = False, size: int = 0):
        super().__init__(name=name, create=create, size=size)
        try:
            resource_tracker.unregister(self._name, "shared_memory")
        except Exception:  # pragma: no cover - tracker internals vary
            pass

    def unlink(self):
        """Unlink explicitly; never called implicitly by GC."""
        try:
            shared_memory._posixshmem.shm_unlink(self._name)
        except FileNotFoundError:
            pass


def create_shared_memory(name: str, size: int) -> Optional[SharedMemory]:
    """Create (or recreate with the right size) a named shm segment."""
    try:
        shm = SharedMemory(name=name, create=True, size=size)
    except FileExistsError:
        shm = SharedMemory(name=name)
        if shm.size < size:
            shm.close()
            shm.unlink()
            shm = SharedMemory(name=name, create=True, size=size)
    except Exception as e:  # pragma: no cover
        logger.error(f"cannot create shm {name}: {e!r}")
        return None
    return shm


def attach_shared_memory(name: str) -> Optional[SharedMemory]:
    try:
        return SharedMemory(name=name)
    except FileNotFoundError:
        return None
