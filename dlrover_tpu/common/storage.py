"""Checkpoint storage abstraction + POSIX impl + deletion strategies.

Parity: dlrover/python/common/storage.py:23,127,202. The writer side stays
byte-oriented (the flash-ckpt saver hands us raw shm slices), so the same
interface backs POSIX disk, and later GCS via a fuse mount.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
from abc import ABC, abstractmethod
from typing import Any, Callable, List, Optional

from dlrover_tpu.common.log import default_logger as logger


_FSYNC_DIR_WARNED: set = set()


def fsync_dir(dirname: str) -> None:
    """fsync a directory, making the renames/creates inside it durable
    — a renamed file whose directory entry is still only in the page
    cache when the host dies rolls back to the previous generation.

    Best-effort: some filesystems reject directory fsync (EINVAL/
    ENOTSUP on 9p, vboxsf, object-store FUSE mounts). By the time this
    runs the rename has already committed, so failing the save here
    would turn a durability *upgrade* into a crash on mounts where the
    plain rename used to work — warn once per directory instead (the
    file's own fsync already happened, so real I/O errors surfaced
    there)."""
    dirname = dirname or "."
    try:
        dfd = os.open(dirname, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError as e:
        if dirname not in _FSYNC_DIR_WARNED:
            _FSYNC_DIR_WARNED.add(dirname)
            logger.warning(
                f"directory fsync unsupported on {dirname!r} ({e!r}): "
                "renames there are atomic but their durability rides "
                "on the filesystem's own metadata ordering"
            )


def durable_replace(path: str, write_fn: Callable, mode: str = "w") -> str:
    """Atomic AND durable publish: ``write_fn(f)`` writes the payload to
    a pid+thread-unique tmp file, which is flushed, fsynced, and
    ``os.replace``d onto ``path``. The rename being the commit point
    only helps if the bytes reached the platter first (the PR-11 /
    graftlint durable-rename class) — use this for anything a reader
    treats as committed state. Telemetry that only needs atomic reads
    can keep a plain unfsynced rename (suppressed in place where
    deliberate, cf. agent/monitor.py)."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # the file's bytes being durable means nothing if the rename's
        # directory entry isn't
        fsync_dir(os.path.dirname(path))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


class CheckpointDeletionStrategy(ABC):
    @abstractmethod
    def clean_up(self, step: int, delete_func):
        """Given a newly-committed step, remove stale checkpoint dirs."""


class KeepLatestStepStrategy(CheckpointDeletionStrategy):
    """Keep only the newest ``max_to_keep`` step dirs.

    Parity: storage.py KeepLatestStepStrategy.
    """

    def __init__(self, max_to_keep: int = 1, checkpoint_dir: str = ""):
        self._max_to_keep = max(1, max_to_keep)
        self._checkpoint_dir = checkpoint_dir
        self._steps: List[int] = []

    def clean_up(self, step: int, delete_func):
        if step in self._steps:
            return
        self._steps.append(step)
        self._steps.sort()
        while len(self._steps) > self._max_to_keep:
            stale = self._steps.pop(0)
            path = os.path.join(self._checkpoint_dir, str(stale))
            try:
                delete_func(path)
            except Exception as e:
                logger.warning(f"fail to clean ckpt {path}: {e!r}")


class KeepStepIntervalStrategy(CheckpointDeletionStrategy):
    """Keep steps that are multiples of ``keep_interval``; drop the rest.

    Parity: storage.py:202 KeepStepIntervalStrategy.
    """

    def __init__(self, keep_interval: int, checkpoint_dir: str = ""):
        self._keep_interval = max(1, keep_interval)
        self._checkpoint_dir = checkpoint_dir

    def clean_up(self, step: int, delete_func):
        if step % self._keep_interval == 0:
            return
        path = os.path.join(self._checkpoint_dir, str(step))
        try:
            delete_func(path)
        except Exception as e:
            logger.warning(f"fail to clean ckpt {path}: {e!r}")


class CheckpointStorage(ABC):
    """Byte/object storage seam used by the flash-checkpoint saver."""

    @abstractmethod
    def write(self, content: bytes | str, path: str):
        """Write ``content`` to ``path`` **atomically**: readers must never
        observe a partial file (the commit protocol publishes tracker files
        through this)."""
        ...

    @abstractmethod
    def write_state_dict(self, state_dict: Any, path: str):
        ...

    @abstractmethod
    def read(self, path: str) -> Optional[bytes]:
        ...

    @abstractmethod
    def read_state_dict(self, path: str) -> Any:
        ...

    @abstractmethod
    def safe_rmtree(self, dir_path: str):
        ...

    @abstractmethod
    def safe_remove(self, path: str):
        ...

    @abstractmethod
    def safe_makedirs(self, dir_path: str):
        ...

    @abstractmethod
    def exists(self, path: str) -> bool:
        ...

    @abstractmethod
    def listdir(self, path: str) -> List[str]:
        ...

    def rename(self, src: str, dst: str):
        """Atomic move within the store (quarantining corrupt step dirs).
        Backends without rename semantics may leave this unimplemented —
        callers fall back to deletion."""
        raise NotImplementedError

    def size(self, path: str) -> Optional[int]:
        """Byte length of ``path``; None when absent. Default reads the
        object — backends with cheap metadata should override (shallow
        checkpoint verification leans on this to avoid full reads)."""
        data = self.read(path)
        return None if data is None else len(data)

    def commit(self, step: int, success: bool):
        """Hook run after a step is fully persisted."""


class PosixDiskStorage(CheckpointStorage):
    """Local/NFS/FUSE-mounted filesystem storage (parity: storage.py:127).

    Writes are atomic: tmp file in the target dir + ``os.replace``.
    """

    def __init__(self, deletion_strategy: Optional[CheckpointDeletionStrategy] = None):
        self._deletion_strategy = deletion_strategy

    def write(self, content: bytes | str, path: str):
        mode = "wb" if isinstance(content, bytes) else "w"
        self.safe_makedirs(os.path.dirname(path))
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
        try:
            with os.fdopen(fd, mode) as f:
                f.write(content)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            fsync_dir(os.path.dirname(path))
        except Exception:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def write_state_dict(self, state_dict: Any, path: str):
        self.write(pickle.dumps(state_dict, protocol=pickle.HIGHEST_PROTOCOL), path)

    def read(self, path: str) -> Optional[bytes]:
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def read_state_dict(self, path: str) -> Any:
        data = self.read(path)
        return pickle.loads(data) if data is not None else None

    def safe_rmtree(self, dir_path: str):
        shutil.rmtree(dir_path, ignore_errors=True)

    def safe_remove(self, path: str):
        try:
            os.remove(path)
        except OSError:
            pass

    def safe_makedirs(self, dir_path: str):
        if dir_path:
            os.makedirs(dir_path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        try:
            return sorted(os.listdir(path))
        except OSError:
            return []

    def rename(self, src: str, dst: str):
        os.rename(src, dst)

    def size(self, path: str) -> Optional[int]:
        try:
            return os.path.getsize(path)
        except OSError:
            return None

    def commit(self, step: int, success: bool):
        if success and self._deletion_strategy is not None:
            self._deletion_strategy.clean_up(step, self.safe_rmtree)


def get_checkpoint_storage(
    deletion_strategy: Optional[CheckpointDeletionStrategy] = None,
) -> CheckpointStorage:
    return PosixDiskStorage(deletion_strategy)
