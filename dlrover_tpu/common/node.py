"""Node/job data model and the node-status state machine.

Parity: dlrover/python/common/node.py:37-358 (Node/NodeResource/
NodeGroupResource) and dlrover/python/master/node/status_flow.py:136
(allowed status transitions). Re-designed for TPU: a Node is one *host* of
a TPU slice; ``group`` identifies the slice (all hosts of a slice restart
together), ``tpu_chips`` replaces GPU counts.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
    NodeType,
)

# Allowed transitions of the node status state machine. Anything not listed
# is an invalid transition and is ignored by the job manager.
_STATUS_FLOW = {
    NodeStatus.INITIAL: {
        NodeStatus.PENDING,
        NodeStatus.RUNNING,
        NodeStatus.FAILED,
        NodeStatus.DELETED,
    },
    NodeStatus.PENDING: {
        NodeStatus.RUNNING,
        NodeStatus.SUCCEEDED,
        NodeStatus.FAILED,
        NodeStatus.DELETED,
        NodeStatus.BREAKDOWN,
    },
    NodeStatus.RUNNING: {
        NodeStatus.SUCCEEDED,
        NodeStatus.FAILED,
        NodeStatus.DELETED,
        NodeStatus.BREAKDOWN,
    },
    NodeStatus.SUCCEEDED: {NodeStatus.DELETED},
    NodeStatus.FAILED: {NodeStatus.DELETED, NodeStatus.PENDING},
    NodeStatus.BREAKDOWN: {NodeStatus.DELETED},
    NodeStatus.DELETED: set(),
}


def is_allowed_transition(frm: str, to: str) -> bool:
    if frm == to:
        return False
    return to in _STATUS_FLOW.get(frm, set())


@dataclass
class NodeResource:
    """Resources of one TPU host.

    ``tpu_chips`` = local accelerator chips (e.g. 4 on v5p hosts);
    ``tpu_topology`` = slice topology string (e.g. "2x2x2") when known.
    """

    cpu: float = 0.0
    memory_mb: int = 0
    tpu_chips: int = 0
    tpu_type: str = ""
    tpu_topology: str = ""

    def to_dict(self) -> Dict:
        return {
            "cpu": self.cpu,
            "memory_mb": self.memory_mb,
            "tpu_chips": self.tpu_chips,
            "tpu_type": self.tpu_type,
            "tpu_topology": self.tpu_topology,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "NodeResource":
        return cls(**d)


@dataclass
class NodeGroupResource:
    """Resource spec for a group of identical nodes (one replica type)."""

    count: int = 0
    node_resource: NodeResource = field(default_factory=NodeResource)

    def update(self, count: Optional[int] = None, resource: Optional[NodeResource] = None):
        if count is not None and count >= 0:
            self.count = count
        if resource is not None:
            self.node_resource = resource


class Node:
    """One schedulable host in the job.

    State machine + relaunch bookkeeping. The master's job manager owns the
    authoritative instance; agents refer to nodes by (type, id).
    """

    def __init__(
        self,
        node_type: str = NodeType.WORKER,
        node_id: int = 0,
        rank_index: Optional[int] = None,
        name: str = "",
        status: str = NodeStatus.INITIAL,
        config_resource: Optional[NodeResource] = None,
        max_relaunch_count: int = 3,
        group: int = 0,
        group_size: int = 1,
    ):
        self.type = node_type
        self.id = node_id
        self.rank_index = rank_index if rank_index is not None else node_id
        self.name = name or f"{node_type}-{node_id}"
        self.status = status
        self.config_resource = config_resource or NodeResource()
        self.used_resource = NodeResource()
        self.max_relaunch_count = max_relaunch_count
        self.relaunch_count = 0
        self.relaunchable = True
        self.is_released = False
        self.exit_reason: str = ""
        self.group = group
        self.group_size = group_size
        self.create_time: float = time.time()
        # physical host identity (k8s spec.nodeName / VM hostname) — set
        # by watchers/agents; "" when the platform doesn't expose it.
        # Cluster-level bad-node detection keys on THIS, never on the
        # per-job logical name (every job has a "worker-0")
        self.hostname: str = ""
        self.start_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.heartbeat_time: float = 0.0
        self.start_hang_time: float = 0.0
        self.paral_config: Dict = {}
        self.reported_status: str = ""
        self.restart_training = False
        # an eviction notice arrived for this node: its coming death is
        # a SCHEDULED departure (no relaunch budget burned, booked as
        # `eviction`, host excluded from the next rendezvous)
        self.evicting = False

    # ------------------------------------------------------------------
    # state machine
    # ------------------------------------------------------------------
    def update_status(self, status: str) -> bool:
        """Apply a status transition; returns True if it was legal."""
        if not is_allowed_transition(self.status, status):
            return False
        self.status = status
        now = time.time()
        if status == NodeStatus.RUNNING and self.start_time is None:
            self.start_time = now
        if status in (
            NodeStatus.SUCCEEDED,
            NodeStatus.FAILED,
            NodeStatus.BREAKDOWN,
            NodeStatus.DELETED,
        ):
            self.finish_time = now
        return True

    def update_node_check_result(self, result: str):
        self.reported_status = result

    def is_unrecoverable_failure(self) -> bool:
        """Failures that must not be relaunched.

        Parity: exitcode policy in dlrover/python/elastic_agent/torch/
        training.py:354-357 — fatal user-code errors don't get new pods.
        """
        if self.relaunch_count >= self.max_relaunch_count:
            return True
        return self.exit_reason == NodeExitReason.FATAL_ERROR

    def inc_relaunch_count(self):
        self.relaunch_count += 1

    def get_relaunch_node_info(self, new_id: int) -> "Node":
        """Clone this node as its relaunch replacement."""
        new_node = Node(
            node_type=self.type,
            node_id=new_id,
            rank_index=self.rank_index,
            status=NodeStatus.INITIAL,
            config_resource=copy.deepcopy(self.config_resource),
            max_relaunch_count=self.max_relaunch_count,
            group=self.group,
            group_size=self.group_size,
        )
        new_node.relaunch_count = self.relaunch_count + 1
        return new_node

    def timeout(self, timeout_secs: float) -> bool:
        return (
            self.heartbeat_time > 0
            and time.time() - self.heartbeat_time > timeout_secs
        )

    def __repr__(self):  # pragma: no cover - debugging aid
        return (
            f"Node({self.type}-{self.id} rank={self.rank_index} "
            f"status={self.status} relaunch={self.relaunch_count})"
        )
