"""Polling-daemon base shared by master and agent background loops
(auto-scaler, resource/training monitors, config tuner)."""

from __future__ import annotations

import threading
from typing import Optional

from dlrover_tpu.common.log import default_logger as logger


class PollingDaemon:
    """A named polling thread with clean start/stop; subclasses implement
    ``_tick``. Exceptions in a tick are logged and do not kill the loop."""

    def __init__(self, name: str, interval: float):
        self._name = name
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=self._name, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stopped.wait(self._interval):
            try:
                self._tick()
            except Exception as e:
                logger.warning(f"{self._name} tick failed: {e!r}")

    def _tick(self):
        raise NotImplementedError


class WatchingDaemon(PollingDaemon):
    """A PollingDaemon that degrades its poll to a slow resync when an
    event stream is available: subclasses implement ``_watch_stream()``
    (returning an iterator of events, or None when the backend cannot
    stream) and ``_tick()``. Each event wakes the loop immediately.

    A stream that ends instantly without delivering anything (a server
    that accepted the connection but rejects watches) is retried with
    backoff and, after a few consecutive duds, abandoned — the daemon
    then polls at its normal interval instead of believing a watch that
    never fires."""

    _MAX_DUD_STREAMS = 3

    def __init__(self, name: str, interval: float, resync: float = 60.0):
        super().__init__(name, interval)
        self._resync = resync
        self._wake = threading.Event()
        self._watch_ok = False

    def _watch_stream(self):  # pragma: no cover - interface
        return None

    def start(self):
        super().start()
        threading.Thread(
            target=self._consume_watch,
            daemon=True,
            name=f"{self._name}-watch",
        ).start()

    def stop(self):
        self._stopped.set()
        self._wake.set()  # unblock a loop parked in its resync wait
        super().stop()

    def _consume_watch(self):
        import time as _time

        duds = 0
        while not self._stopped.is_set():
            try:
                stream = self._watch_stream()
            except Exception as e:
                # transient backend failure: keep polling responsive and
                # RETRY — a daemon that quietly stops watching while
                # claiming _watch_ok would slow itself to resync cadence
                logger.warning(f"{self._name} watch failed: {e!r}")
                self._watch_ok = False
                self._stopped.wait(10.0)
                continue
            if stream is None:
                return  # backend cannot stream: stay pure-polling
            t0 = _time.time()
            delivered = 0
            for _event in stream:
                if self._stopped.is_set():
                    return
                delivered += 1
                self._watch_ok = True
                self._wake.set()
            if delivered == 0 and _time.time() - t0 < 1.0:
                duds += 1
                self._watch_ok = False
                if duds >= self._MAX_DUD_STREAMS:
                    # long cool-off, then try again — the API server may
                    # just be restarting; never abandon forever
                    logger.warning(
                        f"{self._name}: watch streams end instantly "
                        f"({duds}x); polling, retrying watch in 60s"
                    )
                    duds = 0
                    self._stopped.wait(60.0)
                else:
                    self._stopped.wait(min(2.0**duds, 10.0))
            else:
                duds = 0
            # stream closed (server-side watch timeout) -> re-watch

    def _loop(self):
        # first tick at startup so pre-existing state reconciles
        # immediately; then event-driven with a slow resync backstop
        while not self._stopped.is_set():
            try:
                self._tick()
            except Exception as e:
                logger.warning(f"{self._name} tick failed: {e!r}")
            self._wake.wait(
                timeout=self._resync if self._watch_ok else self._interval
            )
            self._wake.clear()
