"""Polling-daemon base shared by master and agent background loops
(auto-scaler, resource/training monitors, config tuner)."""

from __future__ import annotations

import threading
from typing import Optional

from dlrover_tpu.common.log import default_logger as logger


class PollingDaemon:
    """A named polling thread with clean start/stop; subclasses implement
    ``_tick``. Exceptions in a tick are logged and do not kill the loop."""

    def __init__(self, name: str, interval: float):
        self._name = name
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=self._name, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stopped.wait(self._interval):
            try:
                self._tick()
            except Exception as e:
                logger.warning(f"{self._name} tick failed: {e!r}")

    def _tick(self):
        raise NotImplementedError
