"""Typed message catalog + codec for master<->agent RPC.

Parity: dlrover/python/common/grpc.py:30-445 — the reference carries pickled
dataclasses through a 2-RPC proto (``report``/``get``). We keep that minimal
protocol surface (it makes rolling upgrades trivial) but harden the codec:
messages are dataclasses registered in a catalog, and deserialization uses a
restricted unpickler that only resolves classes from this module.

TPU deltas vs the reference catalog:
- ``CommWorld`` carries the JAX-distributed coordinator address (our analog
  of the torch rendezvous store endpoints) plus the slice/node-unit layout;
- resource stats describe TPU hosts (chips, HBM) instead of GPUs.
"""

from __future__ import annotations

import io
import pickle
import socket
from contextlib import closing
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def find_free_port(host: str = "127.0.0.1") -> int:
    with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.bind((host, 0))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return s.getsockname()[1]


def addr_connected(addr: str, timeout: float = 1.0) -> bool:
    try:
        host, port = addr.rsplit(":", 1)
        with closing(socket.create_connection((host, int(port)), timeout)):
            return True
    except OSError:
        return False


class Message:
    """Base class; every RPC payload subclasses this."""


# ---------------------------------------------------------------------------
# codec — restricted pickle
# ---------------------------------------------------------------------------

_SAFE_MODULES = ("dlrover_tpu.common.comm", "builtins", "collections")


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if module.startswith("dlrover_tpu.common.comm") or module in _SAFE_MODULES:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"forbidden class in message: {module}.{name}"
        )


def serialize_message(msg) -> bytes:
    return pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_message(data: bytes):
    if not data:
        return None
    return _RestrictedUnpickler(io.BytesIO(data)).load()


# ---------------------------------------------------------------------------
# envelopes
# ---------------------------------------------------------------------------


@dataclass
class BaseRequest(Message):
    node_id: int = -1
    node_type: str = ""
    data: bytes = b""


@dataclass
class BaseResponse(Message):
    success: bool = True
    message: str = ""
    data: bytes = b""


# ---------------------------------------------------------------------------
# task / data sharding messages (parity: grpc.py Task/TaskRequest/ShardConfig)
# ---------------------------------------------------------------------------


@dataclass
class Shard(Message):
    name: str = ""
    start: int = 0
    end: int = 0
    record_indices: List[int] = field(default_factory=list)


@dataclass
class Task(Message):
    task_id: int = -1
    task_type: str = ""
    shard: Shard = field(default_factory=Shard)

    @property
    def is_empty(self) -> bool:
        return self.task_id < 0


@dataclass
class TaskRequest(Message):
    dataset_name: str = ""


@dataclass
class TaskResult(Message):
    dataset_name: str = ""
    task_id: int = -1


@dataclass
class DatasetShardParams(Message):
    batch_size: int = 0
    num_epochs: int = 1
    dataset_size: int = 0
    shuffle: bool = False
    num_minibatches_per_shard: int = 2
    dataset_name: str = ""
    task_type: str = ""
    storage_type: str = "text"


@dataclass
class StreamingDataReport(Message):
    """Producer → master: advance a streaming dataset's watermark or
    close the stream (parity: the message-queue offsets feeding the
    reference's StreamingDatasetSplitter, dataset_splitter.py:359)."""

    dataset_name: str = ""
    new_records: int = 0
    end: bool = False


@dataclass
class ShardCheckpointRequest(Message):
    dataset_name: str = ""


@dataclass
class ShardCheckpoint(Message):
    content: str = ""


@dataclass
class DatasetEpochRequest(Message):
    dataset_name: str = ""


@dataclass
class DatasetEpoch(Message):
    epoch: int = 0


# ---------------------------------------------------------------------------
# rendezvous messages (parity: grpc.py JoinRendezvousRequest/CommWorld etc.)
# ---------------------------------------------------------------------------


@dataclass
class JoinRendezvousRequest(Message):
    node_id: int = 0
    node_rank: int = 0
    local_world_size: int = 1
    rdzv_name: str = ""
    node_unit: int = 1
    node_group: int = -1


@dataclass
class RendezvousParamsReport(Message):
    """Agent -> master: configure a rendezvous (parity: the rdzv params the
    MasterRendezvousHandler reports at construction, training.py:732)."""

    rdzv_name: str = ""
    min_nodes: int = 1
    max_nodes: int = 1
    waiting_timeout: float = 30.0
    node_unit: int = 1


@dataclass
class WaitingNodeNumRequest(Message):
    node_id: int = 0
    local_world_size: int = 1
    rdzv_name: str = ""


@dataclass
class WaitingNodeNum(Message):
    waiting_num: int = 0


@dataclass
class CommWorldRequest(Message):
    node_id: int = 0
    rdzv_name: str = ""


@dataclass
class CommWorld(Message):
    rdzv_name: str = ""
    round: int = 0
    group: int = 0
    # node_rank -> local_world_size for every participant of this round
    world: Dict[int, int] = field(default_factory=dict)
    # JAX bootstrap: coordinator address chosen by master (host:port of the
    # lowest-rank node in the world) — the TPU analog of the torch rdzv store.
    coordinator_addr: str = ""


@dataclass
class NetworkReadyRequest(Message):
    node_id: int = 0


@dataclass
class NetworkCheckResultRequest(Message):
    node_id: int = 0
    elapsed_time: float = 0.0
    succeeded: bool = True


@dataclass
class NetworkCheckStatus(Message):
    nodes: List[int] = field(default_factory=list)
    reason: str = ""


@dataclass
class StragglerExistRequest(Message):
    node_id: int = 0


# ---------------------------------------------------------------------------
# node / job lifecycle messages
# ---------------------------------------------------------------------------


@dataclass
class NodeMeta(Message):
    node_type: str = ""
    node_id: int = 0
    rank_index: int = 0
    addr: str = ""
    cpu: float = 0.0
    memory_mb: int = 0
    tpu_chips: int = 0
    tpu_type: str = ""


@dataclass
class NodeEventReport(Message):
    event_type: str = ""
    node_type: str = ""
    node_id: int = 0
    status: str = ""
    exit_reason: str = ""
    message: str = ""


@dataclass
class NodeFailureReport(Message):
    node_id: int = 0
    node_rank: int = 0
    error_data: str = ""
    level: str = ""
    restart_count: int = 0


@dataclass
class EvictionNotice(Message):
    """A worker received an eviction/preemption notice and is draining
    (SIGTERM, ``DLROVER_TPU_EVICTION_DEADLINE_S``, master ``evict``
    command). The master treats this as a SCHEDULED departure: exclude
    the doomed rank from rendezvous, pre-arm the warm resize, relaunch
    without burning relaunch budget. Re-reported after the drain with
    ``drain_ms`` set — the measured drain latency the Brain's dwell
    gate prices (idempotent: the second report updates the event)."""

    node_id: int = 0
    grace_s: float = 0.0
    drain_ms: float = 0.0
    reason: str = ""


@dataclass
class HeartbeatReport(Message):
    node_id: int = 0
    timestamp: float = 0.0


@dataclass
class HeartbeatResponse(Message):
    action: str = ""  # "" | "restart" | "stop"


@dataclass
class ResourceStats(Message):
    node_id: int = 0
    cpu_percent: float = 0.0
    used_memory_mb: int = 0
    tpu_duty_cycle: float = 0.0
    tpu_hbm_used_mb: int = 0


@dataclass
class GlobalStepReport(Message):
    node_id: int = 0
    step: int = 0
    timestamp: float = 0.0


@dataclass
class JobMetricsSample(Message):
    """One point of the job metric series (parity: the stats the
    reference's JobMetricCollector hands its reporter)."""

    timestamp: float = 0.0
    global_step: int = 0
    steps_per_sec: float = 0.0
    alive_nodes: int = 0
    total_cpu_percent: float = 0.0
    total_memory_mb: int = 0
    # fleet goodput (obs/goodput.py ledger, aggregated per worker by
    # TelemetryAggregator): the %-of-wall-time-productive number the
    # Brain's allocation objective plans against. 0.0 = not reported.
    goodput_pct: float = 0.0


@dataclass
class JobMetricsRequest(Message):
    last_n: int = 0  # 0 = whole retained series


# -- Brain service (cluster-level optimizer) --------------------------------
@dataclass
class BrainMetricsReport(Message):
    """persist_metrics (parity: brain.proto:196)."""

    job_name: str = ""
    sample: JobMetricsSample = field(default_factory=JobMetricsSample)


@dataclass
class BrainOptimizeRequest(Message):
    job_name: str = ""
    node_unit: int = 1


@dataclass
class BrainOptimizePlan(Message):
    worker_count: int = 0  # 0 = no recommendation
    worker_memory_mb: int = 0
    reason: str = ""
    # hostnames the scheduler should avoid (cluster-level bad-node /
    # hot-node detection, parity: hot-PS exclusion in optalgorithm/)
    exclude_nodes: List[str] = field(default_factory=list)


@dataclass
class BrainJobEndReport(Message):
    """Terminal summary of a job — the rows cross-job cold-start
    resourcing fits from (parity: the reference Brain's job_metrics
    table keyed by ExitReason, optimize_job_worker_create_resource.go)."""

    job_name: str = ""
    exit_reason: str = "completed"  # completed | failed | oom
    worker_count: int = 0
    worker_memory_mb: int = 0


@dataclass
class BrainNodeEventReport(Message):
    """One node-level incident (oom/failed/hot) with its host — feeds
    OOM-adjust and cluster-level bad-node detection."""

    job_name: str = ""
    node_id: int = 0
    hostname: str = ""
    event: str = ""  # oom | failed | hot | eviction | ...
    memory_mb: int = 0
    cpu_percent: float = 0.0
    # free-form context ("grace=30.0s drain_ms=412"): eviction events
    # carry the measured drain latency the Brain dwell gate parses
    detail: str = ""


@dataclass
class BrainJobMetricsRequest(Message):
    job_name: str = ""
    last_n: int = 0


# -- Brain cluster scheduler (closed-loop multi-job allocation) -------------
@dataclass
class ClusterScalePlanRequest(Message):
    """Master → Brain poll for this job's slice of the cluster plan.

    ``ack_version`` is the highest plan version the master has durably
    EXECUTED: the Brain marks versions up to it acked and redelivers
    anything newer still pending — the PR-7 worker-command
    redeliver-until-acked pattern, so a lost response re-executes an
    idempotent ``scale_to`` instead of silently dropping the plan."""

    job_name: str = ""
    ack_version: int = 0


@dataclass
class ClusterScalePlanSlice(Message):
    """One job's slice of a versioned cluster plan. ``version == 0``
    means "no pending plan". ``sig`` is the scheduler's sign-off
    (crc32 over the version/job/count/ts tuple) — executors verify it
    before acting so a corrupted or spoofed row cannot resize a job."""

    version: int = 0
    job_name: str = ""
    worker_count: int = 0
    prev_count: int = 0
    reason: str = ""
    # cluster-level bad-node exclusion riding the plan (the scheduler's
    # bad_node_exclusion verdict at emission time)
    exclude_hosts: List[str] = field(default_factory=list)
    issued_ts: float = 0.0
    sig: int = 0


@dataclass
class PlanOutcomeReport(Message):
    """Master → Brain realized-outcome feedback for an executed plan
    slice: decision→resized latency plus the goodput the job actually
    ran at afterwards — the row that lets the scheduler's next pass see
    the result of its last one. Recording it is the plan's sign-off
    (status → acked)."""

    job_name: str = ""
    version: int = 0
    worker_count: int = 0
    decision_to_resized_ms: float = 0.0
    resized_to_training_ms: float = 0.0
    realized_goodput_pct: float = 0.0


@dataclass
class JobMetrics(Message):
    samples: List[JobMetricsSample] = field(default_factory=list)


@dataclass
class TrainMetricsReport(Message):
    """Periodic scalar training metrics (loss / eval_loss / lr / ...)
    from a worker to the master's collector — the AtorchTrainer
    metric-logging hook's master leg (ref atorch_trainer.py:127).

    ``open_span`` / ``open_span_elapsed_s`` carry the worker's current
    open trace span (obs/trace.SpanHeartbeat via the runtime-metrics
    file): the hang-attribution channel that lets the master say
    "worker 3 stuck in ckpt_commit for 42s" instead of "no step
    progress". Empty string = nothing open at last report."""

    node_id: int = 0
    step: int = 0
    metrics: Dict[str, float] = field(default_factory=dict)
    open_span: str = ""
    open_span_elapsed_s: float = 0.0


@dataclass
class TrainingStatusReport(Message):
    node_id: int = 0
    status: int = 0  # TrainingLoopStatus
    timestamp: float = 0.0


@dataclass
class NodeAddressRequest(Message):
    node_type: str = ""


@dataclass
class NodeAddresses(Message):
    # rank_index -> addr
    addrs: Dict[int, str] = field(default_factory=dict)


@dataclass
class ClusterVersionRequest(Message):
    node_type: str = ""
    node_id: int = 0
    version_type: str = "global"


@dataclass
class ClusterVersion(Message):
    version: int = 0


@dataclass
class UpdateClusterVersionRequest(Message):
    node_type: str = ""
    node_id: int = 0
    version_type: str = "global"
    version: int = 0


# ---------------------------------------------------------------------------
# kv store (rendezvous store backing; parity: grpc.py KeyValuePair)
# ---------------------------------------------------------------------------


@dataclass
class KeyValuePair(Message):
    key: str = ""
    value: bytes = b""


@dataclass
class KeyValueQuery(Message):
    key: str = ""


@dataclass
class KeyValueAdd(Message):
    key: str = ""
    amount: int = 0


@dataclass
class KeyValueWait(Message):
    keys: List[str] = field(default_factory=list)
    timeout: float = 60.0


# ---------------------------------------------------------------------------
# sync / barrier service (parity: sync_service.py messages)
# ---------------------------------------------------------------------------


@dataclass
class SyncJoinRequest(Message):
    sync_name: str = ""
    node_id: int = 0
    node_type: str = ""


@dataclass
class SyncFinishRequest(Message):
    sync_name: str = ""


@dataclass
class SyncResult(Message):
    done: bool = False


@dataclass
class BarrierRequest(Message):
    barrier_name: str = ""
    notify: bool = False


# ---------------------------------------------------------------------------
# auto-paral config (parity: grpc.py ParallelConfig family)
# ---------------------------------------------------------------------------


@dataclass
class DataLoaderConfig(Message):
    dataloader_name: str = ""
    batch_size: int = 0
    num_workers: int = 0
    version: int = 0


@dataclass
class OptimizerConfig(Message):
    optimizer_name: str = ""
    learning_rate: float = 0.0
    # multiply the LR by this when the master retunes the batch size
    # (linear-scaling rule)
    batch_size_factor: float = 1.0
    version: int = 0


@dataclass
class ParallelConfig(Message):
    dataloader: DataLoaderConfig = field(default_factory=DataLoaderConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    # TPU strategy knobs the master can retune at runtime:
    mesh_shape: Dict[str, int] = field(default_factory=dict)
    remat_policy: str = ""
    restart: bool = False
    # the auto-scaler's top-k predicted next worker counts, most likely
    # first — workers pre-lower the train step for these meshes in the
    # background (the speculative leg of the elastic-resize fast path)
    candidate_worker_counts: List[int] = field(default_factory=list)


@dataclass
class ParallelConfigRequest(Message):
    node_id: int = 0


@dataclass
class CheckpointReadyRequest(Message):
    """Agent tells master the latest in-memory checkpoint step per node."""

    node_id: int = 0
    step: int = 0


@dataclass
class ElasticRunConfigRequest(Message):
    pass


@dataclass
class ElasticRunConfig(Message):
    configs: Dict[str, str] = field(default_factory=dict)


@dataclass
class ScaleRequest(Message):
    """Ask master to scale node group(s) — used by tests/tools."""

    node_type: str = ""
    count: int = 0


# -- master -> worker command channel (forensics / profiling) ---------------
@dataclass
class WorkerCommand(Message):
    """One master-issued command for a specific worker. Kinds:

    - ``flight_dump`` — dump a flight-recorder bundle now;
    - ``profile`` — capture ``arg`` train steps with jax.profiler;
    - ``evict`` — enter the graceful-drain state machine with a grace
      window of ``arg`` seconds (0 = the trainer's configured default):
      finish the in-flight step, emergency shm checkpoint, flush
      forensics, exit clean.

    Commands ride the existing pull architecture: the agent polls them
    off the master (``WorkerCommandRequest``) and relays them to the
    training process through a JSON file (the paral-config pattern) —
    the master never needs a connection INTO a worker."""

    id: int = 0  # master-assigned, monotonic per worker
    kind: str = ""
    arg: int = 0
    reason: str = ""


@dataclass
class WorkerCommandRequest(Message):
    node_id: int = -1  # -1 = the requesting node (BaseRequest.node_id)
    # highest command id the agent has durably relayed: the master
    # clears only acked commands, so a lost RESPONSE redelivers
    # instead of dropping (the pop itself must not be the ack — the
    # poll is a read with a side effect otherwise)
    ack_id: int = 0


@dataclass
class WorkerCommands(Message):
    commands: List[WorkerCommand] = field(default_factory=list)


# -- hierarchical control plane (agent aggregation tier) ---------------------
@dataclass
class ProcDelta(Message):
    """One training process's slice of an ``AgentReportBatch``.

    ``changed``/``removed`` are the delta-encoded scalar telemetry
    (``common/telemetry_delta.py``): only keys whose value changed
    since the last batch the master ACKED, plus keys that disappeared.
    ``step_advanced`` gates the SpeedMonitor leg exactly the way the
    legacy ``TrainingMonitor`` gated ``report_global_step`` — ``step``
    itself always carries the current step for metric attribution."""

    proc_id: int = 0
    # global worker id for telemetry/collector attribution; -1 = use
    # the batch's node_id (the single-proc-per-node common case)
    worker_id: int = -1
    step: int = -1  # -1 = no step known yet
    step_ts: float = 0.0
    step_advanced: bool = False
    changed: Dict[str, float] = field(default_factory=dict)
    removed: List[str] = field(default_factory=list)
    open_span: str = ""
    open_span_elapsed_s: float = 0.0


@dataclass
class AgentReportBatch(Message):
    """One node's whole control-plane tick in a single RPC: the agent
    aggregation tier coalesces every per-process runtime-metrics /
    global-step / telemetry report into this message, delta-encoded
    against the last acked snapshot, and piggybacks the poll legs
    (worker commands, paral config) on the same round trip — steady
    state is ~1 RPC per node per tick instead of one per process per
    channel.

    ``epoch``/``seq``/``full`` are the delta protocol
    (``common/telemetry_delta.py``): the master reconstructs full
    scalars from its per-node snapshot and answers ``resync=True``
    when it cannot (restart, gap) — the next batch is then a full
    snapshot under a fresh epoch. No scalar is ever dropped."""

    node_id: int = 0
    epoch: int = 0
    seq: int = 0
    full: bool = False
    procs: List[ProcDelta] = field(default_factory=list)
    # piggybacked command-poll leg (WorkerCommandRequest semantics:
    # ack clears, the rest redelivers)
    command_ack_id: int = 0
    # piggybacked paral-config poll leg: the dataloader version the
    # agent last wrote (-1 = none yet). The response carries the
    # config only when the agent's copy is stale.
    paral_version: int = -1
    # piggybacked resource leg (the ResourceMonitor channel)
    resource: Optional[ResourceStats] = None


@dataclass
class AgentBatchResponse(Message):
    """Master's answer to an ``AgentReportBatch``: the batched poll
    legs ride back on the same round trip. ``resync=True`` means the
    delta could not be applied (nothing was) — the agent must re-send
    a full snapshot."""

    resync: bool = False
    commands: List[WorkerCommand] = field(default_factory=list)
    # only set when the agent's paral_version is stale
    paral_config: Optional[ParallelConfig] = None
