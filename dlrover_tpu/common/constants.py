"""Constant catalogs for dlrover-tpu.

Parity: dlrover/python/common/constants.py:291-file (NodeType/NodeStatus/
JobExitReason/TrainingExceptionLevel catalogs), restated for a TPU stack:
the schedulable unit is a *host* of a TPU slice, and a "node group" is a
slice (all hosts of a slice fail and restart together — the reference's
node-unit concept, rdzv_manager.py:129).
"""


class PlatformType:
    LOCAL = "local"
    KUBERNETES = "k8s"


class NodeType:
    MASTER = "master"
    WORKER = "worker"
    # TF-PS parity types (sparse/elastic-PS layer):
    PS = "ps"
    CHIEF = "chief"
    EVALUATOR = "evaluator"


class NodeStatus:
    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    BREAKDOWN = "breakdown"  # hardware fault detected by health check


class TaskType:
    TRAIN = "train"
    EVAL = "eval"
    # streaming: no shard ready yet, worker should retry (not exhausted)
    WAIT = "wait"


class NodeEventType:
    ADDED = "added"
    MODIFIED = "modified"
    DELETED = "deleted"


class NodeExitReason:
    SUCCEEDED = "succeeded"
    KILLED = "killed"
    OOM = "oom"
    FATAL_ERROR = "fatal_error"
    HARDWARE_ERROR = "hardware_error"
    UNKNOWN_ERROR = "unknown_error"
    RELAUNCHED = "relaunched"
    # deliberately removed by a scale-down; the rank may come back later
    # with a fresh relaunch budget
    SCALED_DOWN = "scaled_down"
    # evicted by the platform (spot/preemptible reclaim): a SCHEDULED
    # departure — the replacement does not burn relaunch budget, the
    # gap is booked to the `eviction` goodput category, and the Brain
    # prices the job's floor/dwell accordingly
    PREEMPTED = "preempted"
    # convicted of silent data corruption by the paired-device audit
    # vote (parallel/sdc.py): the chip computes wrong-but-finite
    # numbers, so it must NEVER rejoin — permanent rendezvous
    # quarantine until hardware replacement, and the scheduler treats
    # the host as absent capacity
    SDC_QUARANTINED = "sdc_quarantined"


class JobExitReason:
    SUCCEEDED = "succeeded"
    CODE_ERROR = "code_error"
    WORKER_OOM = "worker_oom"
    WORKER_ERROR = "worker_error"
    HANG_ERROR = "hang_error"
    RDZV_TIMEOUT_ERROR = "rdzv_timeout_error"
    PENDING_TIMEOUT = "pending_timeout"
    UNKNOWN_ERROR = "unknown_error"


class TrainingExceptionLevel:
    PROCESS_ERROR = "process_error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    WARNING = "warning"
    INFO = "info"


class RendezvousName:
    ELASTIC_TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class NetworkFailureReason:
    NO_INIT = "not_initialized"
    NODE_FAILURE = "node_failure"
    WAITING_NODE = "waiting_node"


class TrainingLoopStatus:
    START = 1
    END = 2
    PENDING = 3


class JobStage:
    """Lifecycle stage of the whole job on the master."""

    INIT = "init"
    RUNNING = "running"
    SUSPENDED = "suspended"
    STOPPING = "stopping"
    STOPPED = "stopped"


class CheckpointConstant:
    MODEL_STATES_NAME = "model_states"
    TRAIN_STATE_NAME = "train_state"
    TRACKER_FILE = "latest_step"
    SAVE_TIMEOUT = 600


class ConfigPath:
    """Runtime paral-config plumbing (master -> agent -> dataloader).

    Parity: dlrover/python/common/constants.py ConfigPath + the paral-config
    file loop (elastic_agent/config/paral_config_tuner.py:30).
    """

    ENV_PARAL_CONFIG = "DLROVER_TPU_PARAL_CONFIG_PATH"
    PARAL_CONFIG = "/tmp/dlrover_tpu/auto_paral_config.json"
    ENV_RUNTIME_METRICS = "DLROVER_TPU_RUNTIME_METRICS_PATH"
    RUNTIME_METRICS = "/tmp/dlrover_tpu/runtime_metrics.json"
    # master->worker command relay (flight dumps / profiler captures):
    # the agent's WorkerCommandRelay polls the master and mirrors
    # pending commands here; the trainer polls the file at log cadence
    ENV_WORKER_COMMANDS = "DLROVER_TPU_WORKER_COMMANDS_PATH"
    WORKER_COMMANDS = "/tmp/dlrover_tpu/worker_commands.json"


class NodeEnv:
    """Env vars the agent exports into training processes."""

    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    # JAX distributed bootstrap (the TPU analog of MASTER_ADDR/PORT +
    # NCCL rendezvous): our master owns coordinator assignment.
    COORDINATOR_ADDR = "DLROVER_TPU_COORDINATOR_ADDR"
    PROCESS_ID = "DLROVER_TPU_PROCESS_ID"
    NUM_PROCESSES = "DLROVER_TPU_NUM_PROCESSES"
    RESTART_COUNT = "DLROVER_TPU_RESTART_COUNT"
    GRAFT_PLATFORM = "JAX_PLATFORMS"


class DefaultValues:
    SERVICE_PORT = 0  # pick a free port
    RDZV_TIMEOUT_SECS = 600
    PENDING_TIMEOUT_SECS = 900
    HANG_TIMEOUT_SECS = 1800
    HEARTBEAT_INTERVAL_SECS = 15
    MONITOR_INTERVAL_SECS = 5
    MAX_RELAUNCH_COUNT = 3
    SHARD_QUEUE_TIMEOUT = 600


class NodeCheckResult:
    """Outcome of a node health (network) check round."""

    NORMAL = "normal"
    FAULT = "fault"
    STRAGGLER = "straggler"
