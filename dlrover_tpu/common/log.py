"""Logging setup for dlrover-tpu.

Parity: dlrover/python/common/log.py (default_logger with env-tunable level).
"""

import logging
import os
import sys

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(filename)s:%(lineno)d:%(funcName)s] %(message)s"
)


def _build_logger(name: str = "dlrover_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if logger.handlers:
        return logger
    level_name = os.getenv("DLROVER_TPU_LOG_LEVEL", "INFO").upper()
    level = getattr(logging, level_name, logging.INFO)
    logger.setLevel(level)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    logger.propagate = False
    return logger


default_logger = _build_logger()
