"""Singleton runtime context with tunable knobs.

Parity: dlrover/python/common/global_context.py:180-file — one process-wide
``Context`` carrying timeouts, feature switches and (in the reference)
Brain-tunable parameters. Ours adds the TPU-specific knobs (virtual device
counts for CPU-hosted tests, slice/node-unit sizes).
"""

from __future__ import annotations

import os
import threading


class Context:
    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        self.master_port: int = 0
        self.reporter: str = "log"
        self.relaunch_always: bool = False
        self.node_heartbeat_timeout_secs: int = 180
        self.seconds_to_wait_pending_pod: int = 900
        self.seconds_huge_training_threshold: int = 1800
        self.hang_detection_secs: int = 1800
        # how long a streaming-data WAIT may suppress hang handling; past
        # this, a silent producer is treated like any other stall
        self.data_starvation_timeout_secs: int = 3600
        self.rdzv_timeout_secs: int = 600
        self.network_check_timeout_secs: int = 300
        self.straggler_time_ratio: float = 2.0
        self.seconds_interval_to_optimize: int = 300
        self.train_speed_record_num: int = 50
        self.auto_tune: bool = False
        # TPU specifics
        self.hosts_per_slice: int = int(os.getenv("DLROVER_TPU_HOSTS_PER_SLICE", "1"))
        self.local_devices_per_host: int = int(
            os.getenv("DLROVER_TPU_DEVICES_PER_HOST", "0")
        )

    @classmethod
    def singleton_instance(cls) -> "Context":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance
