"""Model-family configs.

Covers the reference's benchmark families: GPT-2 (nanogpt / GPT-2 xl 1.5B
flash-ckpt benchmarks, BASELINE.md) and Llama-2 (atorch/examples/llama2).
One config dataclass switches the architectural differences (learned vs
rotary positions, LayerNorm vs RMSNorm, GELU-MLP vs SwiGLU, MHA vs GQA,
optional MoE blocks).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    num_layers: int = 12
    model_dim: int = 768
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # None => MHA
    mlp_dim: Optional[int] = None  # None => 4*model_dim (gpt) / swiglu dim
    max_seq_len: int = 1024
    # architecture switches
    rope: bool = False  # False => learned positional embeddings
    rope_theta: float = 10000.0
    rmsnorm: bool = False
    swiglu: bool = False
    tie_embeddings: bool = True
    # MoE: every `moe_every`-th block uses an expert FFN
    num_experts: int = 0
    moe_every: int = 2
    capacity_factor: float = 1.25
    # per-expert capacity re-split ([num_experts] ints, static): ()
    # keeps the uniform capacity_factor sizing; a non-empty tuple
    # (parallel/moe.py CapacityRebalancer.splits from measured load)
    # gives each expert its own cutoff — the bucket dim becomes
    # max(splits), so hot experts stop overflowing while cold ones
    # ship padding. Changing it is a recompile (static shapes).
    capacity_splits: tuple = ()
    # experts per token (1 = Switch, 2 = GShard-style top-2; parity:
    # switch_gating.py:154 covers both) and the router z-loss weight
    # (keeps gate logits small; 0 disables)
    moe_top_k: int = 1
    router_z_weight: float = 1e-3
    # sequence-parallel attention scheme when the mesh has sp > 1:
    # "ring" (P2P pipeline, any head count) or "ulysses" (two
    # all-to-alls; needs (heads/tp) % sp == 0) — parallel/{ring_
    # attention,ulysses}.py
    sp_scheme: str = "ring"
    # numerics
    dtype: str = "bfloat16"  # activation/compute dtype
    param_dtype: str = "float32"
    remat: bool = False  # checkpoint each block (HBM <-> FLOPs trade)
    # store layer params STACKED ([L, ...] leaves) and run the blocks
    # under ONE lax.scan: the traced graph is O(1) in depth instead of
    # O(L), which is what lets a 48-layer model compile WITH remat
    # (parity: the reference's activation-checkpoint optimization,
    # optimization_library.py:39-58, is only usable at depth because
    # torch re-executes python; XLA needs the scan). Homogeneous blocks
    # only (no MoE interleave — same restriction as the pipeline).
    scan_layers: bool = False
    # muP forward multipliers (models/mup.py sets these; defaults = SP)
    mup_attn_scale: Optional[float] = None  # None => 1/sqrt(head_dim)
    mup_output_mult: float = 1.0
    # int8 MXU path for the MLP projections (ops/int8_matmul.py — the
    # TPU-native analog of the reference's FP8 optimization)
    int8_mlp: bool = False

    def __post_init__(self):
        if self.scan_layers and self.num_experts:
            raise ValueError(
                "scan_layers needs homogeneous blocks; MoE interleave "
                "(num_experts > 0) makes every moe_every-th block a "
                "different pytree"
            )

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.model_dim // self.num_heads

    @property
    def ffn_dim(self) -> int:
        if self.mlp_dim:
            return self.mlp_dim
        return 4 * self.model_dim


def gpt2_small() -> TransformerConfig:
    return TransformerConfig()


def gpt2_xl() -> TransformerConfig:
    """GPT-2 xl 1.5B — the reference's flash-ckpt benchmark model
    (docs/blogs/flash_checkpoint.md:292, megatron_flash_checkpoint.md)."""
    return TransformerConfig(
        num_layers=48, model_dim=1600, num_heads=25, max_seq_len=1024
    )


def llama2_7b() -> TransformerConfig:
    """Llama-2-7B — the reference's atorch throughput benchmark model
    (atorch/examples/llama2/README.md:398)."""
    return TransformerConfig(
        vocab_size=32000,
        num_layers=32,
        model_dim=4096,
        num_heads=32,
        num_kv_heads=32,
        mlp_dim=11008,
        max_seq_len=4096,
        rope=True,
        rmsnorm=True,
        swiglu=True,
        tie_embeddings=False,
    )


def is_moe_layer(cfg: TransformerConfig, i: int) -> bool:
    """THE layer-placement rule: block ``i`` carries an expert FFN.
    Every consumer (init/forward layout, metric normalization, the
    dry-runner's all-to-all pricing, the analytic profiler) routes
    through here so the rule cannot drift between them."""
    return bool(
        cfg.num_experts and i % cfg.moe_every == cfg.moe_every - 1
    )


def num_moe_layers(cfg: TransformerConfig) -> int:
    return sum(
        1 for i in range(cfg.num_layers) if is_moe_layer(cfg, i)
    )


def tiny(**overrides) -> TransformerConfig:
    """Test config: small every-feature model."""
    cfg = TransformerConfig(
        vocab_size=256,
        num_layers=2,
        model_dim=32,
        num_heads=4,
        num_kv_heads=2,
        mlp_dim=64,
        max_seq_len=64,
        rope=True,
        rmsnorm=True,
        swiglu=True,
        tie_embeddings=False,
        dtype="float32",
    )
    return replace(cfg, **overrides)
