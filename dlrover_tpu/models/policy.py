"""Mixed-precision policy system.

Parity: atorch's AMP optimization (auto/opt_lib amp_optimization +
amp/amp.py apex/native glue, SURVEY §2.3 "AMP / misc"). The TPU story is
simpler by hardware design — bf16 has fp32's exponent range, so there is
no GradScaler/inf-check machinery to port; a policy is just which dtype
each role uses:

- ``param_dtype``  — master weights (and optimizer state);
- ``compute_dtype`` — matmul/activation dtype (MXU native bf16).

Logits, losses and normalization statistics are ALWAYS fp32 — that is
the model's numerics contract (transformer.py), not a policy knob, so
there is deliberately no "output" role here.

Policies parse from the haiku/jmp-style string form
(``"params=float32,compute=bfloat16"``) or a preset name, and apply
onto a ``TransformerConfig``.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict

from dlrover_tpu.models.config import TransformerConfig

_ALIASES = {
    "f32": "float32",
    "fp32": "float32",
    "float32": "float32",
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
    "f16": "float16",
    "fp16": "float16",
    "float16": "float16",
}


@dataclass(frozen=True)
class MixedPrecisionPolicy:
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    @staticmethod
    def parse(spec: str) -> "MixedPrecisionPolicy":
        """``"params=f32,compute=bf16"`` (any subset; jmp conventions)
        or a preset name."""
        if spec in PRESETS:
            return PRESETS[spec]
        kw: Dict[str, str] = {}
        for part in spec.split(","):
            if not part.strip():
                continue
            key, _, value = part.partition("=")
            key = key.strip().rstrip("s")  # "params" → "param"
            value = _ALIASES.get(value.strip())
            if value is None:
                raise ValueError(f"unknown dtype in policy: {part!r}")
            if key == "param":
                kw["param_dtype"] = value
            elif key == "compute":
                kw["compute_dtype"] = value
            else:
                raise ValueError(
                    f"unknown policy role: {part!r} (logits are always "
                    f"fp32; only params/compute are policy knobs)"
                )
        return MixedPrecisionPolicy(**kw)

    def apply(self, cfg: TransformerConfig) -> TransformerConfig:
        """Stamp the policy onto a model config. (The model computes
        norm/softmax statistics in fp32 regardless — that is the
        numerics contract, not a policy knob.)"""
        return replace(
            cfg, dtype=self.compute_dtype, param_dtype=self.param_dtype
        )

    def describe(self) -> str:
        return f"params={self.param_dtype},compute={self.compute_dtype}"


PRESETS = {
    # the TPU default: fp32 master weights, bf16 MXU compute
    "mixed_bf16": MixedPrecisionPolicy(),
    # everything fp32 (debugging / CPU tests)
    "full_fp32": MixedPrecisionPolicy(
        param_dtype="float32", compute_dtype="float32"
    ),
    # memory-lean: bf16 weights too (half the param HBM; fine for
    # inference and for large models whose optimizer keeps fp32 copies)
    "full_bf16": MixedPrecisionPolicy(
        param_dtype="bfloat16", compute_dtype="bfloat16"
    ),
}
